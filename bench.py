"""Benchmark: flagship-model training throughput on the local chip(s).

Four rows, run as separate child processes (the chip claim is exclusive
per process, so each phase gets a fresh claim):
  raw     — model/step/sharding stack driven directly (round-3 number)
  trainer — the SAME config through the real framework: JaxTrainer actor
            gang, session.report every step, Dataset.iter_device_batches
            feeding the step (reference parity: BASELINE.json config #1
            "GPT-2 125M single-host JaxTrainer")
  hbm     — a ~1.15B-param config sized to fill one v5e's 16G HBM with
            remat + flash (BASELINE.md 7B north star, scaled to one chip)
  rl      — PPO learner samples/sec/chip + end-to-end rollout pipeline +
            weight-broadcast latency (BASELINE.json metric #2)

Prints ONE JSON line; the trainer row is the headline metric, the others
ride along as fields:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "raw": {...},
   "hbm": {...}, "rl": {...}, "trainer_overhead_vs_raw_pct": N}

vs_baseline is measured MFU / 0.45 — the BASELINE.json north-star target
(the reference publishes no tokens/sec numbers; see BASELINE.md notes).
"""

from __future__ import annotations

import json
import os
import sys
import time


PEAK_BF16_FLOPS = {
    # per-chip dense bf16 peak
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v6 lite": 918e12,
    "cpu": 5e11,
}


def _peak_flops_kind(kind: str) -> float:
    for k, v in PEAK_BF16_FLOPS.items():
        if kind.startswith(k):
            return v
    return PEAK_BF16_FLOPS["cpu"]


def _peak_flops(device) -> float:
    return _peak_flops_kind(getattr(device, "device_kind", "cpu"))


def _on_tpu(device) -> bool:
    return device.platform == "tpu" or "TPU" in getattr(device, "device_kind", "")


def _tpu_configured() -> bool:
    """A TPU is plumbed into this box (axon tunnel or real VM) AND the env
    doesn't pin another platform. Deliberately does NOT touch jax: the
    chip claim is exclusive per process, and the trainer driver must leave
    it for the worker actor."""
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return False
    import glob

    return bool(os.environ.get("PALLAS_AXON_POOL_IPS")) or bool(
        glob.glob("/dev/accel*")
    )


# --------------------------------------------------------------------------
# shared direct step loop (raw + hbm phases)
# --------------------------------------------------------------------------


def _mesh_and_rules(n_chips: int):
    """Single chip: trivial dp mesh. Multi chip: shard params/opt-state over
    the fsdp axis (ZeRO-3) — the batch rules spec is ('dp','fsdp') so the
    batch shards there too. MeshSpec(dp=n) with fsdp rules would leave the
    fsdp axis at size 1 and silently replicate everything."""
    from ray_tpu.parallel import MeshSpec, PRESET_RULES, build_mesh

    if n_chips == 1:
        return build_mesh(MeshSpec(dp=1)), PRESET_RULES["dp"]
    return build_mesh(MeshSpec(fsdp=n_chips)), PRESET_RULES["fsdp"]


def _run_step_bench(tag, cfg, batch, seq, steps, opt):
    """Compile + warm + time `steps` chained train steps; returns the stats
    dict shared by the raw and hbm rows."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.train.step import make_sharded_init, make_train_step

    dev = jax.devices()[0]
    n_chips = len(jax.devices())
    mesh, rules = _mesh_and_rules(n_chips)
    init_fn, shardings = make_sharded_init(cfg, mesh, rules, opt)
    state = init_fn(jax.random.PRNGKey(0))
    step = make_train_step(cfg, mesh, rules, opt, shardings)

    rng = np.random.default_rng(0)
    batch_data = {
        "tokens": jnp.asarray(
            rng.integers(0, cfg.vocab_size, size=(batch, seq + 1)), jnp.int32
        ),
        "mask": jnp.ones((batch, seq + 1), jnp.int32),
    }

    t0 = time.perf_counter()
    state, metrics = step(state, batch_data)
    jax.block_until_ready(metrics["loss"])
    compile_s = time.perf_counter() - t0
    state, metrics = step(state, batch_data)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_data)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec_per_chip = batch * seq * steps / dt / n_chips
    flops_per_token = cfg.flops_per_token() + cfg.attention_flops_per_token(seq)
    mfu = tokens_per_sec_per_chip * flops_per_token / _peak_flops(dev)
    kind = getattr(dev, "device_kind", dev.platform)
    print(
        f"[bench:{tag}] dev={kind} chips={n_chips} "
        f"model={cfg.d_model}x{cfg.n_layers} batch={batch} seq={seq} "
        f"compile={compile_s:.1f}s step={dt / steps * 1000:.1f}ms "
        f"loss={float(metrics['loss']):.3f} mfu={mfu:.3f}",
        file=sys.stderr,
    )
    return {
        "value": round(tokens_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "mfu": round(mfu, 4),
        "device": kind,
        "step_ms": round(dt / steps * 1000, 2),
    }


# --------------------------------------------------------------------------
# raw mode — direct step loop (identical to the round-3 bench)
# --------------------------------------------------------------------------


def main_raw():
    import dataclasses

    import jax

    from ray_tpu.models import CONFIGS
    from ray_tpu.train.step import default_optimizer

    dev = jax.devices()[0]
    on_tpu = _on_tpu(dev)

    if on_tpu:
        # Pallas flash attention (head-major layout, fused single-block
        # backward), remat that saves EXACTLY the residuals backward reads
        # (flash_min), and unrolled layers (drops scan stack traffic):
        # measured 0.47 MFU vs 0.27 for dense+full-remat on v5e (b16 is the
        # largest batch whose saved residuals fit 16G HBM at compile time).
        cfg = dataclasses.replace(
            CONFIGS["gpt2_125m"],
            attention="flash",
            remat_policy="flash_min",
            scan_layers=False,
        )
        batch, seq, steps = 16, 1024, 30  # window matched to the trainer phase: overhead must compare equal-length timed windows
    else:  # CI / local smoke: tiny model
        cfg = dataclasses.replace(CONFIGS["tiny"], max_seq_len=256)
        batch, seq, steps = 8, 128, 5

    row = _run_step_bench(
        "raw", cfg, batch, seq, steps, default_optimizer(lr=1e-3, warmup=10)
    )
    row["metric"] = (
        "gpt2_125m_train_tokens_per_sec_per_chip"
        if on_tpu
        else "tiny_train_tokens_per_sec_per_chip_cpu"
    )
    row["vs_baseline"] = round(row["mfu"] / 0.45, 4)
    print(json.dumps(row))


# --------------------------------------------------------------------------
# hbm mode — HBM-limit single-chip config (~1.15B params, fp32 adam v)
# --------------------------------------------------------------------------


def main_hbm():
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import CONFIGS
    from ray_tpu.train.step import default_optimizer

    dev = jax.devices()[0]
    on_tpu = _on_tpu(dev)
    n_chips = len(jax.devices())

    if on_tpu:
        cfg = dataclasses.replace(
            CONFIGS["gpt_1b"],
            attention="flash",
            remat_policy="flash_qkv",
            scan_layers=False,
            loss_chunk=128,
        )
        # 6/chip is the largest per-chip batch that fits 16G (15.9G static
        # allocation at 8); multi-chip scales it so dim 0 stays divisible
        batch, seq, steps = 6 * n_chips, 1024, 8
    else:
        cfg = dataclasses.replace(CONFIGS["tiny"], max_seq_len=256)
        batch, seq, steps = 8, 128, 3

    # bf16 momentum: the ~1.15B fp32 params + fp32 adam v alone are ~9G;
    # halving mu is what leaves room for grads + activations on 16G
    opt = default_optimizer(lr=1e-4, warmup=10, mu_dtype=jnp.bfloat16)
    row = _run_step_bench("hbm", cfg, batch, seq, steps, opt)
    row["metric"] = (
        "gpt_1b_hbm_limit_tokens_per_sec_per_chip" if on_tpu else "tiny_hbm_smoke_cpu"
    )
    row["vs_baseline"] = round(row["mfu"] / 0.40, 4)
    row["params_b"] = round(cfg.num_params() / 1e9, 3)
    print(json.dumps(row))


# --------------------------------------------------------------------------
# decode mode — KV-cache serving fast path (tokens/s/chip at the decode step)
# --------------------------------------------------------------------------


def _decode_realtext_spec(k: int = 4, new_tokens: int = 48) -> dict:
    """Real-text drafter measurement riding the decode row: load a hub
    model (RAY_TPU_BENCH_MODEL_PATH, else the checked-in fixture), run
    the n-gram drafter over tokenizer-encoded English prompts, and record
    the measured accept rate + the model's identity. Measured, never
    asserted — drafter yield on real text is a model/workload property,
    and the row exists precisely to OBSERVE it (PR 7's open question).
    Absent model files degrade to the synthetic identity, never a fault."""
    out = {"model_id": None, "params_source": "synthetic",
           "spec_accept_rate_realtext": None}
    path = os.environ.get("RAY_TPU_BENCH_MODEL_PATH") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tests", "fixtures", "hub_gpt2_tiny",
    )
    try:
        from ray_tpu.models.hub import measure_realtext_spec

        m = measure_realtext_spec(path, k=k, new_tokens=new_tokens)
        out.update(
            model_id=m["model_id"],
            params_source=m["params_source"],
            spec_accept_rate_realtext=m["spec_accept_rate"],
        )
    except Exception as e:
        print(f"[bench:decode] realtext spec measurement unavailable: {e!r}",
              file=sys.stderr)
    return out


def _decode_latency_distribution(engine, prompts, new_tokens: int) -> dict:
    """TTFT/inter-token latency distribution for the decode row, pulled
    from the telemetry plane's histograms (serve/telemetry.py): the
    prompts run through a ContinuousBatcher (the production consumer of
    the engine) and the row reads p50/p99 off serve_ttft_s /
    serve_inter_token_latency_s — so TPU certification rounds bank real
    latency distributions next to tokens/s, not just means. Callers must
    pass prompts the engine has NOT seen: a prefix-cache hit would turn
    the banked TTFT into cache-hit admission latency, an order of
    magnitude under what a cold client waits. None fields when telemetry
    is off."""
    out = {"ttft_p50_ms": None, "ttft_p99_ms": None,
           "inter_token_p99_ms": None}
    try:
        from ray_tpu.serve import telemetry
        from ray_tpu.serve.batching import ContinuousBatcher
        from ray_tpu.util.metrics import local_histogram_quantiles

        if telemetry.get_telemetry() is None:
            return out
        batcher = ContinuousBatcher(
            engine, max_batch_size=len(prompts), batch_wait_timeout_s=0.05
        )
        try:
            streams = [
                batcher.submit(tokens=list(p), max_new_tokens=new_tokens)
                for p in prompts
            ]
            for s in streams:
                for _ in s:
                    pass
        finally:
            batcher.close()
        ttft = local_histogram_quantiles("serve_ttft_s", (0.5, 0.99))
        inter = local_histogram_quantiles(
            "serve_inter_token_latency_s", (0.99,))
        if ttft and ttft[0] is not None:
            out["ttft_p50_ms"] = round(ttft[0] * 1000, 2)
            out["ttft_p99_ms"] = round(ttft[1] * 1000, 2)
        if inter and inter[0] is not None:
            out["inter_token_p99_ms"] = round(inter[0] * 1000, 2)
    except Exception as e:
        print(f"[bench:decode] latency distribution unavailable: {e!r}",
              file=sys.stderr)
    return out


def main_decode():
    """Batched KV-cache decode throughput: the serving-side counterpart of
    the training rows. Prefills `batch` slots, then times `new_tokens`
    continuous decode steps through the PAGED engine (the same loop the
    serve replica drives — block-table gather attention, so the row also
    tracks the paging overhead), reporting tokens/s/chip plus block-pool
    utilization and preemptions. The batched-vs-serial and prefix-hit
    gates live in microbench.py; this row is the absolute rate. The row
    also carries the real-text drafter measurement (model-hub weights +
    tokenizer-encoded English prompts) so decode trajectories name which
    weights they speak for."""
    import dataclasses

    import jax
    import numpy as np

    from ray_tpu.models import CONFIGS
    from ray_tpu.models.kv_paging import PagedDecodeEngine

    dev = jax.devices()[0]
    on_tpu = _on_tpu(dev)
    n_chips = len(jax.devices())

    if on_tpu:
        cfg = CONFIGS["gpt2_125m"]
        batch, prompt_len, new_tokens = 8, 128, 128
    else:
        cfg = dataclasses.replace(CONFIGS["tiny"], max_seq_len=256)
        batch, prompt_len, new_tokens = 4, 16, 32

    # telemetry=False: the timed loop's tokens/s must stay comparable to
    # pre-telemetry bench rounds (engine-pure, no per-step observes); the
    # latency-distribution pass below gets its TTFT/inter-token numbers
    # from the BATCHER-side telemetry, which the engine doesn't carry
    engine = PagedDecodeEngine(cfg, max_batch_size=batch, seed=0,
                               telemetry=False)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, size=(batch, prompt_len))
    slots = list(range(batch))

    t0 = time.perf_counter()
    for s in slots:
        engine.admit(s, {"tokens": prompts[s], "max_new_tokens": 10**9})
    prefill_s = time.perf_counter() - t0
    engine.step(slots)  # decode compile + warm
    # spec verify buckets compile OUT of the timed loop: a drafter's
    # first mid-window proposal would otherwise bill a trace+compile
    # to dt and sink the spec-on row
    engine.warmup_verify()
    gen0 = engine.tokens_generated
    t0 = time.perf_counter()
    for _ in range(new_tokens):
        engine.step(slots)
    dt = time.perf_counter() - t0
    # count tokens EMITTED, not steps: with speculation on a step emits
    # 1..k+1 per slot, and a steps-based rate would report a spec-on run
    # as slower while the spec stats next to it say otherwise
    emitted = engine.tokens_generated - gen0

    tokens_per_sec_per_chip = emitted / dt / n_chips
    estats = engine.stats()
    # latency distribution AFTER the timed loop (separate batcher-driven
    # pass over freed slots; the decode rate above stays engine-pure).
    # FRESH prompts: the decoded ones now sit in the prefix cache, and a
    # hit would bank cache-hit TTFT instead of a cold client's wait
    for s in slots:
        engine.release(s)
    latency = _decode_latency_distribution(
        engine, rng.integers(0, cfg.vocab_size, size=(batch, prompt_len)),
        new_tokens,
    )
    kind = getattr(dev, "device_kind", dev.platform)
    print(
        f"[bench:decode] dev={kind} chips={n_chips} batch={batch} "
        f"prompt={prompt_len} new={new_tokens} "
        f"attn={estats['attention_impl']} kv_dtype={estats['kv_cache_dtype']} "
        f"prefill={prefill_s * 1000:.0f}ms step={dt / new_tokens * 1000:.2f}ms "
        f"tok/s/chip={tokens_per_sec_per_chip:.1f} "
        f"kv_util={estats['kv_block_utilization']}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "gpt2_125m_decode_tokens_per_sec_per_chip"
                if on_tpu
                else "tiny_decode_tokens_per_sec_per_chip_cpu",
                "value": round(tokens_per_sec_per_chip, 1),
                "unit": "tokens/s/chip",
                "device": kind,
                "batch": batch,
                "prompt_len": prompt_len,
                "new_tokens": new_tokens,
                "emitted_tokens": int(emitted),
                "prefill_ms": round(prefill_s * 1000, 1),
                "decode_step_ms": round(dt / new_tokens * 1000, 3),
                # which decode fast path produced this number — BENCH_r*
                # trajectories stay comparable across the fused/int8 change
                # ("gather"+"fp" rows are the pre-fused lineage)
                "attention_variant": estats["attention_impl"],
                "kv_dtype": estats["kv_cache_dtype"],
                # latency distribution from the telemetry histograms
                # (serve_ttft_s / serve_inter_token_latency_s): what a
                # client actually waits, not the step-time mean
                **latency,
                # ISSUE 13: the attention the VERIFY step ran (one fused
                # multi-query impl serves decode/verify/prefill, so it
                # equals attention_variant — recorded separately so TPU
                # certification rounds can name the fused-verify config
                # even if the impls ever diverge again) + the chunked-
                # prefill granularity (0 = whole-prompt admission)
                "verify_attention_variant": estats["attention_impl"],
                "prefill_chunk_tokens": estats["prefill_chunk_tokens"],
                # paged-KV observability: live fraction of the block pool
                # at the end of the timed run + preemptions (nonzero means
                # the pool was undersized for this batch/length mix)
                "kv_block_utilization": estats["kv_block_utilization"],
                "preemptions": estats["preemptions"],
                # speculative decoding (serve_speculative_k; 0 = off):
                # rows stay comparable across spec-on/spec-off rounds —
                # tokens/s/chip plus which k and what the drafter earned
                "spec_k": estats["spec_k"],
                "spec_accept_rate": estats["spec_accept_rate"],
                "spec_tokens_per_step": estats["spec_tokens_per_step"],
                # which weights/tokenizer this round can speak for + what
                # the n-gram drafter measured on real-text prompts (hub
                # model; "synthetic" when no checkpoint was loadable)
                **_decode_realtext_spec(),
            }
        )
    )


# --------------------------------------------------------------------------
# trainer mode — the framework in the measured loop
# --------------------------------------------------------------------------


def _trainer_train_fn(config):
    """Runs INSIDE the TrainWorker actor (full-site interpreter: the PJRT
    plugin registers there, and this process — not the driver — claims the
    chip). Pulls device batches from the Dataset shard, reports every step
    through session.report, and reports the measured throughput at the end."""
    import dataclasses
    import time as _time

    import jax
    import numpy as np

    from ray_tpu.models import CONFIGS
    from ray_tpu.parallel import MeshSpec, PRESET_RULES, build_mesh
    from ray_tpu.train import session
    from ray_tpu.train.step import default_optimizer, make_sharded_init, make_train_step

    dev = jax.devices()[0]
    cfg = CONFIGS[config["model"]]
    if config["tpu"]:
        cfg = dataclasses.replace(
            cfg, attention="flash", remat_policy="flash_min", scan_layers=False
        )
    batch, seq = config["batch"], config["seq"]
    steps, warmup = config["steps"], config["warmup"]

    mesh = build_mesh(MeshSpec(dp=len(jax.devices())))
    rules = PRESET_RULES["dp"]
    opt = default_optimizer(lr=1e-3, warmup=10)
    init_fn, shardings = make_sharded_init(cfg, mesh, rules, opt)
    state = init_fn(jax.random.PRNGKey(0))
    step = make_train_step(cfg, mesh, rules, opt, shardings)

    ds = session.get_dataset_shard("train")
    it = ds.iter_device_batches(batch_size=batch, mesh=mesh, rules=rules, prefetch=2)

    t_start = _time.perf_counter()
    n_timed = 0
    t0 = None
    compile_s = None
    for i, b in enumerate(it):
        if i >= warmup + steps:
            break
        state, metrics = step(state, b)
        if i < warmup:
            # compile + cache-warm steps: sync so the timed window below
            # contains ONLY steady-state step+feed work
            jax.block_until_ready(metrics["loss"])
            if i == 0:
                compile_s = _time.perf_counter() - t_start
            if i == warmup - 1:
                t0 = _time.perf_counter()
            continue
        n_timed += 1
        # per-step report through the real session plumbing — but nothing
        # here touches device values (a float(loss) would sync the pipe)
        session.report({"step": i})
    jax.block_until_ready(metrics["loss"])
    dt = _time.perf_counter() - t0
    it.close()  # settle the feed pipeline so its stats finalize

    tokens_per_sec = batch * seq * n_timed / dt
    session.report(
        {
            "final": True,
            "tokens_per_sec": tokens_per_sec,
            "steps_timed": n_timed,
            "step_ms": dt / max(1, n_timed) * 1000.0,
            "compile_s": compile_s,
            "loss": float(metrics["loss"]),
            "device_kind": getattr(dev, "device_kind", dev.platform),
            "n_devices": len(jax.devices()),
            # input-pipeline evidence (VERDICT r4 #2): per-operator stats
            # of the Dataset feed that just sustained the chip
            "dataset_stats": ds.stats_dict(),
        }
    )
    return "done"


def main_trainer():
    """Driver: builds the token Dataset, runs JaxTrainer over one TPU worker
    actor, and computes MFU from the worker's reported throughput. The
    driver itself never initializes a jax backend."""
    import numpy as np

    import ray_tpu
    from ray_tpu import data as rdata
    from ray_tpu.models import CONFIGS
    from ray_tpu.train import JaxTrainer, ScalingConfig

    on_tpu = _tpu_configured()
    if on_tpu:
        model, batch, seq, steps, warmup = "gpt2_125m", 16, 1024, 30, 3
    else:
        model, batch, seq, steps, warmup = "tiny", 8, 128, 6, 2
    vocab = CONFIGS[model].vocab_size

    ray_tpu.init(num_cpus=4, num_tpus=1 if on_tpu else None)

    n_rows = (steps + warmup + 6) * batch

    def gen_tokens(blk):
        n = len(blk["id"])
        rng = np.random.default_rng(int(blk["id"][0]) + 1)
        return {
            "tokens": rng.integers(0, vocab, size=(n, seq + 1)).astype(np.int32),
            "mask": np.ones((n, seq + 1), np.int32),
        }

    ds = rdata.range(n_rows, override_num_blocks=8).map_batches(
        gen_tokens, batch_size=batch
    )

    trainer = JaxTrainer(
        _trainer_train_fn,
        train_loop_config={
            "model": model, "tpu": on_tpu, "batch": batch, "seq": seq,
            "steps": steps, "warmup": warmup,
        },
        scaling_config=ScalingConfig(
            num_workers=1,
            resources_per_worker={"CPU": 1, "TPU": 1} if on_tpu else {"CPU": 1},
        ),
        datasets={"train": ds},
    )
    result = trainer.fit()
    ray_tpu.shutdown()
    if result.error is not None:
        raise SystemExit(f"trainer bench failed: {result.error!r}")

    final = next(
        (m for m in reversed(result.metrics_history) if m.get("final")), None
    )
    if final is None:
        raise SystemExit("trainer bench: no final report")
    per_step_reports = sum(1 for m in result.metrics_history if "step" in m)

    cfg = CONFIGS[model]
    flops_per_token = cfg.flops_per_token() + cfg.attention_flops_per_token(seq)
    tokens_per_sec_per_chip = final["tokens_per_sec"] / final["n_devices"]
    mfu = tokens_per_sec_per_chip * flops_per_token / _peak_flops_kind(
        final["device_kind"]
    )

    print(
        f"[bench:trainer] dev={final['device_kind']} model={model} "
        f"batch={batch} seq={seq} compile={final['compile_s']:.1f}s "
        f"step={final['step_ms']:.1f}ms loss={final['loss']:.3f} "
        f"mfu={mfu:.3f} reports={per_step_reports}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "gpt2_125m_jaxtrainer_tokens_per_sec_per_chip"
                if on_tpu
                else "tiny_jaxtrainer_tokens_per_sec_per_chip_cpu",
                "value": round(tokens_per_sec_per_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(mfu / 0.45, 4),
                "mfu": round(mfu, 4),
                "device": final["device_kind"],
                "step_ms": round(final["step_ms"], 2),
                "session_reports": per_step_reports,
                "dataset_stats": final.get("dataset_stats"),
            }
        )
    )


# --------------------------------------------------------------------------
# rl mode — the second north star: PPO learner samples/sec/chip
# --------------------------------------------------------------------------


def main_rl():
    """Three RL numbers (BASELINE.json metric #2; reference intent:
    rllib/core/learner/learner_group.py:61):
      - learner-only: PPOLearner.update on the chip over a large synthetic
        batch — samples/sec/chip through the jitted epochs-x-minibatches
        program, H2D included (it is part of real learner feed cost)
      - pipeline: PPO end-to-end on CartPole — CPU rollout actors feeding
        the learner through Algorithm.training_step
      - weight-broadcast latency learner -> rollout workers
    The learner runs IN THIS child process (it claims the chip); rollout
    actors are -S CPU workers."""
    import jax
    import numpy as np

    from ray_tpu.rl.learner import PPOLearner
    from ray_tpu.rl.sample_batch import (
        ACTIONS, ADVANTAGES, LOGP, OBS, TARGETS, VALUES, SampleBatch,
    )

    dev = jax.devices()[0]
    on_tpu = _on_tpu(dev)
    kind = getattr(dev, "device_kind", dev.platform)

    obs_dim, n_act = 64, 8
    if on_tpu:
        B, mb, iters = 65536, 8192, 5
    else:
        B, mb, iters = 8192, 1024, 3
    learner = PPOLearner(
        obs_dim, n_act, hidden=(256, 256), minibatch_size=mb, num_epochs=4
    )
    rng = np.random.default_rng(0)
    batch = SampleBatch(
        {
            OBS: rng.normal(size=(B, obs_dim)).astype(np.float32),
            ACTIONS: rng.integers(0, n_act, B).astype(np.int64),
            LOGP: np.full(B, -np.log(n_act), np.float32),
            ADVANTAGES: rng.normal(size=B).astype(np.float32),
            TARGETS: rng.normal(size=B).astype(np.float32),
            VALUES: rng.normal(size=B).astype(np.float32),
        }
    )
    learner.update(batch)  # compile
    # update() trains on the mesh-aligned truncation, not B — credit only
    # what was actually processed (guards a future B/mb retune)
    used = learner._built_used
    assert used == B, (used, B)
    t0 = time.perf_counter()
    for _ in range(iters):
        learner.update(batch)
    dt = time.perf_counter() - t0
    feed_sps = used * iters / dt  # includes fresh H2D per update

    # device-resident batch: the learner PROGRAM's throughput (epochs x
    # minibatches on-chip). On this rig H2D rides a debug tunnel ~200x
    # slower than a TPU-VM's PCIe, so the feed-included number above
    # under-reports the chip by orders of magnitude; real deployments see
    # roughly this one.
    import jax.numpy as jnp

    cols = {
        k: jnp.asarray(batch[k][:used])
        for k in (OBS, ACTIONS, LOGP, ADVANTAGES, TARGETS, VALUES)
    }
    from ray_tpu.rl.sample_batch import LOSS_MASK

    cols[LOSS_MASK] = jnp.ones(used, jnp.float32)
    state, m = learner._update_fn(learner.state, cols)
    jax.block_until_ready(m["total_loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = learner._update_fn(state, cols)
    jax.block_until_ready(m["total_loss"])
    dt = time.perf_counter() - t0
    learner.state = state
    learner_sps = used * iters / dt

    # -- end-to-end PPO pipeline on CartPole + weight broadcast --
    import ray_tpu
    from ray_tpu.rl.ppo import PPOConfig

    ray_tpu.init(num_cpus=10)  # logical slots: the scaling sweep peaks at 8 actors + learner
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=250)
        .training(train_batch_size=2000, minibatch_size=256, num_epochs=4)
        .build()
    )
    algo.train()  # warm: rollout-actor spawn + learner compile at this size
    t0 = time.perf_counter()
    n = 0
    for _ in range(2):
        res = algo.train()
        n += res["num_env_steps_sampled_this_iter"]
    pipeline_sps = n / (time.perf_counter() - t0)

    w = algo.learner_group.get_weights()
    t0 = time.perf_counter()
    algo.workers.set_weights(w)
    broadcast_ms = (time.perf_counter() - t0) * 1000.0
    algo.stop()

    # -- rollout-actor scaling curves (VERDICT r4 #9): the SAME pipeline at
    # 1/2/4/8 rollout actors, two env regimes:
    #   cpu_bound     — CartPole as-is: rollouts saturate host cores, so on
    #                   an N-core host the curve tops out at ~N (on this
    #                   1-core rig it INVERTS from scheduler contention —
    #                   recorded as-is, host_cpus rides along)
    #   latency_bound — CartPole with 1ms step latency (simulator/IO-wait
    #                   shaped, the regime distributed rollouts exist for):
    #                   actors overlap their waits, so the curve shows the
    #                   framework's actual fan-out scaling even on 1 core
    def _slow_cartpole():
        import gymnasium

        class _SlowStep(gymnasium.Wrapper):
            def step(self, action):
                time.sleep(0.001)
                return self.env.step(action)

        return _SlowStep(gymnasium.make("CartPole-v1"))

    def _curve(env_spec, train_batch, frag):
        pts = []
        for n_workers in (1, 2, 4, 8):
            a = (
                PPOConfig()
                .environment(env_spec)
                .rollouts(num_rollout_workers=n_workers,
                          rollout_fragment_length=frag)
                .training(train_batch_size=train_batch, minibatch_size=256,
                          num_epochs=4)
                .build()
            )
            a.train()  # warm (actor spawn; learner jit is size-cached)
            t0 = time.perf_counter()
            n = 0
            for _ in range(2):
                res = a.train()
                n += res["num_env_steps_sampled_this_iter"]
            pts.append(
                {"rollout_actors": n_workers,
                 "samples_per_sec": round(n / (time.perf_counter() - t0), 1)}
            )
            a.stop()
        return pts

    scaling = {
        "cpu_bound": _curve("CartPole-v1", 2000, 250),
        "latency_bound": _curve(_slow_cartpole, 2000, 250),
    }
    ray_tpu.shutdown()

    print(
        f"[bench:rl] dev={kind} learner={learner_sps:,.0f} samples/s "
        f"(feed-included {feed_sps:,.0f}; B={B} epochs=4) "
        f"pipeline={pipeline_sps:,.0f} samples/s broadcast={broadcast_ms:.1f}ms",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "ppo_learner_samples_per_sec_per_chip"
                if on_tpu
                else "ppo_learner_samples_per_sec_cpu",
                "value": round(learner_sps, 1),
                "unit": "samples/s/chip",
                "device": kind,
                "feed_included_samples_per_sec": round(feed_sps, 1),
                "pipeline_samples_per_sec": round(pipeline_sps, 1),
                "weight_broadcast_ms": round(broadcast_ms, 2),
                "update_ms": round(dt / iters * 1000, 2),
                "batch_size": B,
                "rollout_scaling": scaling,
                "host_cpus": os.cpu_count(),
            }
        )
    )


# --------------------------------------------------------------------------
# supervisor
# --------------------------------------------------------------------------


def _install_stack_dumper():
    """Child-side half of the hang watchdog: register a faulthandler that
    dumps EVERY thread's stack to $RAY_TPU_BENCH_STACKDUMP on SIGUSR2. The
    supervisor fires the signal right before group-killing a hung phase, so
    the dump lands in the phase row and a TPU hang (VERDICT weak #1a) shows
    WHERE the child was wedged — inside a collective, the PJRT plugin's
    import, the feed pipeline — instead of evaporating with the process."""
    path = os.environ.get("RAY_TPU_BENCH_STACKDUMP")
    if not path:
        return
    import faulthandler
    import signal

    try:
        f = open(path, "w")
        faulthandler.register(signal.SIGUSR2, file=f, all_threads=True)
    except Exception as e:  # never let observability break the phase
        print(f"[bench] stack dumper not installed: {e}", file=sys.stderr)


def _collect_stack_dump(pid, dump_path, wait_s=3.0):
    """Supervisor-side half: SIGUSR2 the hung child and wait for its
    faulthandler to finish writing dump_path (the caller reads the file).
    A child that never installed the handler dies to SIGUSR2's default
    disposition — detected via signal-0 probe so the wait ends early
    instead of burning the full wait_s (the group SIGKILL was coming
    anyway)."""
    import signal

    try:
        os.kill(pid, signal.SIGUSR2)
    except OSError:
        return
    deadline = time.monotonic() + wait_s
    last = -1
    while time.monotonic() < deadline:
        try:
            size = os.path.getsize(dump_path)
        except OSError:
            size = 0
        if size > 0 and size == last:
            return  # dump finished growing
        last = size
        if size == 0:
            try:
                os.kill(pid, 0)  # still alive?
            except OSError:
                return  # died without a handler: no dump is coming
        time.sleep(0.15)


def _run_child(cmd, child_env, timeout, stack_dump_path=None):
    """Returns (rc|None, stdout, stderr); rc None = hung/timed out.

    Own session + group-kill on timeout: a wedged child may have forked
    helpers (tunnel processes) that inherit the pipes — killing only the
    child would leave communicate() blocked short of EOF forever.

    stack_dump_path: when set, a timed-out child gets SIGUSR2 first so its
    faulthandler (see _install_stack_dumper) can write thread stacks there
    before the SIGKILL lands; the caller reads the file afterwards."""
    import signal
    import subprocess

    p = subprocess.Popen(
        cmd, env=child_env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, start_new_session=True,
    )
    try:
        out, err = p.communicate(timeout=timeout)
        return p.returncode, out or "", err or ""
    except subprocess.TimeoutExpired:
        if stack_dump_path:
            _collect_stack_dump(p.pid, stack_dump_path)
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except OSError:
            p.kill()
        try:
            out, err = p.communicate(timeout=10)
        except Exception:
            out, err = "", ""
        return None, out or "", err or ""
    except BaseException:
        # SIGTERM/budget abort mid-communicate: the child must not outlive
        # the supervisor (it would hold the chip claim hostage)
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except OSError:
            p.kill()
        raise


_MIN_PHASE_WINDOW_S = 5.0  # a smaller budget slice can't fit any phase


def _budget_left(deadline):
    """Seconds left in the global budget (None = unlimited)."""
    return None if deadline is None else deadline - time.monotonic()


def _emit_row(results_path: str, mode: str, row: dict) -> None:
    """Append one completed phase row to the results file IMMEDIATELY
    (VERDICT weak #1b: a later hung phase must degrade to partial results,
    never lose finished work). __graft_entry__._emit_result_row mirrors
    this jsonl contract for the MULTICHIP two_slice row — keep in lockstep."""
    if not results_path:
        return
    try:
        with open(results_path, "a") as f:
            f.write(json.dumps({"phase": mode, "row": row}) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:
        print(f"[bench] could not emit {mode} row: {e}", file=sys.stderr)


def _phase(mode: str, timeout: float, attempts: int, cpu_fallback: bool,
           deadline=None, results_path: str = ""):
    """Run one bench phase in child processes until a JSON line lands.
    Returns the parsed row (dict) or None. When the TPU tunnel is down the
    site hook's plugin registration can block `import jax` forever — the
    child-with-timeout contains that hang, and the tunnel can recover
    between attempts. Every child timeout is clamped to the global budget
    (`deadline`, monotonic); a completed row is appended to `results_path`
    the moment it lands."""
    # test hook: RAY_TPU_BENCH_CHILD_SCRIPT swaps the child for a fake
    # (e.g. one that sleeps forever) without patching this module
    me = os.environ.get("RAY_TPU_BENCH_CHILD_SCRIPT") or os.path.abspath(__file__)
    backoffs = [15.0, 30.0]
    env = dict(os.environ, RAY_TPU_BENCH_CHILD=mode)
    for i in range(attempts):
        left = _budget_left(deadline)
        if left is not None and left < _MIN_PHASE_WINDOW_S:
            print(f"[bench] {mode}: global budget exhausted "
                  f"({left:.0f}s left); skipping", file=sys.stderr)
            return None
        child_timeout = timeout if left is None else min(timeout, left)
        # hang watchdog: the child registers a SIGUSR2 faulthandler on this
        # path; a timed-out child dumps its thread stacks here before dying
        import tempfile

        fd, dump_path = tempfile.mkstemp(prefix=f"bench_{mode}_stacks_")
        os.close(fd)
        env["RAY_TPU_BENCH_STACKDUMP"] = dump_path
        t0 = time.perf_counter()
        try:
            rc, out, err = _run_child(
                [sys.executable, me], env, child_timeout,
                stack_dump_path=dump_path,
            )
            dt = time.perf_counter() - t0
            stacks = ""
            if rc is None:
                try:
                    with open(dump_path) as f:
                        stacks = f.read()
                except OSError:
                    pass
        finally:
            try:
                os.unlink(dump_path)
            except OSError:
                pass
        row = _last_json(out)
        if rc == 0 and row is not None:
            sys.stderr.write(err)
            _emit_row(results_path, mode, row)
            return row
        why = "hung (timeout)" if rc is None else f"rc={rc}"
        tail = "\n".join(err.strip().splitlines()[-6:])
        print(f"[bench] {mode} attempt {i + 1}/{attempts} failed ({why}, "
              f"{dt:.0f}s){': ' + tail if tail else ''}", file=sys.stderr)
        if stacks:
            # the whole point of the watchdog: the hang site rides the
            # incremental results file as a phase row, so a wedged trainer
            # phase can finally be root-caused from the round artifacts
            print(f"[bench] {mode} hung-child thread stacks:\n{stacks}",
                  file=sys.stderr)
            _emit_row(results_path, mode, {
                "hung": True,
                "attempt": i + 1,
                "timeout_s": child_timeout,
                "stack_dump": stacks,
            })
        if i < attempts - 1:
            pause = backoffs[min(i, len(backoffs) - 1)]
            left = _budget_left(deadline)
            if left is not None:
                pause = max(0.0, min(pause, left - _MIN_PHASE_WINDOW_S))
            time.sleep(pause)
    left = _budget_left(deadline)
    if not cpu_fallback or (left is not None and left < _MIN_PHASE_WINDOW_S):
        return None
    print(f"[bench] {mode}: TPU attempts exhausted; CPU fallback", file=sys.stderr)
    from ray_tpu._private.spawn import child_pythonpath

    env.pop("RAY_TPU_BENCH_STACKDUMP", None)  # per-attempt path was deleted
    env["JAX_PLATFORMS"] = "cpu"  # -S skips the blocking site hook
    env["PYTHONPATH"] = child_pythonpath(inherited=env.get("PYTHONPATH"))
    rc, out, err = _run_child(
        [sys.executable, "-S", me], env, 600 if left is None else min(600, left)
    )
    sys.stderr.write(err)
    row = _last_json(out)
    if row is not None:
        _emit_row(results_path, mode, row)
    return row


def _last_json(out: str):
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


class _BenchAborted(Exception):
    """SIGTERM landed: stop launching work, emit best-so-far."""


def _supervise() -> int:
    # INTERLEAVED raw/trainer reps (VERDICT r4 #5): alternating the two
    # phases puts both under the same slow host drift, so the overhead
    # claim is a mean ± spread over paired runs instead of one pair of
    # single-run numbers minutes apart (which once produced a nonsense
    # negative overhead).
    #
    # Global wall-clock budget (VERDICT weak #1b): the worst-case phase
    # schedule exceeds any sane driver kill-timeout by construction, so the
    # supervisor clamps itself — phases that don't fit the remaining budget
    # are SKIPPED and the best-so-far JSON still prints. SIGTERM gets the
    # same degradation instead of losing finished rows.
    import signal

    reps = max(1, int(os.environ.get("RAY_TPU_BENCH_OVERHEAD_REPS", "2")))
    raw_timeout = float(os.environ.get("RAY_TPU_BENCH_TPU_TIMEOUT_S", "300"))
    budget_s = float(os.environ.get("RAY_TPU_BENCH_TOTAL_BUDGET_S", "3300"))
    deadline = time.monotonic() + budget_s if budget_s > 0 else None
    results_path = os.environ.get("RAY_TPU_BENCH_RESULTS", "")

    def _on_term(signum, frame):
        raise _BenchAborted()

    old_term = signal.signal(signal.SIGTERM, _on_term)
    raws, trainers, rep_pairs = [], [], []
    hbm = rl = decode = None
    try:
        for _ in range(reps):
            r = _phase("raw", raw_timeout, 3, cpu_fallback=True,
                       deadline=deadline, results_path=results_path)
            if r is not None:
                raws.append(r)
            t = _phase("trainer", 600, 2, cpu_fallback=True,
                       deadline=deadline, results_path=results_path)
            if t is not None:
                trainers.append(t)
            if r is not None and t is not None:
                # overhead pairs only from reps where BOTH phases ran — a
                # failed rep must not pair measurements minutes apart
                rep_pairs.append((r, t))
        # decode rides early among the satellite rows: it is the cheapest
        # TPU phase, so a later trainer/hbm hang still leaves the serving
        # row in the incremental results file
        decode = _phase("decode", 600, 2, cpu_fallback=True,
                        deadline=deadline, results_path=results_path)
        hbm = _phase("hbm", 600, 2, cpu_fallback=False,
                     deadline=deadline, results_path=results_path)
        rl = _phase("rl", 600, 2, cpu_fallback=False,
                    deadline=deadline, results_path=results_path)
    except _BenchAborted:
        print("[bench] SIGTERM: emitting best-so-far results", file=sys.stderr)
    finally:
        signal.signal(signal.SIGTERM, old_term)
    raw = raws[-1] if raws else None
    trainer = trainers[-1] if trainers else None

    if trainer is not None:
        primary = dict(trainer)
        if raw is not None:
            primary["raw"] = raw
            # only comparable when both phases ran on the same device — a
            # CPU fallback on one side would publish a nonsense "overhead"
            pairs = [
                (r, t) for r, t in rep_pairs
                if r.get("mfu") and r.get("device") == t.get("device")
            ]
            if pairs:
                ovh = [
                    (r["mfu"] - t.get("mfu", 0.0)) / r["mfu"] * 100
                    for r, t in pairs
                ]
                mean = sum(ovh) / len(ovh)
                spread = (max(ovh) - min(ovh)) / 2 if len(ovh) > 1 else None
                primary["trainer_overhead_vs_raw_pct"] = round(mean, 2)
                if spread is not None:
                    primary["trainer_overhead_spread_pct"] = round(spread, 2)
                primary["overhead_pairs"] = [
                    {"raw_mfu": r["mfu"], "trainer_mfu": t.get("mfu")}
                    for r, t in pairs
                ]
    elif raw is not None:
        primary = dict(raw)
        primary["trainer_row_missing"] = True
    else:
        print("[bench] no phase produced a result", file=sys.stderr)
        return 1
    if hbm is not None:
        primary["hbm"] = hbm
    if rl is not None:
        primary["rl"] = rl
    if decode is not None:
        primary["decode"] = decode
    print(json.dumps(primary))
    return 0


if __name__ == "__main__":
    mode = os.environ.get("RAY_TPU_BENCH_CHILD")
    if mode:
        _install_stack_dumper()
    if mode == "raw" or mode == "1":  # "1" = old envvar spelling
        main_raw()
    elif mode == "trainer":
        main_trainer()
    elif mode == "hbm":
        main_hbm()
    elif mode == "rl":
        main_rl()
    elif mode == "decode":
        main_decode()
    else:
        sys.exit(_supervise())
