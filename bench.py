"""Benchmark: flagship-model training throughput on the local chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured MFU / 0.45 — the BASELINE.json north-star target
(the reference publishes no tokens/sec numbers; see BASELINE.md notes).
"""

from __future__ import annotations

import json
import sys
import time


PEAK_BF16_FLOPS = {
    # per-chip dense bf16 peak
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v6 lite": 918e12,
    "cpu": 5e11,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for k, v in PEAK_BF16_FLOPS.items():
        if kind.startswith(k):
            return v
    return PEAK_BF16_FLOPS["cpu"]


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import CONFIGS
    from ray_tpu.parallel import MeshSpec, PRESET_RULES, build_mesh
    from ray_tpu.train.step import default_optimizer, make_sharded_init, make_train_step

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu" or "TPU" in getattr(dev, "device_kind", "")
    n_chips = len(jax.devices())

    import dataclasses

    if on_tpu:
        # Pallas flash attention (head-major layout, fused single-block
        # backward), remat that saves EXACTLY the residuals backward reads
        # (flash_min), and unrolled layers (drops scan stack traffic):
        # measured 0.47 MFU vs 0.27 for dense+full-remat on v5e (b16 is the
        # largest batch whose saved residuals fit 16G HBM at compile time).
        cfg = dataclasses.replace(
            CONFIGS["gpt2_125m"],
            attention="flash",
            remat_policy="flash_min",
            scan_layers=False,
        )
        batch, seq, steps = 16, 1024, 10
    else:  # CI / local smoke: tiny model
        cfg = dataclasses.replace(CONFIGS["tiny"], max_seq_len=256)
        batch, seq, steps = 8, 128, 5

    mesh = build_mesh(MeshSpec(dp=n_chips))
    rules = PRESET_RULES["dp"] if n_chips == 1 else PRESET_RULES["fsdp"]
    opt = default_optimizer(lr=1e-3, warmup=10)
    init_fn, shardings = make_sharded_init(cfg, mesh, rules, opt)
    state = init_fn(jax.random.PRNGKey(0))
    step = make_train_step(cfg, mesh, rules, opt, shardings)

    rng = np.random.default_rng(0)
    batch_data = {
        "tokens": jnp.asarray(
            rng.integers(0, cfg.vocab_size, size=(batch, seq + 1)), jnp.int32
        ),
        "mask": jnp.ones((batch, seq + 1), jnp.int32),
    }

    # warmup (compile)
    t0 = time.perf_counter()
    state, metrics = step(state, batch_data)
    jax.block_until_ready(metrics["loss"])
    compile_s = time.perf_counter() - t0
    state, metrics = step(state, batch_data)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_data)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / dt
    tokens_per_sec_per_chip = tokens_per_sec / n_chips

    flops_per_token = cfg.flops_per_token() + cfg.attention_flops_per_token(seq)
    mfu = tokens_per_sec_per_chip * flops_per_token / _peak_flops(dev)
    vs_baseline = mfu / 0.45

    print(
        f"[bench] dev={getattr(dev, 'device_kind', dev.platform)} chips={n_chips} "
        f"model={cfg.d_model}x{cfg.n_layers} batch={batch} seq={seq} "
        f"compile={compile_s:.1f}s step={dt / steps * 1000:.1f}ms "
        f"loss={float(metrics['loss']):.3f} mfu={mfu:.3f}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "gpt2_125m_train_tokens_per_sec_per_chip"
                if on_tpu
                else "tiny_train_tokens_per_sec_per_chip_cpu",
                "value": round(tokens_per_sec_per_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(vs_baseline, 4),
                "mfu": round(mfu, 4),
                "device": getattr(dev, "device_kind", dev.platform),
                "step_ms": round(dt / steps * 1000, 2),
            }
        )
    )


def _supervise() -> int:
    """Run the real bench in a watched child. When the TPU tunnel is down,
    the site hook's plugin registration blocks `import jax` forever — the
    supervisor contains that hang, retries with a FRESH child (the tunnel
    can recover between attempts), and only after every attempt fails swaps
    in a CPU fallback (marked in the JSON). Healthy runs pay nothing extra:
    the first child does all the work exactly once and its output is
    forwarded verbatim."""
    import os
    import subprocess
    import time as _time

    env = dict(os.environ, RAY_TPU_BENCH_CHILD="1")
    # healthy TPU runs finish in ~90-130s (compile included); prolonged
    # silence means the backend is wedged on a dead tunnel (observed: the
    # device-claim leg hangs AFTER `import jax` succeeds). Err generous: a
    # too-small value silently swaps in the CPU-fallback number.
    tpu_timeout = float(os.environ.get("RAY_TPU_BENCH_TPU_TIMEOUT_S", "300"))
    attempts = int(os.environ.get("RAY_TPU_BENCH_TPU_ATTEMPTS", "3"))
    backoffs = [15.0, 30.0]  # between attempts; tunnel reacquisition is slow

    def run_child(cmd, child_env, timeout):
        """Returns (rc|None, stdout, stderr); rc None = hung/timed out.

        Own session + group-kill on timeout: a wedged child may have forked
        helpers (tunnel processes) that inherit the pipes — killing only the
        child would leave communicate() blocked short of EOF forever."""
        import signal

        p = subprocess.Popen(
            cmd, env=child_env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, start_new_session=True,
        )
        try:
            out, err = p.communicate(timeout=timeout)
            return p.returncode, out or "", err or ""
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except OSError:
                p.kill()
            try:
                out, err = p.communicate(timeout=10)
            except Exception:
                out, err = "", ""
            return None, out or "", err or ""

    me = os.path.abspath(__file__)
    for i in range(attempts):
        t0 = _time.perf_counter()
        rc, out, err = run_child([sys.executable, me], env, tpu_timeout)
        dt = _time.perf_counter() - t0
        if rc == 0 and out.strip():
            if i:
                print(f"[bench] TPU attempt {i + 1}/{attempts} succeeded "
                      f"after earlier failures", file=sys.stderr)
            sys.stderr.write(err)
            sys.stdout.write(out)
            return 0
        why = "hung (timeout)" if rc is None else f"rc={rc}"
        tail = "\n".join(err.strip().splitlines()[-6:])
        print(f"[bench] TPU attempt {i + 1}/{attempts} failed ({why}, "
              f"{dt:.0f}s){': ' + tail if tail else ''}", file=sys.stderr)
        if i < attempts - 1:
            _time.sleep(backoffs[min(i, len(backoffs) - 1)])
    # fall back even when the child RAN and failed (not just hangs): a dead
    # tunnel can also surface as a fast nonzero exit (backend-unregistered
    # raise), and an artifact with an explicit `_cpu` metric + the failure
    # tail above beats no artifact at all. The metric name keeps a real TPU
    # bench bug from masquerading as a TPU result.
    print(f"[bench] TPU backend failed after {attempts} attempts; "
          "CPU fallback", file=sys.stderr)
    env["JAX_PLATFORMS"] = "cpu"  # -S skips the blocking site hook
    from ray_tpu._private.spawn import child_pythonpath

    env["PYTHONPATH"] = child_pythonpath(inherited=env.get("PYTHONPATH"))
    rc, out, err = run_child(
        [sys.executable, "-S", me], env, 600
    )
    sys.stderr.write(err)
    sys.stdout.write(out)
    return rc if rc is not None else 1


if __name__ == "__main__":
    import os

    if os.environ.get("RAY_TPU_BENCH_CHILD") == "1":
        main()
    else:
        sys.exit(_supervise())
