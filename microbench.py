"""Core-runtime microbenchmarks, tracked per round like bench.py.

Reference parity: python/ray/_private/ray_perf.py (the microbenchmark
definitions behind release/microbenchmark). Prints one JSON line with the
headline rates; the targets (VERDICT r1 item 4) are >=5k tasks/s submit,
>=2.5k sync actor calls/s, >=10 GB/s 100MB put.
"""

from __future__ import annotations

import json
import sys
import time


def bench_task_submit(n: int = 2000) -> float:
    import ray_tpu

    @ray_tpu.remote
    def noop():
        return None

    # warm the worker pool
    ray_tpu.get([noop.remote() for _ in range(8)])
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    submit_dt = time.perf_counter() - t0
    ray_tpu.get(refs)
    return n / submit_dt


def bench_task_roundtrip(n: int = 500) -> float:
    import ray_tpu

    @ray_tpu.remote
    def noop():
        return None

    ray_tpu.get(noop.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(noop.remote())
    return n / (time.perf_counter() - t0)


def bench_actor_sync(n: int = 2000) -> float:
    import ray_tpu

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.m.remote())
    return n / (time.perf_counter() - t0)


def bench_actor_async(n: int = 5000) -> float:
    import ray_tpu

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    return n / (time.perf_counter() - t0)


def bench_put_gbps(mb: int = 100, iters: int = 5) -> float:
    import numpy as np

    import ray_tpu

    data = np.random.default_rng(0).bytes(mb * 1024 * 1024)
    arr = np.frombuffer(data, dtype=np.uint8)
    # each ref is dropped before the next put (ray_perf semantics): the
    # slab allocator then reuses warm pages instead of first-touch faulting
    for _ in range(3):
        ref = ray_tpu.put(arr)
        del ref
        time.sleep(0.05)
    t0 = time.perf_counter()
    for _ in range(iters):
        ref = ray_tpu.put(arr)
        del ref
    dt = time.perf_counter() - t0
    return mb * iters / 1024 / dt


def bench_get_gbps(mb: int = 100, iters: int = 5) -> float:
    import numpy as np

    import ray_tpu

    arr = np.frombuffer(np.random.default_rng(0).bytes(mb * 1024 * 1024), dtype=np.uint8)
    ref = ray_tpu.put(arr)
    ray_tpu.get(ref)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = ray_tpu.get(ref)
    dt = time.perf_counter() - t0
    del out
    return mb * iters / 1024 / dt


def main():
    import os

    import ray_tpu

    ray_tpu.init()
    results = {"host_cpus": os.cpu_count()}
    results["task_submit_per_s"] = round(bench_task_submit(), 1)
    results["task_roundtrip_per_s"] = round(bench_task_roundtrip(), 1)
    results["actor_calls_sync_per_s"] = round(bench_actor_sync(), 1)
    results["actor_calls_async_per_s"] = round(bench_actor_async(), 1)
    results["put_100mb_gbps"] = round(bench_put_gbps(), 2)
    results["get_100mb_gbps"] = round(bench_get_gbps(), 2)
    ray_tpu.shutdown()
    targets = {
        "task_submit_per_s": 5000.0,
        "actor_calls_sync_per_s": 2500.0,
        "put_100mb_gbps": 10.0,
    }
    results["targets_met"] = all(results[k] >= v for k, v in targets.items())
    print(json.dumps(results))
    return results


if __name__ == "__main__":
    sys.exit(0 if main()["targets_met"] else 1)
