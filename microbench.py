"""Core-runtime microbenchmarks, tracked per round like bench.py.

Reference parity: python/ray/_private/ray_perf.py (the microbenchmark
definitions behind release/microbenchmark). Prints one JSON line with the
headline rates; the targets (VERDICT r1 item 4) are >=5k tasks/s submit,
>=2.5k sync actor calls/s, >=10 GB/s 100MB put, plus an anti-regression
floor on cross-node 256MB transfer (VERDICT weak #3).
"""

from __future__ import annotations

import json
import os
import sys
import time


def bench_task_submit(n: int = 2000) -> float:
    import ray_tpu

    @ray_tpu.remote
    def noop():
        return None

    # warm the worker pool
    ray_tpu.get([noop.remote() for _ in range(8)])
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    submit_dt = time.perf_counter() - t0
    ray_tpu.get(refs)
    return n / submit_dt


def bench_task_roundtrip(n: int = 500) -> float:
    import ray_tpu

    @ray_tpu.remote
    def noop():
        return None

    ray_tpu.get(noop.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(noop.remote())
    return n / (time.perf_counter() - t0)


def bench_actor_sync(n: int = 2000) -> float:
    import ray_tpu

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.m.remote())
    return n / (time.perf_counter() - t0)


def bench_actor_async(n: int = 5000) -> float:
    import ray_tpu

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    return n / (time.perf_counter() - t0)


def host_memcpy_gbps(mb: int = 100, iters: int = 5) -> float:
    """This host's single-copy floor: put() necessarily pays ONE copy into
    the shm slab, so its ceiling is this number (the 10 GB/s absolute
    target assumes a multicore host where the slab's parallel copy engages;
    on small hosts the honest target is relative to this floor)."""
    import numpy as np

    src = np.frombuffer(np.random.default_rng(0).bytes(mb * 1024 * 1024), dtype=np.uint8)
    dst = bytearray(len(src))
    memoryview(dst)[:] = src.data  # warm dst pages
    t0 = time.perf_counter()
    for _ in range(iters):
        memoryview(dst)[:] = src.data
    return mb * iters / 1024 / (time.perf_counter() - t0)


def bench_put_gbps(mb: int = 100, iters: int = 5) -> float:
    import numpy as np

    import ray_tpu
    from ray_tpu._private.worker import global_worker

    data = np.random.default_rng(0).bytes(mb * 1024 * 1024)
    arr = np.frombuffer(data, dtype=np.uint8)
    # each ref is dropped before the next put (ray_perf semantics): the
    # slab allocator then reuses warm pages instead of first-touch faulting.
    # The sync round-trip per warmup iteration makes the head PROCESS the
    # deletes before the timed loop — otherwise the timed puts allocate
    # cold pages and measure page faults, not the store.
    for _ in range(5):
        ref = ray_tpu.put(arr)
        del ref
        global_worker.request({"t": "nodes"})
    t0 = time.perf_counter()
    for _ in range(iters):
        ref = ray_tpu.put(arr)
        del ref
    dt = time.perf_counter() - t0
    return mb * iters / 1024 / dt


def bench_get_gbps(mb: int = 100, iters: int = 5) -> float:
    import numpy as np

    import ray_tpu

    arr = np.frombuffer(np.random.default_rng(0).bytes(mb * 1024 * 1024), dtype=np.uint8)
    ref = ray_tpu.put(arr)
    ray_tpu.get(ref)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = ray_tpu.get(ref)
    dt = time.perf_counter() - t0
    del out
    return mb * iters / 1024 / dt


def bench_weight_broadcast_ms(mb: int = 10, n_actors: int = 16) -> float:
    """IMPALA-shaped: learner weights -> rollout fleet. put() once (into
    shm), every actor maps the same buffer zero-copy; the measured number
    is the full driver-side latency until every actor holds the weights
    (VERDICT r2 item 5: 10MB to 16 actors, target <50ms localhost)."""
    import numpy as np

    import ray_tpu

    @ray_tpu.remote
    class Rollout:
        def set_weights(self, w):
            self._w = w
            return w.shape[0]

    actors = [Rollout.remote() for _ in range(n_actors)]
    w = np.frombuffer(np.random.default_rng(0).bytes(mb * 1024 * 1024), dtype=np.float32)
    ref = ray_tpu.put(w)
    ray_tpu.get([a.set_weights.remote(ref) for a in actors])  # warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        ref = ray_tpu.put(w)
        ray_tpu.get([a.set_weights.remote(ref) for a in actors])
        best = min(best, time.perf_counter() - t0)
    for a in actors:
        ray_tpu.kill(a)
    return best * 1000.0


def bench_decode_speedup(new_tokens: int = 48) -> dict:
    """Continuous-batching win, gated: ONE engine stepping 8 KV-cache
    decode slots together vs serial single-slot decode on the same host.
    Batched decode amortizes the per-step dispatch + weight reads over the
    whole batch, so the tokens/s ratio must clear 2x (the anti-regression
    floor; the measured ratio is usually far higher). Runs on CPU (tiny
    model) — this gates the BATCHING mechanics, not the chip. Both engines
    run PAGED (block-table gather in the decode step), so the gate also
    proves paging did not regress the batched-decode win."""
    import dataclasses

    import numpy as np

    from ray_tpu.models.kv_paging import PagedDecodeEngine
    from ray_tpu.models import CONFIGS

    cfg = dataclasses.replace(CONFIGS["tiny"], max_seq_len=256)
    B = 8
    prompts = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(B, 16)
    )
    never = {"max_new_tokens": 10**9}

    batched = PagedDecodeEngine(cfg, max_batch_size=B, seed=0)
    slots = list(range(B))
    for s in slots:
        batched.admit(s, {"tokens": prompts[s], **never})
    batched.step(slots)  # decode compile + warm
    t0 = time.perf_counter()
    for _ in range(new_tokens):
        batched.step(slots)
    batched_tps = B * new_tokens / (time.perf_counter() - t0)

    serial = PagedDecodeEngine(cfg, max_batch_size=1, seed=0)
    serial.admit(0, {"tokens": prompts[0], **never})
    serial.step([0])
    t0 = time.perf_counter()
    for _ in range(new_tokens):
        serial.step([0])
    serial_tps = new_tokens / (time.perf_counter() - t0)
    return {
        "decode_batched_tokens_per_s": round(batched_tps, 1),
        "decode_serial_tokens_per_s": round(serial_tps, 1),
        "decode_batched_speedup_x": round(batched_tps / serial_tps, 2),
    }


def bench_decode_long_context(
    prefix_tokens: int = 0, batch: int = 2, new_tokens: int = 12,
) -> dict:
    """Long-context decode: the HBM-bound regime where paged attention's
    cost actually lives (a 4k-token prefix means every decode step reads
    ~4k tokens of K/V per layer — bandwidth, not compute). Three engines
    decode the same prompts:

      gather/fp   the block-table gather step (pre-fused reference path)
      fused/fp    ops/paged_attention block-in-place walk, same bytes read
      fused/int8  + int8 blocks: half the bytes per resident token

    Gated: fused/fp must BEAT gather/fp at the same dtype (the kernel win,
    isolated from quantization), and the int8 pool must hold ~2x the
    blocks of the fp pool for the same byte budget (the capacity win
    admission/autoscaling sees). The prefix admits in chunks through the
    prefix cache — each admit reuses the prior chunks' blocks — so setup
    stays ~linear instead of one quadratic 4k prefill."""
    import dataclasses

    import numpy as np

    from ray_tpu.models import CONFIGS, init_params
    from ray_tpu.models.kv_paging import PagedDecodeEngine
    from ray_tpu.models.transformer import paged_kv_block_bytes

    import jax
    import jax.numpy as jnp

    prefix_tokens = prefix_tokens or int(
        os.environ.get("RAY_TPU_MICROBENCH_LONGCTX_TOKENS", "4096")
    )
    chunk = 1024
    bt = 64
    cfg = dataclasses.replace(
        CONFIGS["tiny"], dtype=jnp.float32, max_seq_len=prefix_tokens + 2 * bt
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompts = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(batch, prefix_tokens)
    )

    def build(impl, dtype):
        eng = PagedDecodeEngine(
            cfg, params, max_batch_size=batch, block_tokens=bt,
            attention_impl=impl, kv_cache_dtype=dtype, seed=0,
            prefill_buckets=(chunk,),
        )
        for s in range(batch):
            for end in range(chunk, prefix_tokens + 1, chunk):
                eng.admit(s, {"tokens": prompts[s][:end],
                              "max_new_tokens": 10**9})
                if end < prefix_tokens:
                    eng.release(s)
        eng.step(list(range(batch)))  # compile + warm
        return eng

    # a 12-token timed window on a shared host is one scheduler hiccup
    # away from inverting the comparison, and timing the engines
    # back-to-back lets slow drift (thermal, co-tenant load) bias one
    # side. So: build + warm all three, then INTERLEAVE timed repeats
    # round-robin and keep each engine's best — best-of-repeats is the
    # noise-free estimate, interleaving makes drift hit all three alike.
    engines = {
        "gather_fp": build("gather", "fp"),
        "fused_fp": build("fused", "fp"),
        "fused_int8": build("fused", "int8"),
    }
    slots = list(range(batch))
    best = {name: 0.0 for name in engines}
    for _ in range(3):
        for name, eng in engines.items():
            t0 = time.perf_counter()
            for _ in range(new_tokens):
                eng.step(slots)
            r = batch * new_tokens / (time.perf_counter() - t0)
            best[name] = max(best[name], r)
    gather_fp = best["gather_fp"]
    fused_fp = best["fused_fp"]
    fused_int8 = best["fused_int8"]

    # capacity: same byte budget, blocks counted by the engine's own
    # byte-budget sizing — int8 should land ~2x fp. The probe config uses
    # bf16 (the serving dtype) so the ratio states the production claim;
    # this engine above runs f32 only because CPU timing wants it
    small = dataclasses.replace(
        cfg, dtype=jnp.bfloat16, max_seq_len=4 * bt
    )
    budget = 64 * paged_kv_block_bytes(small, bt)
    blocks = {}
    for dtype in ("fp", "int8"):
        e = PagedDecodeEngine(
            small, params=None, max_batch_size=1, block_tokens=bt,
            pool_bytes=budget, kv_cache_dtype=dtype, seed=0,
        )
        blocks[dtype] = e.stats()["kv_blocks_total"]
    return {
        "decode_long_context_tokens_per_s": round(fused_int8, 1),
        "decode_long_context_fused_fp_tokens_per_s": round(fused_fp, 1),
        "decode_long_context_gather_tokens_per_s": round(gather_fp, 1),
        "decode_long_context_fused_speedup_x": round(fused_fp / gather_fp, 2),
        "decode_long_context_int8_speedup_x": round(fused_int8 / gather_fp, 2),
        "kv_int8_blocks_ratio": round(blocks["int8"] / blocks["fp"], 2),
    }


def bench_decode_speculative(new_tokens: int = 96, k: int = 4) -> dict:
    """Speculative-decoding win at LOW batch (B=1 — the lone-stream
    latency regime where batching can't help), gated: propose-k drafting
    + one batched k+1-token verify step must beat per-token decode by >=
    1.5x tokens/s. The drafter is a perfect-draft REPLAY of the
    non-speculative engine's own greedy output (the pluggable
    small-draft-model hook), so the gate certifies the
    propose/verify/commit MECHANICS — one verify step must genuinely
    outrun the k+1 single-token steps it replaces; drafter QUALITY is a
    model/workload property this CPU tiny-model row cannot measure.
    In-row identity assertion: the speculative engine's greedy output
    must equal the non-speculative engine's token-for-token, else the
    speedup is forced to 0 (fails the gate loudly).

    Same discipline as the long-context row: both engines build + warm
    first (the warm run is also the identity check), then timed repeats
    INTERLEAVE round-robin and each side keeps its best — host drift hits
    both alike, best-of-repeats drops scheduler hiccups."""
    import dataclasses

    import numpy as np

    from ray_tpu.models import CONFIGS
    from ray_tpu.models.kv_paging import PagedDecodeEngine
    from ray_tpu.models.speculative import ReplayDrafter

    cfg = dataclasses.replace(CONFIGS["tiny"], max_seq_len=256)
    prompt = np.random.default_rng(0).integers(0, cfg.vocab_size, size=24)

    base = PagedDecodeEngine(cfg, max_batch_size=1, seed=0)

    def run(eng):
        tok, done = eng.admit(0, {"tokens": prompt,
                                  "max_new_tokens": new_tokens})
        out = [tok]
        while not done:
            toks, done = eng.step([0])[0]
            out.extend(toks if isinstance(toks, (list, tuple)) else [toks])
        eng.release(0)
        return out

    recorded = run(base)  # greedy reference + prefill/decode warmup
    spec = PagedDecodeEngine(
        cfg, max_batch_size=1, seed=0, speculative_k=k,
        drafter=ReplayDrafter([list(prompt) + recorded]),
    )
    identical = run(spec) == recorded  # verify-step warmup + identity gate

    def timed(eng):
        """tokens/s over the STEP loop (prefill excluded: the gate is the
        per-token decode rate, and both sides prefill identically)."""
        tok, done = eng.admit(0, {"tokens": prompt,
                                  "max_new_tokens": new_tokens})
        n = 1
        t0 = time.perf_counter()
        while not done:
            toks, done = eng.step([0])[0]
            n += len(toks) if isinstance(toks, (list, tuple)) else 1
        dt = time.perf_counter() - t0
        eng.release(0)
        return (n - 1) / dt

    best_off = best_on = 0.0
    for _ in range(3):
        best_off = max(best_off, timed(base))
        best_on = max(best_on, timed(spec))
    speedup = best_on / best_off if identical else 0.0
    out = {
        "spec_off_tokens_per_s": round(best_off, 1),
        "spec_on_tokens_per_s": round(best_on, 1),
        "spec_decode_speedup_x": round(speedup, 2),
        "spec_accept_rate": spec.stats()["spec_accept_rate"],
        "spec_greedy_identical": int(identical),
    }
    out.update(_spec_verify_longctx())
    return out


def _spec_verify_longctx(
    prefix_tokens: int = 0, batch: int = 2, new_tokens: int = 24, k: int = 4,
) -> dict:
    """Long-context half of the speculative row (ISSUE 13), gated: the
    fused multi-query verify step (q = k+1 through the block-in-place
    walk + in-flight log-sum-exp merge) must at least MATCH the
    gather-window verify at long context — before the multi-query
    kernel, speculation re-paid the gather cost the fused decode path
    had eliminated, so long-context streams LOST part of the fused win
    the moment they drafted. Perfect-draft replay (mechanics, not
    drafter quality), in-row greedy-identity assertion zeroes the
    speedup on divergence, warm-then-interleaved best-of-repeats."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import CONFIGS, init_params
    from ray_tpu.models.kv_paging import PagedDecodeEngine
    from ray_tpu.models.speculative import ReplayDrafter

    prefix_tokens = prefix_tokens or int(
        os.environ.get("RAY_TPU_MICROBENCH_LONGCTX_TOKENS", "4096")
    )
    chunk = min(1024, prefix_tokens)
    bt = 64
    cfg = dataclasses.replace(
        CONFIGS["tiny"], dtype=jnp.float32, max_seq_len=prefix_tokens + 2 * bt
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompts = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(batch, prefix_tokens)
    )
    slots = list(range(batch))

    def admit_chunked(eng):
        # the long prefix admits in chunks through the prefix cache (setup
        # stays ~linear); re-admission after release hits the cache whole.
        # Returns each slot's FIRST sampled token (from the full-prompt
        # admission) — it is part of the slot's history, so the replay
        # drafter's recorded sequences must include it or they never
        # prefix-match and speculation silently never runs
        first = {}
        for s in slots:
            for end in range(chunk, prefix_tokens + 1, chunk):
                t, _ = eng.admit(s, {"tokens": prompts[s][:end],
                                     "max_new_tokens": 10**9})
                if end < prefix_tokens:
                    eng.release(s)
            first[s] = int(t)
        return first

    plain = PagedDecodeEngine(
        cfg, params, max_batch_size=batch, block_tokens=bt, seed=0,
        prefill_buckets=(chunk,),
    )
    refs = {s: [t] for s, t in admit_chunked(plain).items()}
    for _ in range(new_tokens - 1):
        r = plain.step(slots)
        for s in slots:
            refs[s].append(r[s][0])

    def build(impl):
        eng = PagedDecodeEngine(
            cfg, params, max_batch_size=batch, block_tokens=bt, seed=0,
            prefill_buckets=(chunk,), attention_impl=impl, speculative_k=k,
            drafter=ReplayDrafter(
                [list(prompts[s]) + refs[s] for s in slots]
            ),
        )
        return eng, admit_chunked(eng)

    def run(eng, first):
        outs = {s: [first[s]] for s in slots}
        while min(len(o) for o in outs.values()) < new_tokens:
            for s, (toks, _) in eng.step(slots).items():
                outs[s].extend(
                    toks if isinstance(toks, (list, tuple)) else [toks]
                )
        return outs

    engines = {"gather": build("gather"), "fused": build("fused:xla")}
    identical = True
    for eng, first in engines.values():  # warm + identity
        o = run(eng, first)
        identical = identical and all(
            o[s][:new_tokens] == refs[s] for s in slots
        )
    # the gate certifies the VERIFY path: if the drafter never engaged
    # (spec_steps == 0) the timed loop would measure plain decode and the
    # comparison would be vacuous — zero the metric so the gate fails loud
    engaged = all(e.spec_steps > 0 for e, _ in engines.values())

    def timed(eng):
        for s in slots:
            eng.release(s)
            # re-admit whole: the prefix cache serves every full block, so
            # only the tail re-prefills — setup off the timed path
            eng.admit(s, {"tokens": prompts[s], "max_new_tokens": 10**9})
        n = 0
        t0 = time.perf_counter()
        while n < batch * new_tokens:
            for toks, _ in eng.step(slots).values():
                n += len(toks) if isinstance(toks, (list, tuple)) else 1
        return n / (time.perf_counter() - t0)

    best = {name: 0.0 for name in engines}
    for _ in range(3):
        for name, (eng, _) in engines.items():
            best[name] = max(best[name], timed(eng))
    ok = identical and engaged
    speedup = best["fused"] / best["gather"] if ok else 0.0
    return {
        "spec_verify_ctx_tokens": prefix_tokens,
        "spec_verify_engaged": int(engaged),
        "spec_verify_gather_tokens_per_s": round(best["gather"], 1),
        "spec_verify_fused_tokens_per_s": round(best["fused"], 1),
        "spec_verify_fused_speedup_x": round(speedup, 2),
    }


def bench_decode_mixed_traffic(
    prefix_tokens: int = 0, chunk: int = 256, decode_slots: int = 2,
    base_steps: int = 32,
) -> dict:
    """Mixed-traffic tail latency (ISSUE 13's scheduling gate): decode
    p99 inter-token latency measured WHILE a long prompt streams into the
    same running batch as prefill chunks (`prefill_chunk_tokens`), gated
    two ways against the decode-only baseline on the same engine:

      decode_mixed_p99_ratio_x <= bound   chunk steps interleave with
        decode steps, so the worst inter-token gap a decode stream sees
        is ~one chunk's compute — BOUNDED, load-independent of prompt
        length. A scheduler regression (multiple chunks coalescing into
        one step, or a silent whole-prefill fallback) blows this by an
        order of magnitude.
      decode_chunk_stall_reduction_x >= bound   the same prompt admitted
        WHOLE stalls every decode stream for its entire prefill; chunked
        admission must cut that head-of-line spike by >= 4x (measured
        ~12-20x: the ratio grows with prompt length — that is the point).

    The engine runs the production shape: fused attention, chunked
    prefill ON, prefix cache OFF (a cache hit would skip the very
    prefill being measured). All chunk-prefill compile keys are warmed by
    a full throwaway admission first."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import CONFIGS, init_params
    from ray_tpu.models.kv_paging import PagedDecodeEngine

    prefix_tokens = prefix_tokens or int(
        os.environ.get("RAY_TPU_MICROBENCH_LONGCTX_TOKENS", "4096")
    )
    bt = 64
    cfg = dataclasses.replace(
        CONFIGS["tiny"], dtype=jnp.float32, max_seq_len=prefix_tokens + 4 * bt
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    dec_prompts = rng.integers(0, cfg.vocab_size, size=(decode_slots, 128))
    long_warm = rng.integers(0, cfg.vocab_size, size=prefix_tokens)
    long_timed = rng.integers(0, cfg.vocab_size, size=prefix_tokens)
    B = decode_slots + 1
    dslots = list(range(decode_slots))
    lslot = decode_slots

    def build(chunk_tokens, buckets):
        eng = PagedDecodeEngine(
            cfg, params, max_batch_size=B, block_tokens=bt,
            attention_impl="fused", prefill_chunk_tokens=chunk_tokens,
            prefix_cache=False, seed=0, prefill_buckets=buckets,
        )
        for s in dslots:
            eng.admit(s, {"tokens": dec_prompts[s],
                          "max_new_tokens": 10**9})
        eng.step(dslots)  # decode compile + warm
        return eng

    eng = build(chunk, (128, chunk))
    # warm EVERY chunk-prefill compile key (ctx buckets double up the
    # prompt, so a 4k prompt walks ~log2 distinct (ctx, chunk) shapes)
    eng.admit(lslot, {"tokens": long_warm, "max_new_tokens": 1})
    while eng.stats()["prefilling"]:
        eng.step(dslots + [lslot])
    eng.release(lslot)

    base = []
    for _ in range(base_steps):
        t0 = time.perf_counter()
        eng.step(dslots)
        base.append(time.perf_counter() - t0)

    eng.admit(lslot, {"tokens": long_timed, "max_new_tokens": 1})
    mixed = []
    while eng.stats()["prefilling"]:
        t0 = time.perf_counter()
        eng.step(dslots + [lslot])
        mixed.append(time.perf_counter() - t0)
    eng.release(lslot)

    # the head-of-line spike chunking removes: the same prompt admitted
    # whole (chunking OFF) blocks the loop for its entire prefill
    whole = build(0, (128, prefix_tokens))
    whole.admit(lslot, {"tokens": long_warm, "max_new_tokens": 1})
    whole.release(lslot)  # prefill compile
    t0 = time.perf_counter()
    whole.admit(lslot, {"tokens": long_timed, "max_new_tokens": 1})
    stall = time.perf_counter() - t0

    p99_base = float(np.percentile(base, 99))
    p99_mixed = float(np.percentile(mixed, 99))
    return {
        "mixed_traffic_prompt_tokens": prefix_tokens,
        "mixed_traffic_chunk_tokens": chunk,
        "decode_only_p99_ms": round(p99_base * 1000, 2),
        "decode_mixed_p99_ms": round(p99_mixed * 1000, 2),
        "decode_mixed_p99_ratio_x": round(p99_mixed / p99_base, 2),
        "whole_prompt_stall_ms": round(stall * 1000, 1),
        "decode_chunk_stall_reduction_x": round(stall / p99_mixed, 2),
    }


def bench_prefix_hit(trials: int = 3) -> dict:
    """Prefix-reuse win, gated: admitting a prompt whose prefix blocks are
    already in the PagedDecodeEngine's hash-trie must beat the cold admit
    of the same prompt by >= 2x — the hit prefills only the (one-token)
    tail while the cold path recomputes the whole prompt. Both compile
    paths are warmed on a throwaway prompt first; each trial uses a FRESH
    prompt so its first admit is a true cold miss."""
    import dataclasses
    import statistics

    import numpy as np

    from ray_tpu.models import CONFIGS
    from ray_tpu.models.kv_paging import PagedDecodeEngine

    bt = 32
    cfg = dataclasses.replace(CONFIGS["tiny"], max_seq_len=512)
    eng = PagedDecodeEngine(
        cfg, max_batch_size=2, seed=0, block_tokens=bt, num_blocks=128,
    )
    rng = np.random.default_rng(0)
    # 15 full blocks + 1 tail token: the hit path prefills ONE token while
    # the cold path recomputes all 481 (the realistic shared-system-prompt
    # shape — the shared span dwarfs the per-request tail)
    plen = 15 * bt + 1
    one = {"max_new_tokens": 1}

    def admit_ms(prompt):
        t0 = time.perf_counter()
        eng.admit(0, {"tokens": prompt, **one})
        dt = (time.perf_counter() - t0) * 1000
        eng.release(0)
        return dt

    warm = rng.integers(0, cfg.vocab_size, size=plen)
    admit_ms(warm)  # cold-path compile
    admit_ms(warm)  # hit-path compile
    cold, hit = [], []
    for _ in range(trials):
        prompt = rng.integers(0, cfg.vocab_size, size=plen)
        cold.append(admit_ms(prompt))
        hit.append(admit_ms(prompt))
    cold_ms = statistics.median(cold)
    hit_ms = statistics.median(hit)
    return {
        "prefix_hit_cold_ms": round(cold_ms, 2),
        "prefix_hit_ms": round(hit_ms, 2),
        "prefix_hit_speedup_x": round(cold_ms / max(hit_ms, 1e-9), 2),
    }


def bench_serve_cross_replica(trials: int = 3) -> dict:
    """Cross-replica prefix transfer win, gated (--only row): serving a
    prompt whose prefix blocks arrive from a PEER engine over the
    transfer path (export -> pack -> wire-check -> unpack -> import ->
    admit) must beat the cold full prefill of the same prompt by >= 1.5x
    — the import pays numpy copies plus a pool scatter instead of
    recomputing attention over the whole shared span. The speedup only
    counts if the importing engine's greedy continuation is TOKEN-
    IDENTICAL to the cold engine's: any divergence zeroes the metric
    (and so fails the gate) — a fast wrong answer is worthless."""
    import dataclasses
    import statistics

    import jax
    import numpy as np

    from ray_tpu.models import CONFIGS, init_params
    from ray_tpu.models.kv_paging import PagedDecodeEngine
    from ray_tpu.serve.kv_transfer import pack_payload, unpack_payload

    bt = 32
    cfg = dataclasses.replace(CONFIGS["tiny"], max_seq_len=1152)
    params = init_params(jax.random.PRNGKey(0), cfg)

    def mk():
        return PagedDecodeEngine(
            cfg, params, max_batch_size=2, seed=0, block_tokens=bt,
            num_blocks=192, model_id="bench",
        )

    def gen(eng, prompt, payload=None):
        """(time-to-first-token ms, greedy tokens) for one generation."""
        req = {"tokens": prompt, "max_new_tokens": 8}
        if payload is not None:
            req["kv_import"] = payload
        t0 = time.perf_counter()
        tok, done = eng.admit(0, req)
        ttft = (time.perf_counter() - t0) * 1000
        out = [tok]
        while not done:
            tok, done = eng.step([0])[0]
            out.append(tok)
        eng.release(0)
        return ttft, out

    rng = np.random.default_rng(0)
    plen = 31 * bt + 1  # a ~1k shared span dwarfs the per-request tail
    # three long-lived engines, as in a real fleet: the peer that computed
    # the prefix, the replica that imports it, the replica that recomputes
    # it cold. Each is warmed on a throwaway prompt first (per-engine jit
    # closures: a fresh engine's first admit pays ~40x in compile) — every
    # trial's prompt is fresh, so the cold engine's admit stays a true miss
    src, dst, cold = mk(), mk(), mk()
    warm = rng.integers(0, cfg.vocab_size, size=plen)
    gen(src, warm)
    gen(cold, warm)
    gen(dst, warm, unpack_payload(*pack_payload(
        src.export_prefix(np.asarray(warm, np.int32))
    )))
    cold_ts, imp_ts, identical, payload_bytes = [], [], True, 0
    for _ in range(trials):
        prompt = rng.integers(0, cfg.vocab_size, size=plen)
        _, out_src = gen(src, prompt)  # peer computes + caches the chain
        cold_ms, out_cold = gen(cold, prompt)
        # the import path pays: export gather + pack + wire check + unpack
        # + pool scatter + tail-only admit (the decode tail is identical
        # on both paths and counted in neither — gen times admit only)
        t0 = time.perf_counter()
        meta, buf = pack_payload(
            src.export_prefix(np.asarray(prompt, np.int32))
        )
        payload = unpack_payload(meta, buf)
        transfer_ms = (time.perf_counter() - t0) * 1000
        imp_ttft, out_imp = gen(dst, prompt, payload)
        cold_ts.append(cold_ms)
        imp_ts.append(transfer_ms + imp_ttft)
        payload_bytes = int(buf.size)
        identical = identical and (out_src == out_cold == out_imp)
    cold_ms = statistics.median(cold_ts)
    imp_ms = statistics.median(imp_ts)
    speedup = cold_ms / max(imp_ms, 1e-9) if identical else 0.0
    return {
        "cross_replica_cold_ttft_ms": round(cold_ms, 2),
        "cross_replica_import_ms": round(imp_ms, 2),
        "cross_replica_payload_mb": round(payload_bytes / 2**20, 3),
        "cross_replica_greedy_identical": identical,
        "cross_replica_prefix_hit_speedup_x": round(speedup, 2),
    }


def bench_serve_weight_swap(new_tokens: int = 48, n_streams: int = 4) -> dict:
    """Live weight hot-swap latency cost, gated (--only row, needs a
    cluster for the bulk plane + pubsub): decode p99 inter-token latency
    measured while a WeightPublisher -> WeightSubscriber swap lands
    mid-generation must stay within 10x the quiescent p99 on the same
    batcher. The swap preempts every live slot and recomputes their
    histories under the new weights (see kv_paging.set_params), so the
    stall IS the product — n_streams/total gaps sit above the 99th
    percentile by construction, which makes p99 land inside the stall:
    the gate bounds the stall itself, not the steady state around it.
    Any stream that drops or comes back short zeroes the row (ratio 999):
    a fast swap that loses streams is worthless. weight_swap_publish_s
    (flatten + chunked puts + manifest push) ships informational."""
    import dataclasses
    import threading

    import jax
    import numpy as np

    from ray_tpu.models import CONFIGS, init_params
    from ray_tpu.models.kv_paging import PagedDecodeEngine
    from ray_tpu.serve.batching import ContinuousBatcher
    from ray_tpu.serve.weight_swap import WeightPublisher, WeightSubscriber

    cfg = CONFIGS["tiny"]
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    versions = [init_params(k, cfg) for k in keys]
    engine = PagedDecodeEngine(
        cfg, versions[0], max_batch_size=n_streams, temperature=0.0,
        num_blocks=128, seed=0, telemetry=False,
    )
    batcher = ContinuousBatcher(engine, telemetry=False)
    sub = WeightSubscriber(engine, "bench_swap", batcher=batcher).start()
    pub = WeightPublisher("bench_swap")
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, size=8) for _ in range(n_streams)]

    def drain(stream, gaps, toks):
        last = None
        while True:
            items, done = stream.next_batch(max_items=1, wait_s=30.0)
            now = time.perf_counter()
            if items:
                if last is not None:
                    gaps.append(now - last)
                last = now
                toks.extend(items)
            if done:
                return

    def phase(swap_params=None, swap_version=None):
        """Run n_streams concurrent generations to completion; returns
        (all inter-token gaps, per-stream token counts, publish seconds)."""
        streams = [
            batcher.submit(tokens=np.asarray(p, np.int32),
                           max_new_tokens=new_tokens)
            for p in prompts
        ]
        gaps = [[] for _ in streams]
        toks = [[] for _ in streams]
        threads = [
            threading.Thread(target=drain, args=(s, g, t), daemon=True)
            for s, g, t in zip(streams, gaps, toks)
        ]
        for t in threads:
            t.start()
        publish_s = 0.0
        if swap_params is not None:
            # let the streams reach steady-state decode, then land the
            # swap mid-generation through the live plane
            while min(len(t) for t in toks) < new_tokens // 3:
                time.sleep(0.005)
            t0 = time.perf_counter()
            pub.publish(swap_params, version=swap_version)
            publish_s = time.perf_counter() - t0
            deadline = time.time() + 30.0
            while engine.weight_version != swap_version and time.time() < deadline:
                time.sleep(0.005)
        for t in threads:
            t.join(timeout=60.0)
        return (
            [g for gs in gaps for g in gs],
            [len(t) for t in toks],
            publish_s,
        )

    # warmup pays every one-time jit: prefill + decode buckets AND the
    # swap path's readmit prefill (preempted histories land in a longer
    # prefill bucket the plain path never compiles) — the measured phase
    # then times the swap itself, not a first-touch compile
    phase(swap_params=versions[1], swap_version=1)
    q_gaps, q_counts, _ = phase()
    s_gaps, s_counts, publish_s = phase(swap_params=versions[2], swap_version=2)
    survived = (
        all(c == new_tokens for c in q_counts + s_counts)
        and engine.weight_version == 2
        and engine.weight_swaps == 2
    )
    q_p99 = float(np.percentile(q_gaps, 99)) if q_gaps else 0.0
    s_p99 = float(np.percentile(s_gaps, 99)) if s_gaps else 0.0
    ratio = (s_p99 / max(q_p99, 1e-9)) if survived else 999.0
    sub.stop()
    batcher.close()
    return {
        "weight_swap_quiescent_p99_ms": round(q_p99 * 1000, 2),
        "weight_swap_during_p99_ms": round(s_p99 * 1000, 2),
        "weight_swap_publish_s": round(publish_s, 3),
        "weight_swap_streams_survived": survived,
        "weight_swap_p99_ratio_x": round(ratio, 2),
    }


def bench_decode_telemetry_overhead(
    new_tokens: int = 128, batch: int = 8,
) -> dict:
    """Telemetry-plane cost, gated: the full serving loop (ContinuousBatcher
    over a PagedDecodeEngine — per-token TTFT/inter-token observes, per-step
    gauges, flight-recorder events) with telemetry + recorder ON must hold
    >= 0.95x the tokens/s of the identical loop with telemetry OFF. The
    plane is supposed to be lock-cheap (deque appends, histogram observes)
    next to a jax dispatch; this row is the anti-regression tripwire that
    keeps it so. Same discipline as the other decode rows: build + warm
    both sides, then INTERLEAVE timed repeats and keep each side's best."""
    import dataclasses

    import numpy as np

    from ray_tpu.models import CONFIGS
    from ray_tpu.models.kv_paging import PagedDecodeEngine
    from ray_tpu.serve import telemetry
    from ray_tpu.serve.batching import ContinuousBatcher

    cfg = dataclasses.replace(CONFIGS["tiny"], max_seq_len=256)
    prompts = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(batch, 16)
    )
    # force=True: the row must measure the plane even if the host exports
    # RAY_TPU_SERVE_TELEMETRY=0; 'off' passes telemetry=False explicitly
    tel = telemetry.get_telemetry(force=True)

    def build(tel_arg):
        eng = PagedDecodeEngine(
            cfg, max_batch_size=batch, seed=0, telemetry=tel_arg,
        )
        b = ContinuousBatcher(
            eng, max_batch_size=batch, batch_wait_timeout_s=0.05,
            telemetry=tel_arg,
        )
        return b

    def run(b):
        streams = [
            b.submit(tokens=list(prompts[s]), max_new_tokens=new_tokens)
            for s in range(batch)
        ]
        t0 = time.perf_counter()
        n = 0
        for s in streams:
            for _ in s:
                n += 1
        return n / (time.perf_counter() - t0)

    sides = {"on": build(tel), "off": build(False)}
    for b in sides.values():
        run(b)  # compile + warm (prefill/decode jits shared via cache)
    best = {name: 0.0 for name in sides}
    # 5 repeats, ALTERNATING order per round: the batcher loop thread +
    # consumer thread make this row noisier than the engine-direct rows
    # on small hosts, and a fixed on-then-off order would let slow drift
    # (GC, thermal) bias one side; best-of-5 with both orders keeps the
    # ~1-2% true telemetry cost measurable under ~5% scheduler noise
    for i in range(5):
        order = ("on", "off") if i % 2 == 0 else ("off", "on")
        for name in order:
            best[name] = max(best[name], run(sides[name]))
    for b in sides.values():
        b.close()
    return {
        "decode_telemetry_on_tokens_per_s": round(best["on"], 1),
        "decode_telemetry_off_tokens_per_s": round(best["off"], 1),
        "decode_telemetry_overhead_ratio_x": round(
            best["on"] / max(best["off"], 1e-9), 3
        ),
    }


def bench_decode_spec_realtext(new_tokens: int = 48, k: int = 4) -> dict:
    """MEASURED (not gated): the n-gram drafter's accept rate on REAL
    text — tokenizer-encoded English prompts through the model-hub
    fixture checkpoint (tests/fixtures/hub_gpt2_tiny: real byte-level BPE
    vocab, real safetensors weights path). PR 7 gated the speculative
    MECHANICS with a perfect-draft replay; what it could not measure was
    what self-drafting actually earns on real token streams — this row
    closes that question on CPU and records the answer next to the gated
    rows. Accept rate here is a property of the drafter x this tiny
    fixture model's output distribution, so it is recorded, never
    asserted; the gated spec row above stays the mechanics certificate."""
    out = {
        "spec_realtext_available": 0,
        "spec_accept_rate_realtext": 0.0,
        "spec_tokens_per_step_realtext": 0.0,
    }
    fixture = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tests", "fixtures", "hub_gpt2_tiny",
    )
    try:
        from ray_tpu.models.hub import measure_realtext_spec

        m = measure_realtext_spec(fixture, k=k, new_tokens=new_tokens)
        out.update(
            spec_realtext_available=1,
            spec_accept_rate_realtext=m["spec_accept_rate"],
            spec_tokens_per_step_realtext=m["spec_tokens_per_step"],
        )
    except Exception as e:  # fixture missing/unreadable: recorded, not fatal
        print(f"[microbench] realtext spec row unavailable: {e!r}",
              file=sys.stderr)
    return out


def bench_train_dcn_plane() -> dict:
    """Training DCN-plane wins, gated (--only row): the interleaved-1F1B
    pipeline schedule and the int8+error-feedback DCN gradient exchange,
    measured in a child process holding 8 virtual CPU devices (a 2-slice x
    4-device mesh — the parent process's jax backend is already claimed at
    its own device count, so the topology needs a fresh interpreter).

      pipeline_bubble_reduction_x >= 1.3   GPipe bubble over interleaved
        bubble at the measured shape (pp=4, n_mb=4, v=2: (3/7)/(3/11) =
        11/7 ~ 1.57). The ratio only counts if the interleaved schedule's
        outputs AND gradients match the sequential oracle and the compiled
        HLO ships the same dcn-crossing hop list as GPipe (same count,
        same one-copy payload per hop) — a faster wrong schedule, or one
        that pays for its ICI hop multiplier with DCN traffic, zeroes the
        metric and fails the gate loudly.
      dcn_grad_bytes_ratio_x >= 3.5   fp32 gradient all-reduce bytes over
        the int8 exchange's bytes on the dcn tier (measured ~3.93 @
        block=256: s8 payload + per-block f32 shared scales). Zeroed
        unless the int8 run's ICI bytes are EXACTLY the fp32 run's (the
        compression must be dcn-only) and its loss trajectory stays within
        5e-3 of fp32 over the measured steps (error feedback working).
    """
    import subprocess

    zeros = {
        "pipeline_interleave_parity": 0,
        "pipeline_dcn_hops_invariant": 0,
        "pipeline_bubble_gpipe": 0.0,
        "pipeline_bubble_interleaved": 0.0,
        "pipeline_bubble_reduction_x": 0.0,
        "dcn_grad_bytes_fp32": 0,
        "dcn_grad_bytes_int8": 0,
        "dcn_grad_ici_bytes_delta": -1,
        "dcn_grad_loss_delta": -1.0,
        "dcn_grad_bytes_ratio_x": 0.0,
    }
    env = dict(
        os.environ,
        RAY_TPU_MICROBENCH_CHILD="train_dcn_plane",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=(
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip(),
    )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True,
            timeout=int(os.environ.get(
                "RAY_TPU_MICROBENCH_TRIAL_TIMEOUT_S", "900"
            )),
        )
    except subprocess.TimeoutExpired:
        print("[microbench] train_dcn_plane child timed out", file=sys.stderr)
        return zeros
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and set(zeros) <= set(obj):
            return obj
        break
    print(f"[microbench] train_dcn_plane child produced no JSON: "
          f"{proc.stderr[-800:]}", file=sys.stderr)
    return zeros


def _train_dcn_plane_child() -> dict:
    """Runs in the 8-device child: measure, self-check, print one JSON."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import CONFIGS
    from ray_tpu.parallel import MeshSpec, build_multislice_mesh, dp_outer
    from ray_tpu.parallel.pipeline import (
        bubble_fraction, interleaved_stage_order, pipeline_apply,
    )
    from ray_tpu.train.step import (
        default_optimizer, make_sharded_init, make_train_step,
    )
    from ray_tpu.util.collective import (
        assert_no_cross_slice, mesh_collective_report,
    )
    from jax.sharding import Mesh

    out = {}

    # ---- interleaved-1F1B: parity + DCN-hop invariance + bubble ----
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("dcn", "pp", "dp"))
    pp, v, n_mb, rows = 4, 2, 4, 8
    ws = jax.random.normal(jax.random.PRNGKey(0), (rows, 16, 16)) / 4.0
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))

    def stage_fn(w, xs):
        return jnp.tanh(xs @ w)

    def pipe(vv, w, xv):
        return pipeline_apply(
            stage_fn, w, xv, mesh=mesh, n_microbatches=n_mb,
            axis_name=("dcn", "pp"), virtual_stages_per_device=vv,
            stage_order="schedule",
        )

    def seq(w):
        r = x
        for i in range(rows):
            r = jnp.tanh(r @ w[i])
        return r

    order = interleaved_stage_order(rows, pp, v)
    ws_sched = jnp.take(ws, jnp.asarray(order), axis=0)
    out_v = jax.jit(lambda w, xv: pipe(v, w, xv))(ws_sched, x)
    g_v = jax.jit(
        jax.grad(lambda w: jnp.sum(pipe(v, w, x) ** 2))
    )(ws_sched)
    g_ref = jax.grad(lambda w: jnp.sum(seq(w) ** 2))(ws)
    parity = bool(
        np.allclose(np.asarray(out_v), np.asarray(seq(ws)), atol=1e-5)
        and np.allclose(
            np.asarray(g_v), np.asarray(g_ref)[np.asarray(order)], atol=1e-4
        )
    )

    def dcn_hops(vv, w):
        hlo = jax.jit(
            jax.value_and_grad(lambda wv: jnp.sum(pipe(vv, wv, x) ** 2))
        ).lower(w).compile().as_text()
        rep = mesh_collective_report(hlo, mesh)
        assert_no_cross_slice(rep)
        return sorted(
            op.payload_bytes for op in rep["ops"]
            if op.crosses_dcn and op.kind == "collective-permute"
        )

    invariant = dcn_hops(1, ws) == dcn_hops(v, ws_sched) != []
    b1 = bubble_fraction(n_mb, pp, 1)
    bv = bubble_fraction(n_mb, pp, v)
    out.update(
        pipeline_interleave_parity=int(parity),
        pipeline_dcn_hops_invariant=int(invariant),
        pipeline_bubble_gpipe=round(b1, 4),
        pipeline_bubble_interleaved=round(bv, 4),
        pipeline_bubble_reduction_x=round(
            b1 / bv if parity and invariant else 0.0, 2
        ),
    )

    # ---- int8 + EF gradient exchange: dcn-only byte drop ----
    # scan_layers=False so every gradient collective is a top-level HLO op:
    # the static counter counts while-body ops once, which would undercount
    # the fp32 baseline and understate the ratio
    cfg = dataclasses.replace(
        CONFIGS["tiny"], n_layers=2, dtype=jnp.float32, scan_layers=False
    )
    topo, rules = dp_outer(
        2, MeshSpec(dp=4), fsdp_params=False, tensor_parallel=False
    )
    tmesh = build_multislice_mesh(topo)

    def batch(i):
        return {
            "tokens": jnp.asarray(
                np.random.default_rng(100 + i).integers(
                    0, cfg.vocab_size, size=(16, 33)
                ),
                jnp.int32,
            ),
            "mask": jnp.ones((16, 33), jnp.int32),
        }

    def run(compression, n_steps=5):
        opt = default_optimizer(lr=1e-3, warmup=1)
        init_fn, shardings = make_sharded_init(
            cfg, tmesh, rules, opt, dcn_grad_compression=compression
        )
        state = init_fn(jax.random.PRNGKey(0))
        step = make_train_step(
            cfg, tmesh, rules, opt, shardings, dcn_grad_compression=compression
        )
        hlo = step.lower(state, batch(0)).compile().as_text()
        losses = []
        for i in range(n_steps):
            state, m = step(state, batch(i))
            losses.append(float(m["loss"]))
        return losses, mesh_collective_report(hlo, tmesh)

    l_off, rep_off = run("off")
    l_i8, rep_i8 = run("int8")
    assert_no_cross_slice(rep_i8)
    loss_delta = max(abs(a - b) for a, b in zip(l_off, l_i8))
    ici_delta = rep_i8["ici_bytes"] - rep_off["ici_bytes"]
    ok = ici_delta == 0 and loss_delta < 5e-3 and rep_i8["dcn_bytes"] > 0
    out.update(
        dcn_grad_bytes_fp32=rep_off["dcn_bytes"],
        dcn_grad_bytes_int8=rep_i8["dcn_bytes"],
        dcn_grad_ici_bytes_delta=ici_delta,
        dcn_grad_loss_delta=round(loss_delta, 6),
        dcn_grad_bytes_ratio_x=round(
            rep_off["dcn_bytes"] / rep_i8["dcn_bytes"] if ok else 0.0, 2
        ),
    )
    print(json.dumps(out))
    return out


def bench_cross_node(mb: int = 256, repeats: int = 3) -> dict:
    """2-node broadcast over the direct bulk plane: produce mb on one agent
    node, pull it on another (zero-copy node-to-node; the head serves only
    locations). Reference row: BASELINE.md multi-node broadcast.

    The timer covers ONLY the consumer-side pull (submit + pull + reply):
    producing the array and sealing it into the source slab happen before
    t0 (a `settle` task on the producer node returns once the object is
    resolvable there). Each repeat produces a FRESH object — pulled
    buffers cache on the consumer node, so re-pulling would time a local
    shm hit, not the plane."""
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    out = {}
    n = mb * 1024 * 1024
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 0})
    try:
        cluster.add_node(num_cpus=2, resources={"src": 1})
        cluster.add_node(num_cpus=2, resources={"dst": 1})

        @ray_tpu.remote(resources={"src": 0.1})
        def produce(i):
            return np.ones(n, dtype=np.uint8)

        @ray_tpu.remote(resources={"src": 0.1})
        def settle(x):
            # materializes on the PRODUCING node (local shm, no wire):
            # returns only once the object is sealed and resolvable there
            return len(x)

        @ray_tpu.remote(resources={"dst": 0.1})
        def consume(x):
            return int(x[0]) + len(x)

        # warm: placement + worker spawn on both nodes + peer resolution
        ray_tpu.get(consume.remote(produce.remote(-1)), timeout=180)

        best = 0.0
        for i in range(repeats):
            ref = produce.remote(i)
            ray_tpu.get(settle.remote(ref), timeout=180)
            t0 = time.perf_counter()
            assert ray_tpu.get(consume.remote(ref), timeout=180) == 1 + n
            dt = time.perf_counter() - t0
            best = max(best, mb / 1024 / dt)
        out["cross_node_256mb_gbps"] = round(best, 2)

        # striping sub-metric, wire-only: the DRIVER pulls over real bulk
        # sockets (same-host slab attach off) with 1 socket vs the stripe
        # fan-out. Informational, ungated: on a single-core host both
        # stripes contend for the same CPU so ~1.0x is expected; the
        # fan-out pays off with a NIC per host.
        try:
            speedup, wire_gbps = _cross_node_striped_speedup(
                mb, produce, settle
            )
            out["cross_node_striped_speedup_x"] = round(speedup, 2)
            out["cross_node_wire_gbps"] = round(wire_gbps, 2)
        except Exception as e:
            print(f"[microbench] striped sub-metric unavailable: {e!r}",
                  file=sys.stderr)
    finally:
        cluster.shutdown()
    return out


def _cross_node_striped_speedup(mb, produce, settle):
    import ray_tpu
    from ray_tpu._private import serialization
    from ray_tpu._private.config import GLOBAL_CONFIG as cfg
    from ray_tpu._private.worker import global_worker

    def wire_pull_gbps(ref, stripe_sockets):
        env = global_worker.request(
            {"t": "get_objects", "object_ids": [ref.id]}
        )[0]
        refs = serialization.shm_buffer_refs(env)
        cfg.apply({
            "bulk_same_host": False,
            "bulk_stripe_sockets": stripe_sockets,
            "bulk_stripe_min_bytes": 32 * 1024 * 1024,
        })
        t0 = time.perf_counter()
        got = global_worker.fetch_buffers_direct(refs[0].node, refs)
        dt = time.perf_counter() - t0
        if got is None or any(v is None for v in got.values()):
            raise RuntimeError("direct wire pull failed")
        return mb / 1024 / dt

    try:
        r1 = produce.remote(1001)
        ray_tpu.get(settle.remote(r1), timeout=180)
        rn = produce.remote(1002)
        ray_tpu.get(settle.remote(rn), timeout=180)
        single = wire_pull_gbps(r1, 1)
        striped = wire_pull_gbps(rn, 4)
        return striped / single, single
    finally:
        cfg.apply({
            "bulk_same_host": True,
            "bulk_stripe_sockets": 4,
            "bulk_stripe_min_bytes": 64 * 1024 * 1024,
        })


def bench_cross_node_gbps(mb: int = 256) -> float:
    return bench_cross_node(mb)["cross_node_256mb_gbps"]


def bench_head_stress(n_tasks: int = 0, n_actors: int = 0) -> dict:
    """Head scale envelope (reference: release/benchmarks many_tasks /
    many_actors): ingest n_tasks QUEUED tasks + n_actors pending actors
    through one head; report ingest rates and control-loop latency under
    the backlog. Runs in its own cluster with the direct task path off so
    every submit lands in the head's queue.

    Default sizes scale with the host: the full 100k/1k envelope on >=8
    cores, proportionally smaller on tiny hosts (a 1-core box takes ~15
    min for the full envelope — rates are what matter, and they are
    per-core properties; tests/test_stress.py pins the absolute envelope)."""
    import ray_tpu
    from ray_tpu._private.worker import global_worker

    cpus = os.cpu_count() or 1
    scale = min(1.0, max(0.2, cpus / 8))
    n_tasks = n_tasks or int(100_000 * scale)
    n_actors = n_actors or int(1_000 * scale)
    ray_tpu.init(num_cpus=2, _system_config={"direct_task_calls": False})
    try:
        @ray_tpu.remote(resources={"never": 1.0})
        def blocked():
            return 1

        @ray_tpu.remote(resources={"never": 1.0})
        class Pending:
            pass

        def ping_ms(n=20):
            t0 = time.perf_counter()
            for _ in range(n):
                global_worker.request({"t": "ping"})
            return (time.perf_counter() - t0) / n * 1000

        base_ms = ping_ms()
        t0 = time.perf_counter()
        refs = [blocked.remote() for _ in range(n_tasks)]
        submit_s = time.perf_counter() - t0
        deadline = time.time() + 300
        while time.time() < deadline:
            if global_worker.request({"t": "task_count"}) >= n_tasks:
                break
            time.sleep(1.0)
        ingest_s = time.perf_counter() - t0
        under_ms = ping_ms()
        t0 = time.perf_counter()
        actors = [Pending.remote() for _ in range(n_actors)]
        actors_s = time.perf_counter() - t0
        out = {
            "stress_tasks_submitted": n_tasks,
            "stress_submit_per_s": round(n_tasks / submit_s, 1),
            "stress_ingest_per_s": round(n_tasks / ingest_s, 1),
            "stress_ping_ms_baseline": round(base_ms, 2),
            "stress_ping_ms_under_load": round(under_ms, 2),
            "stress_ping_ms_under_load_and_actors": round(ping_ms(), 2),
            "stress_actor_creates_per_s": round(n_actors / actors_s, 1),
        }
        del refs, actors
        return out
    finally:
        ray_tpu.shutdown()


# every gate in one table: metric -> (op, target). Targets may be
# callables of the results dict (floor-relative: put/cross-node derive
# from the host's measured memcpy floor). Both the full supervisor and
# the --only selector judge from HERE, so a bound cannot drift between
# the sweep and the targeted CI step.
GATES = {
    "task_submit_per_s": (">=", 5000.0),
    "actor_calls_sync_per_s": (">=", 2500.0),
    # put pays exactly one copy: on hosts whose single-core memcpy floor
    # is below 12.5 GB/s the absolute 10 GB/s is unreachable by
    # construction — the honest target is ~75% of the floor, capped
    "put_100mb_gbps": (">=", lambda r: min(10.0, 0.75 * r["host_memcpy_gbps"])),
    # cross-node pull pays at most ONE host copy on the zero-copy bulk
    # plane (slab-attach or recv-into-slab), so half the single-thread
    # memcpy floor is the honest bound — copy time plus an equal budget
    # for dispatch/seal/teardown (ROADMAP item 3 landed: was an
    # anti-regression floor of min(0.15, 0.02x) while pulls were
    # chunk-copied through the head relay)
    "cross_node_256mb_gbps": (">=", lambda r: 0.5 * r["host_memcpy_gbps"]),
    # batched KV-cache decode must beat serial per-request decode: the
    # continuous-batching serving fast path (both engines run PAGED)
    "decode_batched_speedup_x": (">=", 2.0),
    # a prefix-cache hit must beat the cold prefill of the same prompt
    "prefix_hit_speedup_x": (">=", 2.0),
    # a CROSS-REPLICA prefix hit (export -> pack -> wire-check -> unpack
    # -> import on a peer engine) must still beat recomputing the prefill
    # locally; greedy identity is asserted in-row — divergence zeroes the
    # metric. --only row, not part of the full-sweep trials (see `gated`)
    "cross_replica_prefix_hit_speedup_x": (">=", 1.5),
    # block-in-place paged attention must beat the block-table gather at
    # the same dtype in the long-context (bandwidth-bound) decode regime
    "decode_long_context_fused_speedup_x": (">=", 1.1),
    # int8 KV blocks must ~double pool capacity per byte
    "kv_int8_blocks_ratio": (">=", 1.8),
    # one k+1-token speculative verify step must beat the k+1
    # single-token steps it replaces at low batch (perfect-draft harness)
    "spec_decode_speedup_x": (">=", 1.5),
    # the multi-query fused verify must AT LEAST match the gather-window
    # verify at long context (measured ~1.9x on CPU at 4k ctx) — before
    # ISSUE 13, speculation re-paid the gather cost fused decode saved
    "spec_verify_fused_speedup_x": (">=", 1.0),
    # chunked prefill: decode p99 inter-token latency while a 4k prompt
    # streams in chunks stays BOUNDED vs the decode-only baseline (one
    # chunk's compute, ~25x a tiny-batch CPU decode step; a scheduler
    # regression — chunks coalescing, whole-prefill fallback — is 10x+)
    "decode_mixed_p99_ratio_x": ("<=", 50.0),
    # ... and must cut the whole-prompt head-of-line spike by >= 4x
    "decode_chunk_stall_reduction_x": (">=", 4.0),
    # the telemetry plane (per-token request metrics + flight recorder)
    # must cost at most a few percent of decode throughput — telemetry-on
    # tokens/s over telemetry-off on the identical batcher loop
    "decode_telemetry_overhead_ratio_x": (">=", 0.95),
    # interleaved-1F1B (--only train_dcn_plane row, 8-device child): the
    # pipeline bubble must shrink >= 1.3x vs GPipe at the measured shape,
    # and the ratio is zeroed unless the schedule matches the sequential
    # oracle AND adds zero dcn-crossing hops (the v multiplier rides ICI)
    "pipeline_bubble_reduction_x": (">=", 1.3),
    # int8+error-feedback dcn gradient exchange: >= 3.5x fewer
    # slice-boundary bytes than the fp32 all-reduce (~3.93 @ block=256),
    # zeroed unless ICI bytes are untouched and the loss tracks fp32
    "dcn_grad_bytes_ratio_x": (">=", 3.5),
    # live weight hot-swap (--only serve_weight_swap row): decode p99
    # inter-token latency with a publish->pull->preempt->recompute swap
    # landing mid-generation stays within 10x the quiescent p99; zeroed
    # to 999 if any stream drops or comes back short of its token budget
    "weight_swap_p99_ratio_x": ("<=", 10.0),
}


def _gate_ok(metric: str, value: float, target: float) -> bool:
    op = GATES[metric][0]
    return value <= target if op == "<=" else value >= target


def _run_trial() -> dict:
    """One fresh-process trial of the GATED metrics + this trial's own
    environment noise floor (memcpy) — so every rate ships with the host
    condition it was measured under."""
    import ray_tpu

    out = {"host_memcpy_gbps": round(host_memcpy_gbps(), 2)}
    # decode runs BEFORE ray init: jax (CPU) claims its arena in a clean
    # process, and the cluster's workers never contend with the jit warmup
    out.update(bench_decode_speedup())
    out.update(bench_decode_long_context())
    out.update(bench_decode_speculative())
    out.update(bench_decode_mixed_traffic())
    out.update(bench_decode_telemetry_overhead())
    out.update(bench_decode_spec_realtext())
    out.update(bench_prefix_hit())
    ray_tpu.init()
    out["task_submit_per_s"] = round(bench_task_submit(), 1)
    out["actor_calls_sync_per_s"] = round(bench_actor_sync(), 1)
    out["put_100mb_gbps"] = round(bench_put_gbps(), 2)
    ray_tpu.shutdown()
    print(json.dumps(out))
    return out


def main():
    """Self-certifying supervisor (VERDICT r4 #5): the gated metrics run as
    N FRESH child processes (one cluster each); targets_met is computed
    from the per-metric MEDIANS, so a single host-throttled trial cannot
    fail — or pass — the artifact on its own. Each trial records its own
    memcpy noise floor; the put target derives from the median floor."""
    import gc
    import statistics
    import subprocess

    n_trials = int(os.environ.get("RAY_TPU_MICROBENCH_TRIALS", "5"))
    # every GATES entry is trial-gated except cross-node (needs its own
    # 2-node cluster, measured once in THIS process), the cross-replica
    # transfer row, and the train DCN-plane row (dedicated --only CI
    # steps; the latter spawns its own 8-device jax child) — derived, not
    # hand-listed, so a new gate cannot be silently dropped from the
    # sweep's judgment
    gated = tuple(
        k for k in GATES
        if k not in ("cross_node_256mb_gbps",
                     "cross_replica_prefix_hit_speedup_x",
                     "pipeline_bubble_reduction_x",
                     "dcn_grad_bytes_ratio_x",
                     "weight_swap_p99_ratio_x")
    )
    expected = set(gated) | {"host_memcpy_gbps"}
    trials = []
    # trial 0 is a WARMUP, discarded: it faults in the interpreter/page
    # cache and brings the CPU governor up, which is where most of the
    # historical put_100mb_gbps spread (2.49-7.25 GB/s across trials) came
    # from. Between trials the parent quiesces — gc + a short settle — so
    # one trial's teardown (worker reaping, slab unmap) doesn't bleed into
    # the next trial's timed loops.
    for i in range(n_trials + 1):
        if i:
            gc.collect()
            time.sleep(0.75)
        # the decode metric needs a jax backend; microbench is a CORE
        # runtime artifact, so a trial child must never claim a TPU — force
        # CPU even when the operator's shell exports JAX_PLATFORMS=tpu
        env = dict(os.environ, RAY_TPU_MICROBENCH_CHILD="trial",
                   JAX_PLATFORMS="cpu")
        try:
            proc = subprocess.run(
                [sys.executable, sys.argv[0]], env=env, capture_output=True,
                text=True,
                # ISSUE 13 grew each trial by the verify-longctx + mixed-
                # traffic phases (~2 min extra on a 1-core host)
                timeout=int(os.environ.get(
                    "RAY_TPU_MICROBENCH_TRIAL_TIMEOUT_S", "900"
                )),
            )
        except subprocess.TimeoutExpired:
            # one hung (host-throttled) trial must not sink the artifact —
            # the medians over the remaining trials still certify it
            print(f"[microbench] trial {i} timed out; skipping", file=sys.stderr)
            continue
        if i == 0:
            continue  # warmup: result discarded
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and expected <= set(obj):
                trials.append(obj)
            break
        else:
            print(f"[microbench] trial {i} produced no JSON: "
                  f"{proc.stderr[-500:]}", file=sys.stderr)
    if not trials:
        print(json.dumps({"targets_met": False, "error": "no trials completed"}))
        return {"targets_met": False}

    results = {"host_cpus": os.cpu_count(), "n_trials": len(trials)}
    for k in gated + ("host_memcpy_gbps", "decode_batched_tokens_per_s",
                      "decode_serial_tokens_per_s", "prefix_hit_cold_ms",
                      "prefix_hit_ms", "decode_long_context_tokens_per_s",
                      "decode_long_context_gather_tokens_per_s",
                      "decode_long_context_fused_fp_tokens_per_s",
                      "decode_long_context_int8_speedup_x",
                      "spec_off_tokens_per_s", "spec_on_tokens_per_s",
                      "spec_accept_rate", "spec_greedy_identical",
                      "spec_verify_ctx_tokens", "spec_verify_engaged",
                      "spec_verify_gather_tokens_per_s",
                      "spec_verify_fused_tokens_per_s",
                      "mixed_traffic_prompt_tokens",
                      "mixed_traffic_chunk_tokens",
                      "decode_only_p99_ms", "decode_mixed_p99_ms",
                      "whole_prompt_stall_ms",
                      "decode_telemetry_on_tokens_per_s",
                      "decode_telemetry_off_tokens_per_s",
                      "spec_realtext_available",
                      "spec_accept_rate_realtext",
                      "spec_tokens_per_step_realtext"):
        vals = [t[k] for t in trials]
        results[k] = round(statistics.median(vals), 2)
        results[k + "_spread"] = round(
            statistics.pstdev(vals) if len(vals) > 1 else 0.0, 2
        )
    results["trials"] = trials

    # one pass of the informational (non-gated) metrics in THIS process
    import ray_tpu

    ray_tpu.init()
    results["task_roundtrip_per_s"] = round(bench_task_roundtrip(), 1)
    results["actor_calls_async_per_s"] = round(bench_actor_async(), 1)
    results["get_100mb_gbps"] = round(bench_get_gbps(), 2)
    results["broadcast_10mb_16actors_ms"] = round(bench_weight_broadcast_ms(), 1)
    ray_tpu.shutdown()
    results.update(bench_cross_node())
    results.update(bench_head_stress())

    # targets resolve from the shared GATES table (floor-relative ones —
    # put, cross-node — derive from the MEDIAN memcpy floor: floor and
    # rate come from the same trials, so no minutes-apart drift; the gate
    # rationale lives next to each entry in GATES)
    targets = {
        k: (v(results) if callable(v) else v)
        for k, (_, v) in GATES.items()
        if k in gated or k == "cross_node_256mb_gbps"
    }
    results["put_target_gbps"] = round(targets["put_100mb_gbps"], 2)
    results["cross_node_target_gbps"] = round(
        targets["cross_node_256mb_gbps"], 3
    )
    results["targets"] = {k: round(v, 2) for k, v in targets.items()}
    results["targets_met"] = all(
        _gate_ok(k, results[k], v) for k, v in targets.items()
    )
    print(json.dumps(results))
    return results


# --------------------------------------------------------------------------
# --only: a named row as a targeted CI step
# --------------------------------------------------------------------------

# row name -> (metrics fn, needs a ray cluster, GATES entries the row's
# metrics are judged by). Derived targets pull the memcpy floor in
# automatically. One in-process pass — the fresh-process median-of-N
# discipline belongs to the full supervisor; a targeted CI step wants one
# honest measurement and a hard exit code.
ROWS = {
    "decode_speedup": (bench_decode_speedup, False,
                       ("decode_batched_speedup_x",)),
    "decode_long_context": (bench_decode_long_context, False,
                            ("decode_long_context_fused_speedup_x",
                             "kv_int8_blocks_ratio")),
    "decode_speculative": (bench_decode_speculative, False,
                           ("spec_decode_speedup_x",
                            "spec_verify_fused_speedup_x")),
    "decode_mixed_traffic": (bench_decode_mixed_traffic, False,
                             ("decode_mixed_p99_ratio_x",
                              "decode_chunk_stall_reduction_x")),
    "decode_spec_realtext": (bench_decode_spec_realtext, False, ()),
    "decode_telemetry_overhead": (bench_decode_telemetry_overhead, False,
                                  ("decode_telemetry_overhead_ratio_x",)),
    "prefix_hit": (bench_prefix_hit, False, ("prefix_hit_speedup_x",)),
    "serve_cross_replica": (bench_serve_cross_replica, False,
                            ("cross_replica_prefix_hit_speedup_x",)),
    "serve_weight_swap": (bench_serve_weight_swap, True,
                          ("weight_swap_p99_ratio_x",)),
    "train_dcn_plane": (bench_train_dcn_plane, False,
                        ("pipeline_bubble_reduction_x",
                         "dcn_grad_bytes_ratio_x")),
    "task_submit": (lambda: {"task_submit_per_s": round(bench_task_submit(), 1)},
                    True, ("task_submit_per_s",)),
    "actor_sync": (lambda: {"actor_calls_sync_per_s": round(bench_actor_sync(), 1)},
                   True, ("actor_calls_sync_per_s",)),
    "put": (lambda: {"put_100mb_gbps": round(bench_put_gbps(), 2)},
            True, ("put_100mb_gbps",)),
    # needs_ray=None: the row manages its OWN ray lifecycle (head_stress
    # calls init with a custom system config; cross_node builds a
    # Cluster) — run_only must release any shared cluster first, or the
    # row's init raises "called twice"
    "cross_node": (bench_cross_node, None, ("cross_node_256mb_gbps",)),
    "head_stress": (bench_head_stress, None, ()),
}


def run_only(names) -> bool:
    """Run the named row(s) in THIS process, judge exactly their gates,
    print one JSON object, return pass/fail (the exit code)."""
    unknown = [n for n in names if n not in ROWS]
    if unknown:
        print(f"[microbench] unknown row(s) {unknown}; "
              f"available: {sorted(ROWS)}", file=sys.stderr)
        return False
    results = {"host_cpus": os.cpu_count(), "rows": list(names)}
    needs_floor = any(
        callable(GATES[g][1])
        for n in names for g in ROWS[n][2]
    )
    if needs_floor:
        results["host_memcpy_gbps"] = round(host_memcpy_gbps(), 2)
    inited = False
    import ray_tpu

    try:
        for n in names:
            fn, needs_ray, _ = ROWS[n]
            if needs_ray and not inited:
                ray_tpu.init()
                inited = True
            elif needs_ray is None and inited:
                # row manages its own cluster: hand the runtime back
                ray_tpu.shutdown()
                inited = False
            results.update(fn())
    finally:
        if inited:
            ray_tpu.shutdown()
    checked, ok = {}, True
    for n in names:
        for g in ROWS[n][2]:
            if g not in results:
                # a row that stopped emitting its gated metric must FAIL
                # the targeted step, not silently pass with no judgment
                checked[g] = {"missing": True, "passed": False}
                ok = False
                continue
            op, tgt = GATES[g]
            tgt = tgt(results) if callable(tgt) else tgt
            passed = _gate_ok(g, results[g], tgt)
            checked[g] = {"value": results[g], "op": op,
                          "target": round(tgt, 3), "passed": passed}
            ok = ok and passed
    results["gates"] = checked
    results["targets_met"] = ok
    print(json.dumps(results))
    return ok


if __name__ == "__main__":
    if os.environ.get("RAY_TPU_MICROBENCH_CHILD") == "trial":
        _run_trial()
        sys.exit(0)
    if os.environ.get("RAY_TPU_MICROBENCH_CHILD") == "train_dcn_plane":
        _train_dcn_plane_child()
        sys.exit(0)
    if "--only" in sys.argv:
        # targeted CI step: `microbench.py --only decode_mixed_traffic`
        # (comma-separate for several rows) runs just those rows, judges
        # just their gates, and exits nonzero on any failure. Defaults to
        # CPU like the trial children (set before any row imports jax);
        # an explicit JAX_PLATFORMS export wins.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        idx = sys.argv.index("--only")
        if idx + 1 >= len(sys.argv):
            print(f"usage: {sys.argv[0]} --only <row>[,<row>...]; "
                  f"rows: {sorted(ROWS)}", file=sys.stderr)
            sys.exit(2)
        names = [n for n in sys.argv[idx + 1].split(",") if n]
        sys.exit(0 if run_only(names) else 1)
    sys.exit(0 if main()["targets_met"] else 1)
