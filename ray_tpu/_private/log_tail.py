"""Shared worker-log tailing used by the head and node agents.

Reference parity: _private/log_monitor.py — tail per-process log files and
forward increments for driver printing. One implementation serves both the
head's local tail loop and each agent's forward loop so the chunking /
offset semantics can't drift.
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

_CHUNK = 256 * 1024
_SUFFIX = ".out"


def _log_files(log_dir: str) -> List[str]:
    try:
        return [n for n in os.listdir(log_dir) if n.endswith(_SUFFIX)]
    except OSError:
        return []


def fast_forward(log_dir: str, offsets: Dict[str, int]) -> None:
    """Advance offsets to the current file ends WITHOUT reading content —
    used at startup and across unsubscribed gaps so a (re)subscribing
    driver gets live output, not a megabyte backlog dump."""
    for name in _log_files(log_dir):
        try:
            offsets[name] = os.path.getsize(os.path.join(log_dir, name))
        except OSError:
            pass


def read_increments(log_dir: str, offsets: Dict[str, int]) -> List[Tuple[str, str]]:
    """New content per worker since the recorded offsets:
    [(worker_id, text)], at most _CHUNK bytes per file per call.

    Emits only COMPLETE lines: a partially-written trailing line (or a
    multi-byte UTF-8 character straddling the chunk edge) stays in the file
    for the next call — splitting it would print corrupted half-lines in
    the driver (the reference log monitor buffers to newlines the same
    way). A full newline-free chunk is emitted as-is so one giant line
    can't stall the tail forever."""
    out: List[Tuple[str, str]] = []
    for name in _log_files(log_dir):
        path = os.path.join(log_dir, name)
        try:
            size = os.path.getsize(path)
            pos = offsets.get(name, 0)
            if size <= pos:
                continue
            with open(path, "rb") as f:
                f.seek(pos)
                data = f.read(_CHUNK)
            if len(data) < _CHUNK:
                cut = data.rfind(b"\n") + 1
                if cut == 0:
                    continue  # no complete line yet; retry next tick
                data = data[:cut]
            offsets[name] = pos + len(data)
            out.append((name[: -len(_SUFFIX)], data.decode(errors="replace")))
        except OSError:
            continue
    return out
