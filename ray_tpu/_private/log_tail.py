"""Shared worker-log tailing used by the head and node agents.

Reference parity: _private/log_monitor.py — tail per-process log files and
forward increments for driver printing. One implementation serves both the
head's local tail loop and each agent's forward loop so the chunking /
offset semantics can't drift.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

_CHUNK = 256 * 1024
_SUFFIX = ".out"


def _log_files(log_dir: str) -> List[str]:
    try:
        return [n for n in os.listdir(log_dir) if n.endswith(_SUFFIX)]
    except OSError:
        return []


def fast_forward(log_dir: str, offsets: Dict[str, int]) -> None:
    """Advance offsets to the current file ends WITHOUT reading content —
    used at startup and across unsubscribed gaps so a (re)subscribing
    driver gets live output, not a megabyte backlog dump."""
    for name in _log_files(log_dir):
        try:
            offsets[name] = os.path.getsize(os.path.join(log_dir, name))
        except OSError:
            pass


_FLUSH_PARTIAL_AFTER_S = 1.0


def read_increments(
    log_dir: str,
    offsets: Dict[str, int],
    pending: Optional[Dict[str, Tuple[int, float]]] = None,
) -> List[Tuple[str, str]]:
    """New content per worker since the recorded offsets:
    [(worker_id, text)], at most _CHUNK bytes per file per call.

    Emits COMPLETE lines: a partially-written trailing line (or a
    multi-byte UTF-8 character straddling the chunk edge) is held back —
    splitting it would print corrupted half-lines in the driver (the
    reference log monitor buffers to newlines the same way). Two escape
    hatches keep output flowing: a held partial line that stops growing
    for ~1s is flushed anyway (a crashed worker's final un-terminated
    diagnostic must not be withheld forever), and a newline-free chunk of
    the full _CHUNK size is emitted whole (one giant line must not stall
    the tail). Callers pass a persistent `pending` dict for the
    stale-partial tracking."""
    import time

    out: List[Tuple[str, str]] = []
    if pending is None:
        pending = {}
    for name in _log_files(log_dir):
        path = os.path.join(log_dir, name)
        try:
            size = os.path.getsize(path)
            pos = offsets.get(name, 0)
            if size <= pos:
                pending.pop(name, None)
                continue
            with open(path, "rb") as f:
                f.seek(pos)
                data = f.read(_CHUNK)
            cut = data.rfind(b"\n") + 1
            if cut < len(data):
                # trailing partial line: trim it off — unless the file has
                # stopped growing (crash tail) or the whole chunk is one
                # giant newline-free line
                seen = pending.get(name)
                stale = (
                    seen is not None
                    and seen[0] == size
                    and time.monotonic() - seen[1] >= _FLUSH_PARTIAL_AFTER_S
                )
                if not stale and not (cut == 0 and len(data) == _CHUNK):
                    if seen is None or seen[0] != size:
                        pending[name] = (size, time.monotonic())
                    data = data[:cut]
                    if not data:
                        continue  # partial only; wait (or flush when stale)
                else:
                    pending.pop(name, None)
            else:
                pending.pop(name, None)
            offsets[name] = pos + len(data)
            out.append((name[: -len(_SUFFIX)], data.decode(errors="replace")))
        except OSError:
            continue
    return out
