"""Node: starts and supervises the in-driver head service.

Reference parity: python/ray/_private/node.py (Node.start_head_processes) —
but where the reference spawns separate gcs_server/raylet daemons, ray_tpu
hosts the head service on a background asyncio thread of the driver process
(see head.py for why this is the right shape on a TPU host).
"""

from __future__ import annotations

import glob
import os
import shutil
import time
import uuid
from typing import Dict, Optional

from .config import GLOBAL_CONFIG as cfg
from .head import Head
from .worker import EventLoopThread


def detect_tpu_chips() -> int:
    """Count local TPU chips without importing jax (device files on TPU VMs)."""
    n = len(glob.glob("/dev/accel*"))
    if n:
        return n
    if os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS"):
        try:
            return int(os.environ["TPU_CHIPS_PER_HOST_BOUNDS"].split(",")[-1])
        except ValueError:
            pass
    return 0


def default_resources(num_cpus=None, num_tpus=None, resources=None) -> Dict[str, float]:
    out: Dict[str, float] = {}
    out["CPU"] = float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))
    tpus = num_tpus if num_tpus is not None else detect_tpu_chips()
    if tpus:
        out["TPU"] = float(tpus)
    out["memory"] = float(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES"))
    out["node:__internal_head__"] = 1.0
    if resources:
        out.update({k: float(v) for k, v in resources.items()})
    return out


def _snapshot_session_id(target: str):
    """The session id recorded in a head snapshot (None if unreadable).
    `target` may name any snapshot store (file path, sqlite://, gs://)."""
    import pickle

    from .snapshot_store import store_for

    try:
        data = store_for(target).load()
        return pickle.loads(data).get("session_id") if data else None
    except Exception:
        return None


class Node:
    def __init__(self, resources: Dict[str, float]):
        self.session_id = f"session_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:8]}"
        if cfg.head_restore_path:
            # restoring = resuming the SAME logical cluster: adopt the
            # snapshot's session id so surviving agents/workers (whose shm
            # planes, scratch dirs and sockets are keyed by session)
            # re-register instead of being orphaned
            sid = _snapshot_session_id(cfg.head_restore_path)
            if sid:
                self.session_id = sid
        self.session_dir = os.path.join(cfg.session_dir_root, self.session_id)
        os.makedirs(self.session_dir, exist_ok=True)
        self.socket_path = os.path.join(self.session_dir, "head.sock")
        self.io = EventLoopThread()
        self.head = Head(self.session_dir, resources)
        self.io.run(self.head.start())
        self._stopped = False

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        try:
            self.io.run(self.head.stop(), timeout=10)
        except Exception:
            pass
        self.io.stop()
        shutil.rmtree(self.session_dir, ignore_errors=True)
