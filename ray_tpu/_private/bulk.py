"""Node-to-node bulk object plane: blocking slab-to-socket senders.

Reference parity: object_manager.h:117 chunked push/pull between object
managers. The wire format is RAW (no pickle, no per-chunk framing):

    request = <B op> <Q name_len> name [<Q offset> <Q length> for READ_RANGE]
    reply   = <q n> (+ n raw bytes for READ / READ_RANGE)

ops: INFO=1 (reply is the buffer size), READ=2 (whole buffer), READ_RANGE=3
(a byte range — the striping primitive: one 256MB pull fans out across N
sockets, each asking for a disjoint range). Negative replies: -1 = buffer
unknown on this node, -2 = bad range.

Serving runs on dedicated blocking threads doing sock.sendall straight from
the shm mapping (os.sendfile for spilled buffers) — no asyncio transport
copy, no contention with the agent's control-plane event loop. Consumers
read with blocking sockets + recv_into preallocated slab views, so a direct
pull costs at most one host copy (kernel-to-slab).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
from typing import Dict, Optional

from . import faults
from .config import GLOBAL_CONFIG as cfg

OP_INFO = 1
OP_READ = 2
OP_READ_RANGE = 3

MISSING = -1
BAD_RANGE = -2

_MAX_NAME = 4096
_HDR = struct.Struct("<BQ")
_RANGE = struct.Struct("<QQ")
_REPLY = struct.Struct("<q")

# Process-local serving stats (tests + debugging), PLANE_STATS pattern.
BULK_STATS: Dict[str, int] = {
    "requests": 0,
    "range_requests": 0,
    "bytes_sent": 0,
    "sendfile_bytes": 0,
    "faults_close": 0,
    "faults_blackhole": 0,
}
_STATS_LOCK = threading.Lock()


def _stat(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        BULK_STATS[key] = BULK_STATS.get(key, 0) + n


def reset_bulk_stats() -> None:
    with _STATS_LOCK:
        for k in list(BULK_STATS):
            BULK_STATS[k] = 0


def account(path: str, nbytes: int) -> None:
    """Consumer-side transfer accounting: one pull of `nbytes` over
    `path` (direct | striped | relay | spilled). Never breaks a pull."""
    try:
        from ray_tpu.util import metrics as _m

        _m.bulk_plane_bytes_counter().inc(nbytes, tags={"path": path})
        _m.bulk_plane_pulls_counter().inc(tags={"path": path})
    except Exception:
        pass


# ---------------------------------------------------------------------------
# client-side helpers (worker pull path + microbench share these)
# ---------------------------------------------------------------------------


def recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Fill `view` (writable, contiguous bytes) from the socket — lands
    bytes straight in the caller's buffer (a slab view on the pull path)."""
    got = 0
    size = view.nbytes
    while got < size:
        n = sock.recv_into(view[got:])
        if n == 0:
            raise ConnectionError("bulk peer closed mid-stream")
        got += n


def recv_exact(sock: socket.socket, size: int) -> bytearray:
    buf = bytearray(size)
    if size:
        recv_exact_into(sock, memoryview(buf))
    return buf


def pack_request(op: int, name: str, offset: int = 0, length: int = 0) -> bytes:
    nb = name.encode()
    req = _HDR.pack(op, len(nb)) + nb
    if op == OP_READ_RANGE:
        req += _RANGE.pack(offset, length)
    return req


def read_reply_size(sock: socket.socket) -> int:
    return _REPLY.unpack(bytes(recv_exact(sock, 8)))[0]


def read_info(sock: socket.socket, name: str) -> int:
    sock.sendall(pack_request(OP_INFO, name))
    return read_reply_size(sock)


def read_range_into(
    sock: socket.socket, name: str, offset: int, view: memoryview
) -> int:
    """Pull `view.nbytes` bytes of `name` starting at `offset` straight into
    `view`. Returns the (negative) reply code without touching the view when
    the server can't serve the range."""
    sock.sendall(pack_request(OP_READ_RANGE, name, offset, view.nbytes))
    n = read_reply_size(sock)
    if n < 0:
        return n
    if n != view.nbytes:
        raise ConnectionError(
            f"bulk peer served {n} bytes for a {view.nbytes}-byte range"
        )
    recv_exact_into(sock, view)
    return n


def connect(addr: str, timeout_s: Optional[float] = None) -> socket.socket:
    """Dial a peer's bulk server with the tuned socket options (deep receive
    buffer before connect so the kernel honors it, NODELAY for the small
    request frames, bounded timeout so a blackholed peer can't hang pulls)."""
    host, port_s = addr.rsplit(":", 1)
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8 * 1024 * 1024)
    except OSError:
        pass
    sock.settimeout(
        timeout_s if timeout_s is not None else cfg.bulk_read_timeout_s
    )
    sock.connect((host, int(port_s)))
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class BulkServer:
    """Threaded TCP listener serving one node's shm plane to peers.

    `shm_client_fn` is called lazily per request (the agent's shm client is
    created on first use, after the session handshake)."""

    def __init__(self, shm_client_fn, bind_host: str):
        self._shm_client_fn = shm_client_fn
        self._bind_host = bind_host
        self._lsock: Optional[socket.socket] = None
        self._stopping = False
        self.port = 0

    def start(self) -> int:
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((self._bind_host, 0))
        lsock.listen(128)
        self._lsock = lsock
        self.port = lsock.getsockname()[1]
        threading.Thread(
            target=self._accept_loop, name="bulk-accept", daemon=True
        ).start()
        return self.port

    def stop(self) -> None:
        self._stopping = True
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), name="bulk-send", daemon=True
            ).start()

    # -- per-connection handler (dedicated blocking sender thread) ----------

    def _serve(self, conn: socket.socket) -> None:
        try:
            # deep send buffer: throughput on busy hosts is bounded by
            # sender/receiver scheduling ping-pong; big kernel buffers
            # amortize the context switches
            try:
                conn.setsockopt(
                    socket.SOL_SOCKET, socket.SO_SNDBUF, 8 * 1024 * 1024
                )
            except OSError:
                pass
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                if not self._serve_one(conn):
                    return
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_one(self, conn: socket.socket) -> bool:
        try:
            hdr = recv_exact(conn, _HDR.size)
        except ConnectionError:
            return False  # peer hung up between requests
        op, nlen = _HDR.unpack(bytes(hdr))
        if nlen > _MAX_NAME:
            return False
        name = bytes(recv_exact(conn, nlen)).decode()
        offset = length = 0
        if op == OP_READ_RANGE:
            offset, length = _RANGE.unpack(bytes(recv_exact(conn, _RANGE.size)))
        elif op not in (OP_INFO, OP_READ):
            return False
        _stat("requests")
        if op == OP_READ_RANGE:
            _stat("range_requests")

        action = faults.bulk_action() if faults.ACTIVE else None
        if action == "blackhole":
            # swallow the request, keep the socket open: the consumer's
            # read timeout is what surfaces the loss (partition semantics)
            _stat("faults_blackhole")
            return True

        src = self._resolve(name)
        if src is None:
            conn.sendall(_REPLY.pack(MISSING))
            return True
        kind, obj, size = src
        try:
            if op == OP_INFO:
                conn.sendall(_REPLY.pack(size))
                return True
            if op == OP_READ:
                offset, length = 0, size
            elif offset + length > size:
                conn.sendall(_REPLY.pack(BAD_RANGE))
                return True
            conn.sendall(_REPLY.pack(length))
            if length == 0:
                return True
            limit = offset + length
            if action == "close":
                # mid-stream death: serve about half then drop the socket
                _stat("faults_close")
                limit = offset + max(1, length // 2)
            if kind == "shm":
                self._send_slab(conn, obj, offset, limit)
            else:
                self._sendfile(conn, obj, offset, limit)
            if action == "close":
                conn.close()
                return False
            return True
        finally:
            if kind == "spill":
                obj.close()

    def _resolve(self, name: str):
        """('shm', memoryview, size) | ('spill', open file, size) | None."""
        from .shm import ShmBufferRef

        shm = self._shm_client_fn()
        if shm is None:
            return None
        mv = shm.get(ShmBufferRef(name=name, size=0))
        if mv is not None:
            return ("shm", mv, mv.nbytes)
        try:
            f = open(shm._spill_file(name), "rb")
        except OSError:
            return None
        return ("spill", f, os.fstat(f.fileno()).st_size)

    @staticmethod
    def _send_slab(conn: socket.socket, mv: memoryview, off: int, limit: int):
        """sock.sendall straight from the shm mapping — the kernel copies
        out of the slab pages; no Python-side staging buffer."""
        step = cfg.fetch_chunk_bytes
        sent = 0
        while off < limit:
            n = min(step, limit - off)
            conn.sendall(mv[off : off + n])
            off += n
            sent += n
        _stat("bytes_sent", sent)

    @staticmethod
    def _sendfile(conn: socket.socket, f, off: int, limit: int):
        """Spilled buffers ride os.sendfile: file pages go straight to the
        socket without ever entering userspace."""
        out_fd, in_fd = conn.fileno(), f.fileno()
        sent = 0
        while off < limit:
            n = os.sendfile(out_fd, in_fd, off, min(1 << 26, limit - off))
            if n == 0:
                raise ConnectionError("sendfile hit EOF inside a valid range")
            off += n
            sent += n
        _stat("sendfile_bytes", sent)
        _stat("bytes_sent", sent)
