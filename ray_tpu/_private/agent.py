"""Per-host node agent: the remote half of the control plane.

Reference parity: src/ray/raylet (node_manager.h:117) — the per-node daemon
that registers with the GCS, owns the local worker pool, and serves the
local object plane. ray_tpu's agent is deliberately thinner: scheduling
stays centralized in the head (one scheduler, no resource gossip needed at
TPU-pod scale — tens of hosts, not thousands), so the agent only
  - registers the node + its resources over TCP (ray_syncer / node table),
  - spawns/kills local worker processes on the head's behalf
    (worker_pool.h:420 StartWorkerProcess),
  - serves reads/deletes against the node-local shared-memory object plane
    so the head can pull cross-node dependencies (object_manager.h:117's
    chunked pull, collapsed to request/response over the same framing).

Workers spawned here connect STRAIGHT to the head over TCP — task dispatch
never relays through the agent, keeping the hot path at one hop (the same
reason the reference pushes tasks worker-to-worker, direct_task_transport).
"""

from __future__ import annotations

import asyncio
import os
import shutil
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

from . import protocol
from .config import GLOBAL_CONFIG as cfg

_DEF_GRACE_S = 3.0


class Agent:
    def __init__(
        self,
        head_address: str,
        node_id: str,
        resources: Dict[str, float],
        labels: Optional[Dict[str, str]] = None,
    ):
        self.head_address = head_address
        self.node_id = node_id
        self.resources = resources
        self.labels = labels or {}
        self.conn: protocol.Connection = None  # type: ignore
        self.session: str = ""
        self.scratch_dir: str = ""
        self.shm_session: str = ""
        self._shm = None
        self._shm_tried = False
        self._shm_lock = threading.Lock()
        self.workers: Dict[str, subprocess.Popen] = {}
        self._stop = asyncio.Event()
        self._quit = False  # explicit shutdown (no reconnect attempts)
        self.buffer_addr: str = ""
        self._bulk_server = None

    # ------------------------------------------------------------------

    def _shm_client(self):
        # called from the event loop AND the bulk server's serve threads —
        # the lock keeps a half-initialized None from leaking to a
        # concurrent first caller (stripe pulls arrive N-at-once)
        with self._shm_lock:
            if not self._shm_tried:
                self._shm_tried = True
                from .shm import ShmClient

                try:
                    self._shm = ShmClient(self.shm_session, cfg.shm_store_bytes)
                    self._shm.pretouch_async()  # one pretouch per node slab
                except Exception:
                    self._shm = None
            return self._shm

    async def _start_buffer_server(self) -> str:
        """Start the node-to-node bulk plane (bulk.BulkServer): dedicated
        blocking sender threads doing sock.sendall straight from the shm
        mapping (os.sendfile for spilled buffers) — off this event loop, so
        a 256MB pull never contends with control-plane handlers. The head
        only hands out locations; object bytes never relay through it
        (reference: object_manager.h:117 chunked push/pull)."""
        from .bulk import BulkServer

        # honor the cluster's bind policy: the control plane's bind host
        # (head_tcp_host) decides whether this unauthenticated plane is
        # loopback-only or LAN-exposed — serving raw object bytes on all
        # interfaces of a loopback-configured cluster would leak data
        bind = cfg.head_tcp_host or "0.0.0.0"
        self._bulk_server = BulkServer(self._shm_client, bind)
        port = self._bulk_server.start()
        from .head import _advertise_host

        return f"{_advertise_host(bind)}:{port}"

    async def _connect_and_register(self) -> dict:
        reader, writer = await protocol.open_stream(self.head_address)
        self.conn = protocol.Connection(reader, writer, self.handle, self._on_close)
        self.conn.start()
        return await self.conn.request(
            {
                "t": "register_node",
                "proto": protocol.PROTOCOL_VERSION,
                "node_id": self.node_id,
                "resources": self.resources,
                "labels": self.labels,
                "buffer_addr": self.buffer_addr,
            }
        )

    async def run(self):
        self.buffer_addr = await self._start_buffer_server()
        info = await self._connect_and_register()
        self.session = info["session"]
        self.shm_session = f"{self.session}_{self.node_id}"
        self.scratch_dir = os.path.join(
            cfg.session_dir_root, self.session, "nodes", self.node_id
        )
        os.makedirs(self.scratch_dir, exist_ok=True)
        aux_tasks = []
        if cfg.memory_monitor_refresh_ms > 0:
            aux_tasks.append(
                asyncio.get_running_loop().create_task(self._memory_loop())
            )
        if cfg.log_to_driver:
            aux_tasks.append(
                asyncio.get_running_loop().create_task(self._log_forward_loop())
            )
        if cfg.resource_report_period_ms > 0:
            aux_tasks.append(
                asyncio.get_running_loop().create_task(self._resource_report_loop())
            )
        while True:
            await self._stop.wait()
            if self._quit or not await self._reconnect():
                break
            self._stop.clear()
        for t in aux_tasks:
            t.cancel()
        self._cleanup()

    async def _reconnect(self) -> bool:
        """The head connection died (head crash/restart): keep this node —
        and its live workers — alive and re-register against the head at
        the SAME address (reference: raylet reconnect to a restarted GCS,
        gcs_server.cc:130-178). Workers re-register themselves over their
        own connections; we only re-offer the node + bulk plane."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + cfg.head_reconnect_timeout_s
        while loop.time() < deadline and not self._quit:
            await asyncio.sleep(0.5)
            try:
                info = await self._connect_and_register()
            except Exception:
                continue
            if info["session"] != self.session:
                # a DIFFERENT cluster took the address: this node's shm
                # plane / scratch belong to the old session — bail out
                logger = __import__("logging").getLogger(__name__)
                logger.warning(
                    "head at %s now runs session %s (was %s); shutting down",
                    self.head_address, info["session"], self.session,
                )
                return False
            return True
        return False

    async def _memory_loop(self):
        """Sample this node's memory and report pressure to the head, which
        owns the kill policy (reference: memory_monitor.h sampling in the
        raylet; policy in worker_killing_policy.h)."""
        from .memory_monitor import MemoryMonitor

        mon = MemoryMonitor()
        period = cfg.memory_monitor_refresh_ms / 1000.0
        while not self._stop.is_set():
            await asyncio.sleep(period)
            try:
                pressured, used, total = mon.is_pressured()
            except Exception:
                continue
            if pressured and not self.conn.closed:
                try:
                    await self.conn.send(
                        {"t": "memory_pressure", "node_id": self.node_id,
                         "used": used, "total": total}
                    )
                except Exception:
                    pass

    async def _resource_report_loop(self):
        """Periodic node load report to the head (reference: ray_syncer
        resource gossip, ray_syncer.h:86 — collapsed to agent->head pushes
        since scheduling is centralized; the head folds the reports into
        the node table for the state API / dashboard / autoscaler)."""
        from .memory_monitor import MemoryMonitor

        mon = MemoryMonitor()
        while not self._stop.is_set():
            await asyncio.sleep(cfg.resource_report_period_ms / 1000.0)
            if self.conn is None or self.conn.closed:
                continue
            try:
                used, total = mon.sample()
                report = {
                    "load_1m": os.getloadavg()[0],
                    "mem_used": used,
                    "mem_total": total,
                    "workers": sum(
                        1 for p in self.workers.values() if p.poll() is None
                    ),
                    "ts": time.time(),
                }
                await self.conn.send(
                    {"t": "resource_report", "node_id": self.node_id,
                     "report": report}
                )
            except Exception:
                pass

    async def _on_close(self):
        self._stop.set()

    def _cleanup(self):
        for proc in self.workers.values():
            if proc.poll() is None:
                try:
                    proc.terminate()
                except Exception:
                    pass
        deadline = time.time() + _DEF_GRACE_S
        for proc in self.workers.values():
            try:
                proc.wait(timeout=max(0.0, deadline - time.time()))
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        if self._bulk_server is not None:
            try:
                self._bulk_server.stop()
            except Exception:
                pass
        shm = self._shm_client()
        if shm is not None:
            try:
                shm.disconnect()
                from .shm import ShmClient

                ShmClient.destroy(self.shm_session)
            except Exception:
                pass
        shutil.rmtree(self.scratch_dir, ignore_errors=True)

    # ------------------------------------------------------------------

    async def handle(self, msg):
        t = msg["t"]
        fn = getattr(self, f"_h_{t}", None)
        if fn is None:
            raise ValueError(f"agent got unknown message {t!r}")
        return await fn(msg)

    async def _h_ping(self, msg):
        return "pong"

    async def _h_shutdown(self, msg):
        self._stop.set()
        return True

    async def _ensure_package(self, src: str):
        """For a pkg:// runtime-env source, pull the zip from the head into
        this node's package store if it isn't cached yet, so stage_into
        resolves it locally (reference: the per-node runtime-env agent
        downloading packages from GCS object storage)."""
        if not src.startswith("pkg://"):
            return
        name = src[len("pkg://"):]
        pkg_dir = os.path.join(self.scratch_dir, "packages")
        pkg_path = os.path.join(pkg_dir, name)
        if os.path.exists(pkg_path):
            return
        data = await self.conn.request({"t": "get_package", "name": name}, timeout=120)
        loop = asyncio.get_running_loop()

        def _write():
            import threading

            os.makedirs(pkg_dir, exist_ok=True)
            # pid+tid: concurrent spawns fetching the same package must not
            # share a tmp path (staging.py stage_into pattern)
            tmp = f"{pkg_path}.tmp-{os.getpid()}-{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, pkg_path)

        await loop.run_in_executor(None, _write)

    async def _h_spawn_worker(self, msg):
        """Spawn a local worker that dials the head directly over TCP."""
        worker_id = msg["worker_id"]
        runtime_env = msg.get("runtime_env") or {}
        needs_tpu = msg.get("needs_tpu", False)
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = msg["head_address"]
        env["RAY_TPU_WORKER_ID"] = worker_id
        env["RAY_TPU_NODE_ID"] = self.node_id
        env["RAY_TPU_SESSION_DIR"] = self.scratch_dir
        env["RAY_TPU_SHM_SESSION"] = self.shm_session
        user_env_vars = runtime_env.get("env_vars") or {}
        for k, v in user_env_vars.items():
            env[k] = str(v)
        cwd = self.scratch_dir
        extra_paths = []
        loop = asyncio.get_running_loop()
        if runtime_env.get("working_dir"):
            await self._ensure_package(runtime_env["working_dir"])
            cwd = await loop.run_in_executor(
                None, _stage_dir, self.scratch_dir, runtime_env["working_dir"]
            )
            extra_paths.append(cwd)
        for mod in runtime_env.get("py_modules") or []:
            await self._ensure_package(mod)
            staged = await loop.run_in_executor(None, _stage_dir, self.scratch_dir, mod)
            extra_paths.append(staged if os.path.isdir(staged) else os.path.dirname(staged))
        argv = [sys.executable, "-m", "ray_tpu._private.worker_main"]
        if needs_tpu:
            env.pop("JAX_PLATFORMS", None)
        else:
            if "JAX_PLATFORMS" not in user_env_vars:
                env["JAX_PLATFORMS"] = "cpu"
            argv.insert(1, "-S")
        # workers run -S: carry this agent's sys.path (plus staged dirs first)
        from .spawn import child_pythonpath

        env["PYTHONPATH"] = child_pythonpath(
            extra_paths,
            inherited=env["PYTHONPATH"] if "PYTHONPATH" in user_env_vars else None,
        )
        if cfg.log_to_driver:
            # per-worker log file; _log_forward_loop tails it and sends
            # increments to the head, which republishes to drivers
            # (reference: the per-node log monitor)
            log_dir = os.path.join(self.scratch_dir, "logs")
            os.makedirs(log_dir, exist_ok=True)
            env["PYTHONUNBUFFERED"] = "1"
            logf = open(os.path.join(log_dir, f"{worker_id}.out"), "ab")
            proc = subprocess.Popen(
                argv, env=env, cwd=cwd, stdout=logf, stderr=subprocess.STDOUT
            )
            logf.close()
        else:
            proc = subprocess.Popen(argv, env=env, cwd=cwd)
        self.workers[worker_id] = proc
        return {"pid": proc.pid}

    async def _log_forward_loop(self):
        from . import log_tail

        log_dir = os.path.join(self.scratch_dir, "logs")
        offsets: Dict[str, int] = {}
        pending: Dict[str, tuple] = {}
        wanted = False
        wanted_checked = float("-inf")  # first tick polls immediately
        while not self._stop.is_set():
            await asyncio.sleep(0.3)
            if self.conn is None or self.conn.closed:
                continue
            now = time.monotonic()
            if now - wanted_checked >= 5.0:
                wanted_checked = now
                try:
                    wanted = await self.conn.request({"t": "logs_wanted"}, timeout=5)
                except Exception:
                    wanted = False
            if not wanted:
                # no driver subscribed: ship nothing over TCP, but keep the
                # offsets current so subscription starts with live output
                log_tail.fast_forward(log_dir, offsets)
                continue
            for worker_id, data in log_tail.read_increments(log_dir, offsets, pending):
                try:
                    await self.conn.send(
                        {"t": "worker_logs", "worker_id": worker_id, "data": data}
                    )
                except Exception:
                    pass

    async def _h_kill_worker(self, msg):
        proc = self.workers.pop(msg["worker_id"], None)
        if proc is None:
            return False
        if proc.poll() is None:
            try:
                proc.kill() if msg.get("force") else proc.terminate()
            except Exception:
                pass
        return True

    async def _h_read_buffers(self, msg):
        """Serve node-local shm buffers to the head (relay fallback for
        cross-node pulls). WireBuffer: the slab views ride the control
        socket as out-of-band segments — no pickle copy on this side."""

        shm = self._shm_client()
        out: Dict[str, Optional[protocol.WireBuffer]] = {}
        for name in msg["names"]:
            mv = None if shm is None else shm.get_or_spilled(name)
            out[name] = None if mv is None else protocol.WireBuffer(mv)
        return out

    async def _h_delete_buffers(self, msg):
        shm = self._shm_client()
        if shm is not None:
            for name in msg["names"]:
                shm.delete(name)
        return True


def _stage_dir(scratch_dir: str, src: str) -> str:
    from .staging import stage_into

    return stage_into(scratch_dir, src)
