"""CoreWorker-lite: the per-process runtime shared by driver and workers.

Reference parity: src/ray/core_worker/core_worker.h:284 (CoreWorker) +
python/ray/_private/worker.py (global Worker singleton, connect/get/put/wait).
One instance per process; owns the control-plane connection, the ObjectRef
reference counting hooks, and task/actor submission. Unlike the reference
there is no separate in-process C++ library — the hot compute path on TPU is
a single compiled XLA program, so the orchestration runtime stays in Python
with the bulk-data plane (shared-memory store) in C++.
"""

from __future__ import annotations

import asyncio
import atexit
import collections
import hashlib
import logging
import os
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

logger = logging.getLogger(__name__)

from .. import exceptions
from . import protocol, serialization
from .config import GLOBAL_CONFIG as cfg
from .ids import ActorID, JobID, ObjectID, TaskID

MODE_DRIVER = "driver"
MODE_WORKER = "worker"


class EventLoopThread:
    """A background thread running an asyncio loop, with sync bridges."""

    def __init__(self, name="ray_tpu-io"):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout=None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def post(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)


@dataclass
class _ArgRef:
    """Placeholder for a top-level ObjectRef argument (replaced by its value
    at execution; nested refs stay refs — reference semantics)."""

    object_id: str


async def _swallow_conn_errors(coro):
    """Fire-and-forget sends: a connection torn down mid-send (shutdown,
    worker death) must not leave an unretrieved-exception future."""
    try:
        await coro
    except Exception:
        pass


def _copy_envelope(env):
    """Shallow copy so materialize() never mutates a cached envelope."""
    return serialization.SerializedObject(
        payload=env.payload,
        buffers=list(env.buffers),
        contained_refs=list(env.contained_refs),
        is_error=env.is_error,
    )


class _ActorChannel:
    """Per-(caller, actor) direct transport. Reference parity:
    CoreWorkerDirectActorTaskSubmitter (direct_actor_task_submitter.h:67) —
    calls push straight to the actor's worker process over one ordered
    connection; the head is only consulted for the route (and re-consulted
    when the connection breaks, e.g. across an actor restart).

    A single consumer coroutine drains a FIFO queue: per-caller submission
    order is preserved no matter how route resolution, dependency waits, or
    fallback interleave. Results come back inline; the caller caches them
    locally and forwards them to the head's object directory so any other
    process can still `get` them."""

    def __init__(self, worker: "Worker", actor_id: str):
        self.worker = worker
        self.actor_id = actor_id
        self.queue: asyncio.Queue = asyncio.Queue()
        self.conn: Optional[protocol.Connection] = None
        self.head_routed = False  # permanent fallback: order must not mix
        self.task = asyncio.get_running_loop().create_task(self._consume())

    async def _resolve(self) -> Optional[str]:
        """Poll the head until the actor is alive (with an address) or dead.
        Returns the address or None.

        No wall-clock deadline while the actor is pending/starting: actor
        startup is legitimately slow (worker spawn + heavy imports under
        host contention), and giving up would fail calls on an actor that
        is about to come up. If the actor truly never starts, the head
        marks it dead (spawn failure / init failure / node death) and the
        poll observes that (reference: submitter buffers calls until the
        GCS publishes the actor address, direct_actor_task_submitter.h:67)."""
        delay = 0.02
        warn_at = asyncio.get_running_loop().time() + cfg.worker_register_timeout_s
        while True:
            route = await self.worker.conn.request(
                {"t": "get_actor_route", "actor_id": self.actor_id}
            )
            if route is None or route["state"] == "dead":
                return None
            if route["state"] == "alive" and route["address"]:
                addr = route["address"]
                if not protocol.is_tcp_address(addr) and (
                    route["node_id"] != self.worker.node_id
                ):
                    return None  # unix socket on another machine
                return addr
            if warn_at is not None and asyncio.get_running_loop().time() > warn_at:
                warn_at = None
                logger.warning(
                    "actor %s still %s after %.0fs; calls will block until it "
                    "is scheduled (check cluster resources) or killed",
                    self.actor_id, route["state"], cfg.worker_register_timeout_s,
                )
            await asyncio.sleep(delay)
            delay = min(delay * 2, 0.5)

    async def _connect(self) -> bool:
        if self.conn is not None and not self.conn.closed:
            return True
        addr = await self._resolve()
        if addr is None:
            return False
        try:
            reader, writer = await protocol.open_stream(addr)
        except OSError:
            return False

        async def handler(msg):
            raise ValueError("unexpected push on direct actor channel")

        self.conn = protocol.Connection(reader, writer, handler)
        self.conn.start()
        return True

    async def _resolve_deps(self, spec: dict) -> dict:
        resolved = {}
        missing = []
        for oid in spec.get("deps", []):
            env = self.worker._local_objects.get(oid)
            if env is not None:
                resolved[oid] = env
            else:
                missing.append(oid)
        if missing:
            envs = await self.worker.conn.request(
                {"t": "get_objects", "object_ids": missing}
            )
            resolved.update(dict(zip(missing, envs)))
        return resolved

    async def _consume(self):
        while True:
            spec = await self.queue.get()
            if spec is None:
                return
            try:
                await self._submit_one(spec)
            except Exception:
                logger.exception("direct actor call failed; routing via head")
                self._to_head(spec)

    async def _submit_one(self, spec: dict):
        """Send in FIFO order but do NOT wait for the reply — replies are
        collected by a separate task per call, so calls pipeline exactly
        like the head path (and like the reference's in-flight queue)."""
        if self.head_routed or not await self._connect():
            self.head_routed = True
            self._to_head(spec)
            return
        resolved = await self._resolve_deps(spec)
        msg = {
            "t": "run_task",
            "task_id": spec["task_id"],
            "actor_id": self.actor_id,
            "method": spec["method"],
            "args": {"env": spec["args"], "resolved": resolved},
            "return_ids": spec["return_ids"],
            "trace_ctx": spec.get("trace_ctx"),
        }
        loop = asyncio.get_running_loop()
        fut = loop.create_task(self.conn.request(msg))
        loop.create_task(self._finish(spec, msg, fut))

    async def _finish(self, spec: dict, msg: dict, fut):
        """Collect the reply and settle the return objects. MUST terminate
        every return id one way or another — a get() may be blocked on the
        local pending event with no timeout."""
        try:
            try:
                reply = await fut
            except Exception as e:
                # The connection broke mid-call (worker death / restart). Do
                # NOT resend: the actor may have already executed this call —
                # a replay would double-execute side effects (reference
                # semantics: in-flight actor tasks fail with ActorDiedError
                # on death; only max_task_retries opts into replays). Later
                # calls reconnect to the restarted actor via a fresh route.
                self.conn = None
                await self._fail_returns(spec, f"worker died mid-call: {e!r}")
                return
            for _ in range(3):
                lost = reply.get("lost_deps")
                if not lost:
                    break
                # dep buffers were evicted before the actor could read them.
                # The user code never ran, so a resend is side-effect safe;
                # rebuild the deps from lineage first.
                ok = await self.worker.conn.request(
                    {"t": "reconstruct_objects", "object_ids": lost}
                )
                if not all(ok.get(oid) for oid in lost):
                    await self._fail_returns(spec, f"lost deps {lost} unrecoverable")
                    return
                msg["args"] = {
                    "env": spec["args"],
                    "resolved": await self._resolve_deps(spec),
                }
                reply = await self.conn.request(msg)
            if "results" not in reply:
                await self._fail_returns(spec, f"bad reply {list(reply)}")
                return
            envs = reply["results"]
            for oid, env in zip(spec["return_ids"], envs):
                self.worker._cache_local_object(oid, env)
                await self.worker.conn.send(
                    {"t": "put_object", "object_id": oid, "envelope": env,
                     "initial_refs": 1}
                )
        except Exception as e:  # never leave pending events unsettled
            try:
                await self._fail_returns(spec, f"direct call failed: {e!r}")
            except Exception:
                self.worker._release_pending(spec["return_ids"])
        finally:
            # deps stay pinned until the actor has consumed (or we failed)
            await self._release_deps(spec)

    async def _fail_returns(self, spec: dict, reason: str):
        from ..exceptions import ActorDiedError

        err = serialization.serialize(ActorDiedError(self.actor_id, reason))
        err.is_error = True
        for oid in spec["return_ids"]:
            self.worker._cache_local_object(oid, err)
            await self.worker.conn.send(
                {"t": "put_object", "object_id": oid, "envelope": err,
                 "initial_refs": 1}
            )

    def _to_head(self, spec: dict):
        # release get() waiters: the result will come via the head, not the
        # local cache (events with no cached envelope mean "ask the head")
        self.worker._release_pending(spec["return_ids"])
        try:
            loop = asyncio.get_running_loop()
            # the head takes the caller's +1 at submit (the direct path
            # skipped it; head-path results don't carry it in put_object)
            loop.create_task(
                self.worker.conn.send({"t": "submit_actor_task", "spec": spec})
            )
            # release the direct-path dep pins AFTER the submit lands (the
            # handler pins deps synchronously on arrival)
            loop.create_task(self._release_deps(spec))
        except Exception:
            pass

    async def _release_deps(self, spec: dict):
        """Idempotent release of the dep refs taken at direct submit (both
        the direct send and the head fallback funnel through here)."""
        if spec.get("deps") and not spec.get("_deps_released"):
            spec["_deps_released"] = True
            await self.worker.conn.send(
                {"t": "remove_refs", "counts": {d: 1 for d in spec["deps"]}}
            )

    async def close(self):
        self.task.cancel()
        if self.conn is not None:
            await self.conn.close()


class Worker:
    """The global per-process runtime."""

    def __init__(self):
        self.mode: Optional[str] = None
        self.connected = False
        self.job_id = JobID.from_int(os.getpid() % (2**31))
        self.node_id: Optional[str] = None
        self.session_dir: Optional[str] = None
        self.io: Optional[EventLoopThread] = None
        self.conn: Optional[protocol.Connection] = None
        self.node = None  # driver-only: the Node supervisor
        self._fn_exported: Dict[str, bool] = {}
        import weakref

        self._export_keys: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self.current_actor = None
        self.current_actor_id: Optional[str] = None
        self.current_task_id: Optional[str] = None
        self.namespace: str = ""
        # job-level default runtime_env (tasks/actors inherit it when they
        # don't specify their own)
        self.default_runtime_env: Optional[dict] = None
        self._lock = threading.RLock()
        self._shm = None
        self._shm_tried = False
        # direct-transport state: per-actor channels + locally cached result
        # envelopes (bounded; the head's ObjectDirectory stays the source of
        # truth for every other process)
        self._actor_channels: Dict[str, _ActorChannel] = {}
        self._local_objects: "collections.OrderedDict[str, Any]" = collections.OrderedDict()
        # in-flight direct calls: return id -> Event set when the reply
        # lands locally (get() waits here instead of round-tripping the head)
        self._local_pending: Dict[str, threading.Event] = {}
        self._local_lock = threading.Lock()
        # pubsub: channel -> callbacks invoked on pushed messages
        # (reference: src/ray/pubsub subscriber.h:329); one dispatcher
        # thread drains a queue so callbacks run in publish order
        self._pubsub_callbacks: Dict[str, List[Any]] = {}
        self._pubsub_queue: Optional[Any] = None

    def _cache_local_object(self, oid: str, env) -> None:
        with self._local_lock:
            self._local_objects[oid] = env
            self._local_objects.move_to_end(oid)
            while len(self._local_objects) > 1024:
                self._local_objects.popitem(last=False)
            ev = self._local_pending.pop(oid, None)
        if ev is not None:
            ev.set()

    def _release_pending(self, oids) -> None:
        with self._local_lock:
            evs = [self._local_pending.pop(oid, None) for oid in oids]
        for ev in evs:
            if ev is not None:
                ev.set()

    @property
    def shm(self):
        """Lazy client for the C++ shared-memory object plane (None if
        disabled or unavailable)."""
        if self._shm_tried:
            return self._shm
        self._shm_tried = True
        from .shm import connect_for_session

        self._shm = connect_for_session(self.session_dir)
        return self._shm

    # ------------------------------------------------------------------
    # connection
    # ------------------------------------------------------------------

    def connect_driver(self, node, namespace: str = ""):
        self.mode = MODE_DRIVER
        self._fn_exported.clear()
        self._export_keys.clear()
        if self._shm is not None:
            try:
                self._shm.disconnect()
            except Exception:
                pass
        self._shm = None
        self._shm_tried = False
        self.node = node
        self.io = node.io
        self.session_dir = node.session_dir
        self.namespace = namespace
        self.conn = self.io.run(self._open_conn(node.socket_path))
        info = self.request(
            {"t": "register_driver", "proto": protocol.PROTOCOL_VERSION}
        )
        self.node_id = info["node_id"]
        self.connected = True

    def connect_existing(self, socket_path: str, namespace: str = ""):
        """Attach as an ADDITIONAL driver to a running head — via the
        session unix socket (job submission, `init(address="auto")`) or a
        TCP host:port (remote drivers; reference: worker.py:1186 address
        resolution + util/client). Owns its own IO thread; the head
        outlives this client."""
        import os

        self.mode = MODE_DRIVER
        self._fn_exported.clear()
        self._export_keys.clear()
        if self._shm is not None:
            try:
                self._shm.disconnect()
            except Exception:
                pass
        self._shm = None
        self._shm_tried = False
        self.node = None
        self.io = EventLoopThread()
        self._owns_io = True
        # remote (TCP) drivers have no local session dir: no shm plane —
        # objects ride the socket inline and buffers are pulled via the head
        self.session_dir = (
            None if protocol.is_tcp_address(socket_path) else os.path.dirname(socket_path)
        )
        self.namespace = namespace
        self.conn = self.io.run(self._open_conn(socket_path))
        info = self.request(
            {"t": "register_driver", "proto": protocol.PROTOCOL_VERSION}
        )
        self.node_id = info["node_id"]
        if os.environ.get("RAY_TPU_JOB_RUNTIME_ENV"):
            import json

            self.default_runtime_env = json.loads(os.environ["RAY_TPU_JOB_RUNTIME_ENV"])
        self.connected = True

    def connect_worker(
        self, socket_path: str, worker_id: str, io: EventLoopThread, conn, node_id=None
    ):
        self.mode = MODE_WORKER
        self.io = io
        self.conn = conn
        self.node_id = node_id
        self.connected = True

    async def _open_conn(self, socket_path: str) -> protocol.Connection:
        reader, writer = await protocol.open_stream(socket_path)

        async def handler(msg):
            return await self._handle_push(msg)

        conn = protocol.Connection(reader, writer, handler)
        conn.start()
        return conn

    async def _handle_push(self, msg):
        if msg.get("t") == "pub":
            self.dispatch_pub(msg)
            return None
        raise ValueError(f"driver got unexpected message {msg.get('t')}")

    def dispatch_pub(self, msg: dict) -> None:
        """Deliver a pushed channel message to local subscriber callbacks.
        Runs on the IO loop (or the worker's protocol loop) — callbacks run
        on ONE daemon dispatcher thread, preserving publish order (a thread
        per message could apply seq=1 after seq=2, stranding subscribers on
        a stale snapshot) and keeping user code off the protocol loop."""
        if not self._pubsub_callbacks.get(msg["channel"]):
            return
        with self._lock:
            if self._pubsub_queue is None:
                import queue as _queue

                self._pubsub_queue = _queue.SimpleQueue()
                threading.Thread(
                    target=self._pubsub_dispatch_loop, daemon=True, name="pubsub-cb"
                ).start()
        self._pubsub_queue.put(msg)

    def _pubsub_dispatch_loop(self):
        while True:
            msg = self._pubsub_queue.get()
            for cb in list(self._pubsub_callbacks.get(msg["channel"], ())):
                try:
                    cb(msg["seq"], msg["data"])
                except Exception:
                    logger.exception("pubsub callback failed for %s", msg["channel"])

    # ------------------------------------------------------------------
    # pubsub (reference: src/ray/pubsub; serve long-poll rides poll_channel)
    # ------------------------------------------------------------------

    def publish(self, channel: str, data) -> int:
        return self.request({"t": "publish", "channel": channel, "data": data})

    def subscribe(self, channel: str, callback) -> Tuple[int, Any]:
        """Register a push callback(seq, data); returns the (seq, data)
        snapshot at subscribe time (0, None if never published)."""
        self._pubsub_callbacks.setdefault(channel, []).append(callback)
        snap = self.request({"t": "subscribe", "channel": channel})
        return snap["seq"], snap["data"]

    def unsubscribe(self, channel: str) -> None:
        self._pubsub_callbacks.pop(channel, None)
        try:
            self.request({"t": "unsubscribe", "channel": channel})
        except Exception:
            pass

    def start_log_forwarding(self) -> None:
        """Print workers' stdout/stderr in this driver, prefixed with the
        worker id (reference: worker.py print redirection fed by the log
        monitor). Subscribes to the head's "__logs__" channel."""

        def on_log(seq, entry):
            prefix = f"({entry['worker_id']}) "
            text = entry["data"]
            for line in text.splitlines():
                print(prefix + line, flush=True)

        try:
            self.subscribe("__logs__", on_log)
        except Exception:
            pass  # logs are best-effort; never fail init over them

    def poll_channel(self, channel: str, last_seq: int = 0, timeout: float = 30.0):
        """Long-poll for a publish newer than last_seq. Returns (seq, data)
        or None on timeout (caller re-polls)."""
        reply = self.request(
            {"t": "poll_channel", "channel": channel, "last_seq": last_seq,
             "timeout": timeout},
            timeout=timeout + 10.0,
        )
        if reply.get("timeout"):
            return None
        return reply["seq"], reply["data"]

    def request(self, msg: dict, timeout: Optional[float] = None) -> Any:
        if not self.conn or self.conn.closed:
            raise exceptions.RayTpuError("ray_tpu is not connected (call ray_tpu.init())")
        return self.io.run(self.conn.request(msg, timeout))

    def send(self, msg: dict):
        if self.conn is None or self.conn.closed or self.io is None:
            return
        try:
            self.io.post(_swallow_conn_errors(self.conn.send(msg)))
        except RuntimeError:
            pass  # loop shut down

    def send_ordered(self, msg: dict):
        """Fire-and-forget submit. Per-connection FIFO both on the asyncio
        send side and in the head's handler dispatch, so a later request()
        from this process observes its effects (the reference gets the same
        property from gRPC in-order delivery per channel)."""
        if self.conn is None or self.conn.closed or self.io is None:
            raise exceptions.RayTpuError("ray_tpu is not connected (call ray_tpu.init())")
        self.io.post(_swallow_conn_errors(self.conn.send(msg)))

    def disconnect(self):
        self.connected = False
        self.mode = None
        channels, self._actor_channels = dict(self._actor_channels), {}
        if self.io is not None:
            for ch in channels.values():
                try:
                    self.io.run(ch.close(), timeout=2)
                except Exception:
                    pass
        self._pubsub_callbacks.clear()
        with self._local_lock:
            self._local_objects.clear()
            pending, self._local_pending = dict(self._local_pending), {}
        for ev in pending.values():
            ev.set()  # wake blocked get()s; they fall through to a
            # not-connected error instead of waiting forever
        self.conn = None
        if getattr(self, "_owns_io", False) and self.io is not None:
            try:
                self.io.stop()
            except Exception:
                pass
            self.io = None
            self._owns_io = False

    # ------------------------------------------------------------------
    # refcounting (reference_count.h:61 — simplified owner-side counting)
    # ------------------------------------------------------------------

    def merged_runtime_env(self, task_env: Optional[dict]) -> Optional[dict]:
        """Per-field merge of a task/actor runtime_env over the job-level
        default (reference semantics: env_vars union, task wins per key;
        other fields override wholesale)."""
        default = self.default_runtime_env
        if not default:
            return task_env
        if not task_env:
            return default
        merged = {**default, **task_env}
        if default.get("env_vars") or task_env.get("env_vars"):
            merged["env_vars"] = {
                **(default.get("env_vars") or {}),
                **(task_env.get("env_vars") or {}),
            }
        return merged

    def add_object_ref(self, object_id: str):
        if self.connected:
            self.send({"t": "add_refs", "counts": {object_id: 1}})

    def remove_object_ref(self, object_id: str):
        with self._local_lock:
            self._local_objects.pop(object_id, None)
        if self.connected:
            self.send({"t": "remove_refs", "counts": {object_id: 1}})

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------

    def put(self, value) -> "ObjectRef":
        from ..object_ref import ObjectRef

        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed.")
        from .config import GLOBAL_CONFIG as cfg

        oid = ObjectID.from_put(self.job_id).hex()
        env = serialization.serialize(value)
        # pin=True: put data has no lineage, so it must never be evicted
        env = serialization.externalize(
            env, self.shm, cfg.object_inline_limit_bytes, pin=True
        )
        # fire-and-forget: messages on one connection are handled in order,
        # so a later get() cannot observe the object missing; dropping the
        # ack makes put() bandwidth-bound instead of RTT-bound
        self.send_ordered(
            {"t": "put_object", "object_id": oid, "envelope": env, "initial_refs": 1}
        )
        return ObjectRef(oid, skip_adding_local_ref=True)

    def get(self, refs, timeout: Optional[float] = None):
        from ..object_ref import ObjectRef

        is_single = isinstance(refs, ObjectRef)
        ref_list = [refs] if is_single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
        # fast path: results of direct actor calls are cached locally (or in
        # flight — then wait on the local event) — no head round-trip for
        # the produce-then-get pattern
        envs: List[Any] = [None] * len(ref_list)
        missing: List[int] = []
        pending: List[Tuple[int, Any]] = []
        with self._local_lock:
            for i, r in enumerate(ref_list):
                env = self._local_objects.get(r.id)
                if env is not None:
                    envs[i] = _copy_envelope(env)
                    continue
                ev = self._local_pending.get(r.id)
                if ev is not None:
                    pending.append((i, ev))
                else:
                    missing.append(i)
        deadline = None if timeout is None else time.monotonic() + timeout
        for i, ev in pending:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            if not ev.wait(remaining):
                raise exceptions.GetTimeoutError(
                    f"Get timed out after {timeout}s waiting for {ref_list[i].id}"
                )
            with self._local_lock:
                env = self._local_objects.get(ref_list[i].id)
            if env is not None:
                envs[i] = _copy_envelope(env)
            else:
                missing.append(i)  # routed via the head after all
        def remaining():
            if deadline is None:
                return None
            return max(0.0, deadline - time.monotonic())

        if missing:
            fetched = self.request(
                {
                    "t": "get_objects",
                    "object_ids": [ref_list[i].id for i in missing],
                    "timeout": remaining(),
                }
            )
            for i, env in zip(missing, fetched):
                envs[i] = env
        values = []
        for env, ref in zip(envs, ref_list):
            for attempt in range(3):
                try:
                    env = serialization.materialize(env, self.shm)
                    break
                except exceptions.ObjectLostError:
                    # buffers evicted/lost: ask the head to rebuild the
                    # object from its creating task's lineage, then refetch
                    # (reference: ObjectRecoveryManager resubmission)
                    if attempt == 2:
                        raise
                    ok = self.request(
                        {"t": "reconstruct_objects", "object_ids": [ref.id]}
                    )
                    if not ok.get(ref.id):
                        raise exceptions.ObjectLostError(ref.id) from None
                    env = self.request(
                        {"t": "get_objects", "object_ids": [ref.id],
                         "timeout": remaining()}
                    )[0]
            value = serialization.deserialize(env)
            if getattr(env, "is_error", False):
                raise value
            values.append(value)
        return values[0] if is_single else values

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        from ..object_ref import ObjectRef

        refs = list(refs)
        if len(set(r.id for r in refs)) != len(refs):
            raise ValueError("wait() expects a list of unique ObjectRefs.")
        if num_returns > len(refs):
            raise ValueError("num_returns cannot exceed the number of refs")
        ready_ids, pending_ids = self.request(
            {
                "t": "wait_objects",
                "object_ids": [r.id for r in refs],
                "num_returns": num_returns,
                "timeout": timeout,
            }
        )
        by_id = {r.id: r for r in refs}
        return [by_id[i] for i in ready_ids], [by_id[i] for i in pending_ids]

    # ------------------------------------------------------------------
    # tasks
    # ------------------------------------------------------------------

    def _export_callable(self, obj, ns: str) -> str:
        # identity memo: re-pickling the same function on EVERY submit just
        # to recompute its content hash dominates the submit hot path. A
        # function's captured globals/closures therefore FREEZE at first
        # export — the reference has the same semantics (function_manager
        # exports once per function object and workers cache by hash).
        # Keyed per (object, ns) so 'fn' and 'cls' namespaces can't alias.
        try:
            memo = self._export_keys.get(obj)
        except TypeError:  # not weakref-able
            memo = None
        if memo is not None and ns in memo:
            return memo[ns]
        blob = cloudpickle.dumps(obj)
        key = hashlib.sha1(blob).hexdigest()
        with self._lock:
            if key not in self._fn_exported:
                self.request({"t": "kv_put", "ns": ns, "key": key, "value": blob, "overwrite": False})
                self._fn_exported[key] = True
        try:
            self._export_keys.setdefault(obj, {})[ns] = key
        except TypeError:
            pass
        return key

    def _prepare_args(self, args: tuple, kwargs: dict):
        """Replace top-level ObjectRefs with _ArgRef markers; collect deps."""
        from ..object_ref import ObjectRef

        deps: List[str] = []

        def conv(a):
            if isinstance(a, ObjectRef):
                deps.append(a.id)
                return _ArgRef(a.id)
            return a

        new_args = tuple(conv(a) for a in args)
        new_kwargs = {k: conv(v) for k, v in kwargs.items()}
        env = serialization.serialize((new_args, new_kwargs))
        # nested refs found during pickling are deps too (must exist at exec)
        for r in env.contained_refs:
            deps.append(r.id)
        return env, sorted(set(deps))

    def submit_task(
        self,
        function,
        args: tuple,
        kwargs: dict,
        *,
        name: str = "",
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: int = 0,
        scheduling_strategy=None,
        runtime_env: Optional[dict] = None,
    ) -> List["ObjectRef"]:
        from ..object_ref import ObjectRef

        fn_key = self._export_callable(function, "fn")
        task_id = TaskID.for_task(self.job_id)
        return_ids = [ObjectID.for_return(task_id, i).hex() for i in range(num_returns)]
        env, deps = self._prepare_args(args, kwargs)
        from ..util import tracing

        with tracing.span_for_submission(
            f"task_submit.{name or getattr(function, '__name__', 'task')}",
            task_id=task_id.hex(),
        ):
            trace_ctx = tracing.inject_current_context()
        spec = {
            "task_id": task_id.hex(),
            "name": name,
            "fn_key": fn_key,
            "trace_ctx": trace_ctx,
            "args": env,
            "deps": deps,
            "return_ids": return_ids,
            "resources": resources,
            "max_retries": max_retries,
            "scheduling_strategy": scheduling_strategy,
            "runtime_env": self.merged_runtime_env(runtime_env),
        }
        # fire-and-forget (FIFO per connection): submission is
        # serialization-bound, not RTT-bound; the head takes the caller's
        # +1 on each return id when it processes the submit
        self.send_ordered({"t": "submit_task", "spec": spec})
        return [ObjectRef(oid, skip_adding_local_ref=True) for oid in return_ids]

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------

    def create_actor(
        self,
        cls,
        args: tuple,
        kwargs: dict,
        *,
        name: Optional[str] = None,
        namespace: Optional[str] = None,
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_concurrency: int = 1,
        scheduling_strategy=None,
        lifetime: Optional[str] = None,
        runtime_env: Optional[dict] = None,
    ) -> str:
        cls_key = self._export_callable(cls, "cls")
        actor_id = ActorID.of(self.job_id).hex()
        env, deps = self._prepare_args(args, kwargs)
        spec = {
            "actor_id": actor_id,
            "cls_key": cls_key,
            "cls_name": getattr(cls, "__name__", str(cls)),
            "args": env,
            "deps": deps,
            "name": name,
            "namespace": namespace if namespace is not None else self.namespace,
            "resources": resources,
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "scheduling_strategy": scheduling_strategy,
            "lifetime": lifetime,
            "runtime_env": self.merged_runtime_env(runtime_env),
        }
        self.request({"t": "create_actor", "spec": spec})
        return actor_id

    def submit_actor_task(
        self,
        actor_id: str,
        method: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
    ) -> List["ObjectRef"]:
        from ..object_ref import ObjectRef

        task_id = TaskID.for_actor_task(ActorID.from_hex(actor_id))
        return_ids = [ObjectID.for_return(task_id, i).hex() for i in range(num_returns)]
        env, deps = self._prepare_args(args, kwargs)
        from ..util import tracing

        with tracing.span_for_submission(
            f"actor_submit.{method}", task_id=task_id.hex(), actor_id=actor_id
        ):
            trace_ctx = tracing.inject_current_context()
        spec = {
            "task_id": task_id.hex(),
            "actor_id": actor_id,
            "method": method,
            "trace_ctx": trace_ctx,
            "args": env,
            "deps": deps,
            "return_ids": return_ids,
        }
        if cfg.direct_actor_calls:
            # no up-front add_refs for RESULTS: the caller's +1 rides the
            # put_object that delivers them (initial_refs=1); the head
            # reconciles early remove_refs via its signed counters. Deps DO
            # get pinned here — the user may drop their ObjectRef right
            # after .remote(), and the channel still has to resolve them.
            if deps:
                self.send_ordered({"t": "add_refs", "counts": {d: 1 for d in deps}})
            with self._lock:  # two threads must not race in two channels
                ch = self._actor_channels.get(actor_id)
                if ch is None:
                    ch = self.io.run(self._make_channel(actor_id))
                    self._actor_channels[actor_id] = ch
            with self._local_lock:
                for oid in return_ids:
                    self._local_pending[oid] = threading.Event()
            self.io.loop.call_soon_threadsafe(ch.queue.put_nowait, spec)
        else:
            self.send_ordered({"t": "submit_actor_task", "spec": spec})
        return [ObjectRef(oid, skip_adding_local_ref=True) for oid in return_ids]

    async def _make_channel(self, actor_id: str) -> "_ActorChannel":
        return _ActorChannel(self, actor_id)


global_worker = Worker()


# --------------------------------------------------------------------------
# task execution (the worker side of run_task)
# --------------------------------------------------------------------------


def resolve_task_args(args_msg: dict) -> Tuple[tuple, dict]:
    env: serialization.SerializedObject = args_msg["env"]
    resolved: Dict[str, serialization.SerializedObject] = args_msg["resolved"]
    env = serialization.materialize(env, global_worker.shm)
    args, kwargs = serialization.deserialize(env)
    lost: List[str] = []

    def conv(a):
        if isinstance(a, _ArgRef):
            dep_env = resolved.get(a.object_id)
            if dep_env is None:
                lost.append(a.object_id)
                return None
            try:
                dep_env = serialization.materialize(dep_env, global_worker.shm)
            except exceptions.ObjectLostError:
                # buffer gone (evicted): collect the OBJECT id — ALL lost
                # deps are reported together so the head reconstructs them
                # in one round
                lost.append(a.object_id)
                return None
            value = serialization.deserialize(dep_env)
            if getattr(dep_env, "is_error", False):
                raise value
            return value
        return a

    args = tuple(conv(a) for a in args)
    kwargs = {k: conv(v) for k, v in kwargs.items()}
    if lost:
        raise exceptions.LostDepsError(lost)
    return args, kwargs


def execute_and_package(
    fn, fn_name: str, args_msg: dict, return_ids: List[str], pin_results: bool = False
) -> dict:
    """Run a task function and package results as envelopes.

    pin_results=True (actor methods): actor outputs have no lineage — the
    method ran against mutable state — so their shm buffers must never be
    LRU-evicted. Stateless task outputs stay evictable (reconstructible).

    Reference: _raylet.pyx:1630 execute_task_with_cancellation_handler.
    """
    try:
        try:
            args, kwargs = resolve_task_args(args_msg)
        except exceptions.LostDepsError as e:
            # dependency buffers were evicted: signal the head to rebuild
            # them from lineage and re-dispatch (not a user error, and not
            # a retry — reference: dependency resolution failure triggering
            # ObjectRecoveryManager)
            return {"lost_deps": e.object_ids}
        result = fn(*args, **kwargs)
        n = len(return_ids)
        if n == 0:
            return {"results": []}
        if n == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != n:
                raise ValueError(
                    f"Task {fn_name} set num_returns={n} but returned {len(values)} values"
                )
        from .config import GLOBAL_CONFIG as cfg

        envs = []
        for v in values:
            env = serialization.serialize(v)
            envs.append(
                serialization.externalize(
                    env, global_worker.shm, cfg.object_inline_limit_bytes,
                    pin=pin_results,
                )
            )
        return {"results": envs}
    except Exception as e:  # noqa: BLE001
        tb = traceback.format_exc()
        if isinstance(e, (exceptions.TaskError, exceptions.ActorError)):
            err: Exception = e
        else:
            err = exceptions.TaskError(fn_name, tb, e)
        env = serialization.serialize(err)
        env.is_error = True  # type: ignore[attr-defined]
        return {"results": [env for _ in return_ids] or [env]}


@atexit.register
def _shutdown_at_exit():
    w = global_worker
    if w.mode == MODE_DRIVER and w.node is not None:
        try:
            w.node.stop()
        except Exception:
            pass
