"""CoreWorker-lite: the per-process runtime shared by driver and workers.

Reference parity: src/ray/core_worker/core_worker.h:284 (CoreWorker) +
python/ray/_private/worker.py (global Worker singleton, connect/get/put/wait).
One instance per process; owns the control-plane connection, the ObjectRef
reference counting hooks, and task/actor submission. Unlike the reference
there is no separate in-process C++ library — the hot compute path on TPU is
a single compiled XLA program, so the orchestration runtime stays in Python
with the bulk-data plane (shared-memory store) in C++.
"""

from __future__ import annotations

import asyncio
import atexit
import collections
import hashlib
import logging
import os
import sys
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

logger = logging.getLogger(__name__)

from .. import exceptions
from . import protocol, serialization
from .config import GLOBAL_CONFIG as cfg
from .ids import ActorID, JobID, ObjectID, TaskID

MODE_DRIVER = "driver"
MODE_WORKER = "worker"


class EventLoopThread:
    """A background thread running an asyncio loop, with sync bridges."""

    def __init__(self, name="ray_tpu-io"):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout=None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def post(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)


@dataclass
class _ArgRef:
    """Placeholder for a top-level ObjectRef argument (replaced by its value
    at execution; nested refs stay refs — reference semantics)."""

    object_id: str


def _bulk_account(path: str, nbytes: int) -> None:
    from .bulk import account

    account(path, nbytes)


class _HeapDest:
    """Pull destination when the local slab can't host the buffer (store
    disabled/full): plain bytearray with the PendingBuffer interface."""

    __slots__ = ("name", "size", "view", "_buf")

    def __init__(self, name: str, size: int):
        self.name = name
        self.size = size
        self._buf = bytearray(size)
        self.view = memoryview(self._buf)

    def commit(self):
        return None  # not slab-resident; caller serves self.view directly

    def abort(self):
        pass


def _flag_bounded(od, key, cap: int = 1024) -> None:
    """Record a best-effort flag in an OrderedDict with FIFO eviction —
    misses (ids that never come back) must not pile up for the process
    lifetime; dropping the oldest only downgrades a rare cancel to
    best-effort."""
    od[key] = None
    while len(od) > cap:
        od.popitem(last=False)


async def _swallow_conn_errors(coro):
    """Fire-and-forget sends: a connection torn down mid-send (shutdown,
    worker death) must not leave an unretrieved-exception future."""
    try:
        await coro
    except Exception:
        pass


def _copy_envelope(env):
    """Shallow copy so materialize() never mutates a cached envelope."""
    return serialization.SerializedObject(
        payload=env.payload,
        buffers=list(env.buffers),
        contained_refs=list(env.contained_refs),
        is_error=env.is_error,
    )


class _ActorChannel:
    """Per-(caller, actor) direct transport. Reference parity:
    CoreWorkerDirectActorTaskSubmitter (direct_actor_task_submitter.h:67) —
    calls push straight to the actor's worker process over one ordered
    connection; the head is only consulted for the route (and re-consulted
    when the connection breaks, e.g. across an actor restart).

    A single consumer coroutine drains a FIFO queue: per-caller submission
    order is preserved no matter how route resolution, dependency waits, or
    fallback interleave. Results come back inline; the caller caches them
    locally and forwards them to the head's object directory so any other
    process can still `get` them."""

    def __init__(self, worker: "Worker", actor_id: str):
        self.worker = worker
        self.actor_id = actor_id
        # thread-safe FIFO: callers append directly (visible immediately —
        # closes the submit/stash ordering race a call_soon-deferred
        # asyncio.Queue.put would open) and wake the consumer via the loop
        self.deque: "collections.deque" = collections.deque()
        self._more = asyncio.Event()
        self.conn: Optional[protocol.Connection] = None
        self.direct_addr: Optional[str] = None  # for the sync bypass socket
        self.head_routed = False  # permanent fallback: order must not mix
        self.inflight = 0  # direct calls sent, reply not yet settled
        self.inflight_tids: set = set()  # their task ids, for cancel()
        # sync-bypass stash: at most ONE deferred call (see Worker.get's
        # bypass path); guarded by worker._stash_lock
        self.stashed: Optional[dict] = None
        self.task = asyncio.get_running_loop().create_task(self._consume())

    def wake(self):
        self._more.set()

    def claim_stash(self, spec: Optional[dict] = None) -> Optional[dict]:
        """Atomically take the stashed call (or `spec` specifically).
        Returns it, or None if absent/already claimed."""
        with self.worker._stash_lock:
            s = self.stashed
            if s is None or (spec is not None and s is not spec):
                return None
            self.stashed = None
            for oid in s["return_ids"]:
                self.worker._stash_by_oid.pop(oid, None)
            return s

    def busy(self) -> bool:
        """True when ANY call is queued, stashed, or in flight — the sync
        bypass may only run when the channel is completely quiet (worker-
        side execution order must match submission order)."""
        return bool(self.deque) or self.inflight > 0 or self.stashed is not None

    async def _resolve(self) -> Optional[str]:
        """Poll the head until the actor is alive (with an address) or dead.
        Returns the address or None.

        No wall-clock deadline while the actor is pending/starting: actor
        startup is legitimately slow (worker spawn + heavy imports under
        host contention), and giving up would fail calls on an actor that
        is about to come up. If the actor truly never starts, the head
        marks it dead (spawn failure / init failure / node death) and the
        poll observes that (reference: submitter buffers calls until the
        GCS publishes the actor address, direct_actor_task_submitter.h:67)."""
        delay = 0.02
        warn_at = asyncio.get_running_loop().time() + cfg.worker_register_timeout_s
        while True:
            route = await self.worker.conn.request(
                {"t": "get_actor_route", "actor_id": self.actor_id}
            )
            if route is None or route["state"] == "dead":
                return None
            if route["state"] == "alive" and route["address"]:
                addr = route["address"]
                if not protocol.is_tcp_address(addr) and (
                    route["node_id"] != self.worker.node_id
                ):
                    return None  # unix socket on another machine
                return addr
            if warn_at is not None and asyncio.get_running_loop().time() > warn_at:
                warn_at = None
                logger.warning(
                    "actor %s still %s after %.0fs; calls will block until it "
                    "is scheduled (check cluster resources) or killed",
                    self.actor_id, route["state"], cfg.worker_register_timeout_s,
                )
            await asyncio.sleep(delay)
            delay = min(delay * 2, 0.5)

    async def _connect(self) -> bool:
        if self.conn is not None and not self.conn.closed:
            return True
        addr = await self._resolve()
        if addr is None:
            return False
        try:
            reader, writer = await protocol.open_stream(addr)
        except OSError:
            return False

        async def handler(msg):
            raise ValueError("unexpected push on direct actor channel")

        self.conn = protocol.Connection(
            reader, writer, handler, name=f"actor:{self.actor_id[:8]}"
        )
        self.conn.start()
        self.direct_addr = addr  # the sync bypass dials the same endpoint
        return True

    async def _resolve_deps(self, spec: dict) -> dict:
        return await _resolve_spec_deps(self.worker, spec)

    async def _consume(self):
        while True:
            while not self.deque:
                self._more.clear()
                await self._more.wait()
            spec = self.deque.popleft()
            if spec is None:
                return
            try:
                await self._submit_one(spec)
            except Exception:
                logger.exception("direct actor call failed; routing via head")
                self._to_head(spec)

    async def _submit_one(self, spec: dict):
        """Send in FIFO order but do NOT wait for the reply — replies are
        collected by a separate task per call, so calls pipeline exactly
        like the head path (and like the reference's in-flight queue)."""
        if self.head_routed or not await self._connect():
            self.head_routed = True
            self._to_head(spec)
            return
        self.inflight += 1
        self.inflight_tids.add(spec["task_id"])
        try:
            resolved = await self._resolve_deps(spec)
        except BaseException:
            self.inflight -= 1
            self.inflight_tids.discard(spec["task_id"])
            raise
        msg = {
            "t": "run_task",
            "task_id": spec["task_id"],
            "actor_id": self.actor_id,
            "method": spec["method"],
            "args": {"env": spec["args"], "resolved": resolved},
            "return_ids": spec["return_ids"],
            "trace_ctx": spec.get("trace_ctx"),
        }
        loop = asyncio.get_running_loop()
        fut = loop.create_task(self.conn.request(msg))
        loop.create_task(self._finish(spec, msg, fut))

    async def _finish(self, spec: dict, msg: dict, fut):
        """Collect the reply and settle the return objects. MUST terminate
        every return id one way or another — a get() may be blocked on the
        local pending event with no timeout.

        inflight is decremented BEFORE the result is cached: caching wakes
        the caller, and the caller's next submit must see a quiet channel
        (inflight==0) or the sync bypass never engages."""
        settled = [False]

        def settle():
            if not settled[0]:
                settled[0] = True
                self.inflight -= 1
                self.inflight_tids.discard(spec["task_id"])

        try:
            try:
                reply = await fut
            except Exception as e:
                # The connection broke mid-call (worker death / restart). Do
                # NOT resend: the actor may have already executed this call —
                # a replay would double-execute side effects (reference
                # semantics: in-flight actor tasks fail with ActorDiedError
                # on death; only max_task_retries opts into replays). Later
                # calls reconnect to the restarted actor via a fresh route.
                self.conn = None
                settle()
                await self._fail_returns(spec, f"worker died mid-call: {e!r}")
                return
            for _ in range(3):
                lost = reply.get("lost_deps")
                if not lost:
                    break
                # dep buffers were evicted before the actor could read them.
                # The user code never ran, so a resend is side-effect safe;
                # rebuild the deps from lineage first.
                ok = await self.worker.conn.request(
                    {"t": "reconstruct_objects", "object_ids": lost}
                )
                if not all(ok.get(oid) for oid in lost):
                    settle()
                    await self._fail_returns(spec, f"lost deps {lost} unrecoverable")
                    return
                # stale local envelopes point at the EVICTED buffers; the
                # head holds the reconstructed ones
                self.worker._invalidate_local(lost)
                msg["args"] = {
                    "env": spec["args"],
                    "resolved": await self._resolve_deps(spec),
                }
                reply = await self.conn.request(msg)
            if "results" not in reply:
                settle()
                await self._fail_returns(spec, f"bad reply {list(reply)}")
                return
            envs = reply["results"]
            if len(envs) != len(spec["return_ids"]):
                settle()
                await self._fail_returns(
                    spec,
                    f"actor returned {len(envs)} results for "
                    f"{len(spec['return_ids'])} return ids",
                )
                return
            settle()  # BEFORE caching: caching wakes the caller (see above)
            for oid, env in zip(spec["return_ids"], envs):
                self.worker._cache_local_object(oid, env)
                self.worker._enqueue_put(oid, env)
        except Exception as e:  # never leave pending events unsettled
            settle()
            try:
                await self._fail_returns(spec, f"direct call failed: {e!r}")
            except Exception:
                self.worker._release_pending(spec["return_ids"])
        finally:
            settle()
            # HANG-PROOFING: as in _TaskChannel._finish — any waiter a
            # missed settle left parked flips to the head-fetch route
            self.worker._release_pending(spec["return_ids"])
            # deps stay pinned until the actor has consumed (or we failed)
            await self._release_deps(spec)

    async def _fail_returns(self, spec: dict, reason: str, error=None):
        from ..exceptions import ActorDiedError

        err = serialization.serialize(
            error if error is not None else ActorDiedError(self.actor_id, reason)
        )
        err.is_error = True
        for oid in spec["return_ids"]:
            self.worker._cache_local_object(oid, err)
            self.worker._enqueue_put(oid, err)

    def _to_head(self, spec: dict):
        # release get() waiters: the result will come via the head, not the
        # local cache (events with no cached envelope mean "ask the head")
        self.worker._release_pending(spec["return_ids"])
        try:
            loop = asyncio.get_running_loop()
            # the head takes the caller's +1 at submit (the direct path
            # skipped it; head-path results don't carry it in put_object).
            # Acked: a silently lost submit orphans the call forever
            loop.create_task(_swallow_conn_errors(
                self.worker._acked_push(
                    {"t": "submit_actor_task", "spec": spec},
                    what=f"submit_actor_task {spec['task_id'][:8]}",
                )
            ))
            # release the direct-path dep pins AFTER the submit lands (the
            # handler pins deps synchronously on arrival)
            loop.create_task(self._release_deps(spec))
        except Exception:
            pass

    async def _release_deps(self, spec: dict):
        """Idempotent release of the dep refs taken at direct submit (both
        the direct send and the head fallback funnel through here)."""
        await _release_spec_deps(self.worker, spec)

    async def close(self):
        self.task.cancel()
        # un-stash so a flush timer firing later finds nothing
        self.claim_stash()
        if self.conn is not None:
            await self.conn.close()

    def cancel(self, tid: str) -> bool:
        """Cancel an actor method call (io loop). Queued caller-side or
        stashed (sync bypass): drop + settle returns. Sent to the actor:
        forward so the worker raises in the executing thread (a call still
        queued worker-side is remembered and dropped before it runs)."""
        loop = asyncio.get_running_loop()
        if self._cancel_from_deque(tid, loop):
            return True
        with self.worker._stash_lock:
            s = self.stashed if (
                self.stashed is not None and self.stashed.get("task_id") == tid
            ) else None
        if s is not None:
            if self.claim_stash(s) is not None:
                loop.create_task(self._cancel_spec(s))
                return True
            # claim lost: the sweeper flushed the stash to the deque between
            # our read and the claim — the spec is sitting in the queue now,
            # so re-scan it or the cancel silently falls through every branch
            if self._cancel_from_deque(tid, loop):
                return True
        # only claim tids this channel actually sent: reporting True for a
        # foreign tid would stop Worker.cancel_task before the head sees it
        if (
            tid in self.inflight_tids
            and self.conn is not None
            and not self.conn.closed
        ):
            loop.create_task(_swallow_conn_errors(
                self.conn.send({"t": "cancel_task", "task_id": tid})
            ))
            return True
        return False

    def _cancel_from_deque(self, tid: str, loop) -> bool:
        """Drop + settle a call still queued caller-side, if present."""
        for spec in list(self.deque):
            if spec is not None and spec.get("task_id") == tid:
                try:
                    self.deque.remove(spec)
                except ValueError:
                    continue
                loop.create_task(self._cancel_spec(spec))
                return True
        return False

    async def _cancel_spec(self, spec: dict):
        from ..exceptions import TaskCancelledError

        await self._fail_returns(
            spec, "cancelled",
            error=TaskCancelledError(f"task {spec['task_id']} was cancelled"),
        )
        await self._release_deps(spec)

    def flush_stale_stash(self, now: float) -> bool:
        """(io loop, via the sweeper) flush an unclaimed stash to the
        ordered queue — `remote()` without a matching get must still
        execute (side effects)."""
        s = self.stashed
        if s is None or now - s.get("_stash_t", now) < 0.008:
            return False
        s = self.claim_stash(s)
        if s is None:
            return False
        self.deque.append(s)
        self.wake()
        return True


class _TaskLease:
    """One granted worker lease (direct_task_transport.cc:191): a direct
    connection to a leased worker, reused across tasks until idle."""

    __slots__ = ("worker_id", "node_id", "conn", "inflight", "inflight_tids", "last_used")

    def __init__(self, worker_id: str, node_id: str, conn):
        self.worker_id = worker_id
        self.node_id = node_id
        self.conn = conn
        self.inflight = 0
        self.inflight_tids: set = set()  # task ids pushed, reply pending
        self.last_used = 0.0


class _TaskChannel:
    """Per-resource-shape direct transport for NORMAL tasks. Reference
    parity: CoreWorkerDirectTaskSubmitter (direct_task_transport.cc:588) —
    the caller asks the head for a worker LEASE, then pushes task specs
    straight to that worker and reuses the lease across tasks (:191). The
    head stays out of the per-task path entirely: results ride back inline,
    are forwarded in BATCHES to the head's object directory, and post-hoc
    task records (batched) keep lineage + observability intact.

    Leases grow up to cfg.direct_task_max_leases while every held lease is
    busy (parallelism parity with head dispatch); idle leases are released
    after cfg.task_lease_idle_ms so capacity returns to the cluster."""

    def __init__(self, worker: "Worker", resources: Dict[str, float]):
        self.worker = worker
        self.resources = resources
        self.queue: asyncio.Queue = asyncio.Queue()
        self.leases: List[_TaskLease] = []
        # ids cancelled while their spec was in dep-resolution limbo or the
        # lease-wait loop (not in the queue, not on a lease); _dispatch
        # drops them. Bounded: misses (task finished/not ours) would
        # otherwise accumulate — a dropped entry only downgrades a rare
        # cancel to best-effort. _resolving tracks specs parked in
        # _resolve_then_requeue so cancel() can claim them as ours
        self._cancelled_tids: "collections.OrderedDict" = collections.OrderedDict()
        self._resolving: set = set()
        self._acquiring = 0  # in-flight lease requests
        self._no_lease_until = 0.0
        self.max_leases = max(1, cfg.direct_task_max_leases)
        self._wake = asyncio.Event()  # set on task finish / lease grant
        loop = asyncio.get_running_loop()
        self.task = loop.create_task(self._consume())
        self._reaper = loop.create_task(self._idle_reaper())

    async def _consume(self):
        while True:
            spec = await self.queue.get()
            if spec is None:
                return
            try:
                await self._dispatch(spec)
            except Exception:
                logger.exception("direct task dispatch failed; routing via head")
                self._to_head(spec)

    async def _resolve_then_requeue(self, spec: dict):
        """Dependency wait OFF the dispatch path and WITHOUT holding a
        lease (reference: direct_task_transport resolves dependencies
        BEFORE requesting a worker lease). Parking with a lease held
        deadlocks: N dep-blocked tasks can pin every lease — and the
        cluster capacity behind them — while their producer tasks wait for
        that same capacity."""
        try:
            spec["_resolved"] = await _resolve_spec_deps(self.worker, spec)
        except exceptions.PlaneRequestTimeout:
            # the dep pull exhausted its deadline + retransmit budget: the
            # head connection is unresponsive for this request, but the
            # head's OWN dep resolution may still work (its handler waits
            # on local events, no round-trip) — route there instead of
            # parking the task forever
            logger.error(
                "dep pull for task %r exhausted its retransmit budget; "
                "routing via head", spec.get("task_id"),
            )
            self._resolving.discard(spec["task_id"])
            self._to_head(spec)
            return
        except Exception:
            logger.exception("dep resolution failed; routing via head")
            self._resolving.discard(spec["task_id"])
            self._to_head(spec)
            return
        self._resolving.discard(spec["task_id"])
        self.queue.put_nowait(spec)

    async def _dispatch(self, spec: dict):
        """One task per lease at a time (reference: a granted lease runs a
        single task; parallelism comes from MULTIPLE leases). Growth is
        launched in parallel for the visible backlog; when every lease is
        busy and growth is exhausted, wait for a completion — and after
        sustained saturation hand the spec to the head, which owns queuing."""
        if spec["task_id"] in self._cancelled_tids:
            self._cancelled_tids.pop(spec["task_id"], None)
            await self._cancel_spec(spec)
            return
        if spec.get("deps") and "_resolved" not in spec:
            # park dep waits concurrently; ready specs re-enter the queue
            self._resolving.add(spec["task_id"])
            asyncio.get_running_loop().create_task(
                self._resolve_then_requeue(spec)
            )
            return
        loop = asyncio.get_running_loop()
        saturated_since = None
        tid = spec["task_id"]
        # visible to cancel() while we wait for a lease below (same
        # "owned but not queued" window as the dep-resolution park)
        self._resolving.add(tid)
        try:
            while True:
                if tid in self._cancelled_tids:
                    # cancelled while this spec waited here for a free lease
                    self._cancelled_tids.pop(tid, None)
                    await self._cancel_spec(spec)
                    return
                # head connection down (crash + restart window): hold the
                # spec — a _to_head fallback would silently drop it on the
                # dead conn. The caller's next sync request() reconnects.
                while self.worker.conn is None or self.worker.conn.closed:
                    if not self.worker.connected:
                        return  # disconnected for real; waiters released
                    if not await self.worker._reconnect_async():
                        await asyncio.sleep(0.3)
                lease = self._pick_lease()
                if lease is not None and lease.inflight == 0:
                    self._resolving.discard(tid)
                    await self._submit_one(lease, spec)
                    return
                room = self.max_leases - len(self.leases) - self._acquiring
                if room > 0 and loop.time() >= self._no_lease_until:
                    want = min(self.queue.qsize() + 1, room)
                    for _ in range(want):
                        self._acquiring += 1
                        loop.create_task(self._acquire())
                if lease is None and self._acquiring == 0:
                    self._to_head(spec)  # no lease obtainable: head queues
                    return
                if saturated_since is None:
                    saturated_since = loop.time()
                elif loop.time() - saturated_since > 1.0:
                    # long-running tasks hold every lease; the head may have
                    # capacity beyond our lease cap — let it schedule/queue
                    self._to_head(spec)
                    return
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), 0.1)
                except asyncio.TimeoutError:
                    pass
        finally:
            self._resolving.discard(tid)

    def _pick_lease(self) -> Optional[_TaskLease]:
        live = [l for l in self.leases if l.conn is not None and not l.conn.closed]
        self.leases = live
        return min(live, key=lambda l: l.inflight, default=None)

    async def _acquire(self):
        grant = None
        try:
            grant = await self.worker.conn.request(
                {"t": "request_task_lease", "resources": self.resources}
            )
            loop = asyncio.get_running_loop()
            if not grant:
                self._no_lease_until = loop.time() + 0.2
                return
            addr = grant["address"]
            if not protocol.is_tcp_address(addr) and (
                grant["node_id"] != self.worker.node_id
            ):
                # unix socket on another machine: un-dialable from here
                await self._give_back(grant)
                grant = None
                self._no_lease_until = loop.time() + 5.0
                return

            async def handler(msg):
                raise ValueError("unexpected push on task lease connection")

            reader, writer = await protocol.open_stream(addr)
            conn = protocol.Connection(
                reader, writer, handler, name=f"lease:{grant['worker_id'][:8]}"
            )
            conn.start()
            lease = _TaskLease(grant["worker_id"], grant["node_id"], conn)
            lease.last_used = loop.time()
            self.leases.append(lease)
            grant = None  # owned by the lease now
        except Exception:
            # a granted-but-undialable lease MUST go back: leaking it leaves
            # the head holding the worker busy + its node share allocated
            if grant is not None:
                await self._give_back(grant)
            self._no_lease_until = asyncio.get_running_loop().time() + 0.2
        finally:
            self._acquiring -= 1
            self._wake.set()

    async def _give_back(self, grant: dict):
        try:
            await self.worker.conn.send(
                {"t": "release_task_lease", "worker_id": grant["worker_id"]}
            )
        except Exception:
            pass  # conn died; the head reclaims leases on conn close

    async def _submit_one(self, lease: _TaskLease, spec: dict):
        loop = asyncio.get_running_loop()
        # claim the lease synchronously (no await before the send): the
        # idle reaper must never see inflight==0 between pick and send —
        # it would close the conn under this task
        lease.inflight += 1
        lease.inflight_tids.add(spec["task_id"])
        lease.last_used = loop.time()
        resolved = spec.pop("_resolved", None) or {}
        msg = {
            "t": "run_task",
            "task_id": spec["task_id"],
            "fn_key": spec["fn_key"],
            "args": {"env": spec["args"], "resolved": resolved},
            "return_ids": spec["return_ids"],
            "trace_ctx": spec.get("trace_ctx"),
        }
        # record RUNNING at dispatch (batched): the head's observability —
        # and its OOM killing policy, which picks victims among running
        # tasks — must see direct-pushed tasks while they execute
        self.worker._enqueue_task_record(
            spec, "running", lease.worker_id, lease.node_id
        )
        fut = loop.create_task(lease.conn.request(msg))
        loop.create_task(self._finish(lease, spec, msg, fut))

    async def _finish(self, lease: _TaskLease, spec: dict, msg: dict, fut):
        """Settle every return id exactly once (a get() may be parked on
        the local pending event)."""
        requeued = False
        try:
            try:
                reply = await fut
            except Exception:
                # Lease broke mid-task (worker death): the task MAY have
                # executed. Reference semantics: rerun only when the user
                # opted into retries (max_retries), else WorkerCrashedError.
                lease.conn = None
                if spec["task_id"] in self._cancelled_tids:
                    # the worker died around a cancel (force kill, or the
                    # async raise landing as the process fell over): a
                    # cancelled task never retries
                    self._cancelled_tids.pop(spec["task_id"], None)
                    from ..exceptions import TaskCancelledError

                    await self._fail_returns(
                        spec, "cancelled", error_cls=TaskCancelledError
                    )
                    return
                used = spec.get("_retries_used", 0)
                if used < spec.get("max_retries", 0):
                    spec["_retries_used"] = used + 1
                    spec.pop("_resolved", None)  # deps re-resolve fresh
                    # requeue on OUR channel (with retry accounting), NOT
                    # _to_head: worker deaths cluster with head outages,
                    # and a send on a dead head conn drops the spec; the
                    # dispatch loop holds specs through reconnection
                    requeued = True  # the retry still needs its dep pins
                    self.queue.put_nowait(spec)
                else:
                    # an OOM kill by the head must surface as
                    # OutOfMemoryError, matching the head-routed path
                    kill_reason = None
                    try:
                        kill_reason = await self.worker.conn.request(
                            {"t": "worker_kill_reason",
                             "worker_id": lease.worker_id}
                        )
                    except Exception:
                        pass
                    if kill_reason:
                        from ..exceptions import OutOfMemoryError

                        await self._fail_returns(
                            spec, kill_reason, error_cls=OutOfMemoryError
                        )
                    else:
                        await self._fail_returns(spec, "worker died mid-task")
                return
            for _ in range(3):
                lost = reply.get("lost_deps")
                if not lost:
                    break
                # dep buffers evicted before execution: user code never ran,
                # resend (same lease) is side-effect free
                ok = await self.worker.conn.request(
                    {"t": "reconstruct_objects", "object_ids": lost}
                )
                if not all(ok.get(oid) for oid in lost):
                    await self._fail_returns(spec, f"lost deps {lost} unrecoverable")
                    return
                # stale local envelopes point at the EVICTED buffers; the
                # head holds the reconstructed ones
                self.worker._invalidate_local(lost)
                msg["args"] = {
                    "env": spec["args"],
                    "resolved": await _resolve_spec_deps(self.worker, spec),
                }
                reply = await lease.conn.request(msg)
            if "results" not in reply:
                await self._fail_returns(spec, f"bad reply {list(reply)}")
                return
            if len(reply["results"]) != len(spec["return_ids"]):
                # zip() would silently drop the unmatched ids and leave
                # their local waiters parked forever
                await self._fail_returns(
                    spec,
                    f"worker returned {len(reply['results'])} results for "
                    f"{len(spec['return_ids'])} return ids",
                )
                return
            for oid, env in zip(spec["return_ids"], reply["results"]):
                self.worker._cache_local_object(oid, env)
                self.worker._enqueue_put(oid, env)
            self.worker._enqueue_task_record(
                spec, "done", lease.worker_id, lease.node_id
            )
        except Exception as e:
            try:
                await self._fail_returns(spec, f"direct task failed: {e!r}")
            except Exception:
                self.worker._release_pending(spec["return_ids"])
        finally:
            lease.inflight -= 1
            lease.inflight_tids.discard(spec["task_id"])
            if not requeued:
                # settled one way or another: drop any cancel flag so a
                # too-late cancel doesn't linger (a requeued retry keeps
                # it — the re-dispatch check consumes it)
                self._cancelled_tids.pop(spec["task_id"], None)
                # HANG-PROOFING: no local waiter may stay parked after a
                # spec's terminal processing. Every success/failure path
                # above settles the events — but if any path ever misses
                # one (the class of bug behind a once-in-ten-runs stuck
                # get()), flip the waiter to the head-fetch route (the
                # results were forwarded there) instead of hanging forever
                self.worker._release_pending(spec["return_ids"])
            lease.last_used = asyncio.get_running_loop().time()
            self._wake.set()  # the dispatcher may be waiting for a free lease
            if not requeued:
                await _release_spec_deps(self.worker, spec)

    async def _fail_returns(self, spec: dict, reason: str, error_cls=None):
        from ..exceptions import WorkerCrashedError

        if error_cls is None:
            error_cls = WorkerCrashedError
        err = serialization.serialize(
            error_cls(f"task {spec['task_id']}: {reason}")
        )
        err.is_error = True
        for oid in spec["return_ids"]:
            self.worker._cache_local_object(oid, err)
            self.worker._enqueue_put(oid, err)
        self.worker._enqueue_task_record(spec, "failed", None, None)

    def _to_head(self, spec: dict):
        if spec["task_id"] in self._cancelled_tids:
            # cancel() already claimed this spec (it was parked resolving /
            # waiting): handing it to the head would run it anyway
            self._cancelled_tids.pop(spec["task_id"], None)
            try:
                asyncio.get_running_loop().create_task(self._cancel_spec(spec))
            except Exception:
                pass
            return
        # the head resolves deps itself: shipping pre-resolved envelopes
        # would bloat the socket + the head's stored TaskRecord
        spec.pop("_resolved", None)
        # the head takes the caller's +1 at submit; release local waiters so
        # get() routes through the head
        self.worker._release_pending(spec["return_ids"])
        try:
            loop = asyncio.get_running_loop()
            # acked + retransmitted: a silently lost submit_task frame means
            # the head never hears of the task — no record, outputs never
            # materialize, every dependent parks
            loop.create_task(_swallow_conn_errors(
                self.worker._acked_push(
                    {"t": "submit_task", "spec": spec},
                    what=f"submit_task {spec['task_id'][:8]}",
                )
            ))
            loop.create_task(_release_spec_deps(self.worker, spec))
        except Exception:
            pass

    def cancel(self, tid: str) -> bool:
        """Cancel a task owned by this channel (io loop). Queued caller-
        side: drop it and settle its returns. Pushed to a leased worker:
        forward the cancel so the worker raises it in the executing
        thread. In dep-resolution limbo: flag for _dispatch to drop.
        Reference: the direct-path half of CoreWorker::CancelTask."""
        loop = asyncio.get_running_loop()
        q = self.queue._queue  # type: ignore[attr-defined]
        for spec in list(q):
            if spec is not None and spec.get("task_id") == tid:
                try:
                    q.remove(spec)
                except ValueError:
                    continue  # consumer claimed it between list and remove
                loop.create_task(self._cancel_spec(spec))
                return True
        for lease in self.leases:
            if tid in lease.inflight_tids and lease.conn is not None:
                # flag BEFORE forwarding: if the worker dies instead of
                # replying (e.g. a force kill racing this send), _finish's
                # retry path must fail the task as cancelled, not rerun it
                # on a fresh lease. _finish pops the flag when the spec
                # settles, whatever the outcome
                _flag_bounded(self._cancelled_tids, tid)
                loop.create_task(_swallow_conn_errors(
                    lease.conn.send({"t": "cancel_task", "task_id": tid})
                ))
                return True
        # flag for _dispatch's checks (dep-resolution limbo, lease-wait
        # loop). When the spec is verifiably ours (parked resolving), the
        # cancel WILL take effect -> report True; otherwise best-effort
        _flag_bounded(self._cancelled_tids, tid)
        return tid in self._resolving

    async def _cancel_spec(self, spec: dict):
        from ..exceptions import TaskCancelledError

        await self._fail_returns(
            spec, "cancelled by ray_tpu.cancel()", error_cls=TaskCancelledError
        )
        await _release_spec_deps(self.worker, spec)

    async def _idle_reaper(self):
        idle_s = cfg.task_lease_idle_ms / 1000.0
        while True:
            await asyncio.sleep(max(idle_s / 2, 0.05))
            now = asyncio.get_running_loop().time()
            # retire WITHOUT awaiting between the idle check and removal
            # from self.leases: an await there would let the dispatcher
            # submit onto a lease this loop is about to close
            retiring: List[_TaskLease] = []
            keep: List[_TaskLease] = []
            for lease in self.leases:
                if lease.conn is None or lease.conn.closed:
                    continue
                if lease.inflight == 0 and now - lease.last_used > idle_s:
                    retiring.append(lease)
                else:
                    keep.append(lease)
            self.leases = keep
            for lease in retiring:
                try:
                    await self.worker.conn.send(
                        {"t": "release_task_lease", "worker_id": lease.worker_id}
                    )
                except Exception:
                    pass
                await lease.conn.close()

    async def close(self):
        self.task.cancel()
        self._reaper.cancel()
        for lease in self.leases:
            if lease.conn is not None:
                try:
                    await lease.conn.close()
                except Exception:
                    pass
        self.leases = []


async def _resolve_spec_deps(worker: "Worker", spec: dict) -> dict:
    """Resolve dep envelopes for a direct push (local cache first, head
    for the rest) — shared by the actor and task direct channels.

    The head request is instrumented AND recoverable: every request/reply
    pair on the head connection carries a monotonic rid; a reply missing
    past data_plane_request_warn_s logs a loud repeating error naming the
    orphaned get_objects request (rid, owning task, dep ids), and past
    data_plane_request_deadline_s the request is RETRANSMITTED with the
    same rid (get_objects is idempotent — the fresh execution answers even
    if the original handler parked on a lost wakeup, the historical wedge
    here). Exhausting the retransmit budget surfaces PlaneRequestTimeout,
    which _resolve_then_requeue converts into head-side routing — the task
    falls back to the head's own dep resolution instead of vanishing."""
    resolved = {}
    missing = []
    for oid in spec.get("deps", []):
        env = worker._local_objects.get(oid)
        if env is not None:
            resolved[oid] = env
        else:
            missing.append(oid)
    if missing:
        warn_s = float(cfg.data_plane_request_warn_s)
        deadline_s = float(cfg.data_plane_request_deadline_s)
        envs = await worker.conn.request(
            {"t": "get_objects", "object_ids": missing},
            warn_after_s=warn_s if warn_s > 0 else None,
            warn_tag=(
                f"get_objects for task {spec.get('task_id')!r} "
                f"({len(missing)} deps: "
                f"{[str(o)[:16] for o in missing[:4]]}{'...' if len(missing) > 4 else ''})"
            ),
            deadline_s=deadline_s if deadline_s > 0 else None,
            retries=int(cfg.data_plane_request_retries),
        )
        resolved.update(dict(zip(missing, envs)))
    return resolved


async def _release_spec_deps(worker: "Worker", spec: dict):
    """Idempotent release of the dep refs taken at direct submit."""
    if spec.get("deps") and not spec.get("_deps_released"):
        spec["_deps_released"] = True
        try:
            await worker.conn.send(
                {"t": "remove_refs", "counts": {d: 1 for d in spec["deps"]}}
            )
        except Exception:
            pass  # conn died (shutdown/head restart); refs reconcile later


class Worker:
    """The global per-process runtime."""

    def __init__(self):
        self.mode: Optional[str] = None
        self.connected = False
        self.job_id = JobID.from_int(os.getpid() % (2**31))
        self.node_id: Optional[str] = None
        self.session_dir: Optional[str] = None
        self.io: Optional[EventLoopThread] = None
        self.conn: Optional[protocol.Connection] = None
        self.node = None  # driver-only: the Node supervisor
        self._fn_exported: Dict[str, bool] = {}
        import weakref

        self._export_keys: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self.current_actor = None
        self.current_actor_id: Optional[str] = None
        self.current_task_id: Optional[str] = None
        self.namespace: str = ""
        # job-level default runtime_env (tasks/actors inherit it when they
        # don't specify their own)
        self.default_runtime_env: Optional[dict] = None
        self._lock = threading.RLock()
        self._shm = None
        self._shm_tried = False
        # direct-transport state: per-actor channels + locally cached result
        # envelopes (bounded; the head's ObjectDirectory stays the source of
        # truth for every other process)
        self._actor_channels: Dict[str, _ActorChannel] = {}
        # bulk plane: per-node blocking-socket POOLS to peer agents' buffer
        # servers (object_manager.h:117 — object bytes move node-to-node,
        # the head only resolves locations). _peer_info caches each node's
        # resolved {addr, shm_session}; _peer_planes caches same-host
        # attachments to a peer node's shm store (colocated clusters pull
        # slab-to-slab, no TCP at all).
        self._peer_conns: Dict[str, list] = {}
        self._peer_info: Dict[str, dict] = {}
        self._peer_planes: Dict[str, Any] = {}
        self._peer_sock_locks: Dict[str, threading.Lock] = {}
        self._peer_lock = threading.Lock()
        # direct normal-task channels keyed by resource shape
        # (direct_task_transport.cc:588) + batched head forwards (io-loop
        # state only)
        self._task_channels: Dict[Any, _TaskChannel] = {}
        self._put_batch: Dict[str, Any] = {}  # oid -> envelope (un-flushed)
        self._record_batch: List[dict] = []
        self._flush_handle = None
        # sync-bypass state: stashed (deferred) actor calls by return id +
        # per-thread blocking sockets to actor workers
        self._stash_lock = threading.Lock()
        self._stash_by_oid: Dict[str, Tuple[Any, dict]] = {}
        self._bypass_local = threading.local()
        self._batch_lock = threading.Lock()  # _put/_record/_ref batches
        self._ref_batch: Dict[str, int] = {}
        self._sweeper_on = False
        self._sweeper_loop = None
        self._sweep_task = None
        self._reconnecting = False
        self._local_objects: "collections.OrderedDict[str, Any]" = collections.OrderedDict()
        # in-flight direct calls: return id -> Event set when the reply
        # lands locally (get() waits here instead of round-tripping the head)
        self._local_pending: Dict[str, threading.Event] = {}
        self._local_lock = threading.Lock()
        # refs whose __del__ fired: processed by _drain_dead_refs from
        # normal contexts (a GC-time __del__ may run while ITS OWN thread
        # holds the locks above — appending to a deque is lock-free)
        self._dead_refs: collections.deque = collections.deque()
        # pubsub: channel -> callbacks invoked on pushed messages
        # (reference: src/ray/pubsub subscriber.h:329); one dispatcher
        # thread drains a queue so callbacks run in publish order
        self._pubsub_callbacks: Dict[str, List[Any]] = {}
        self._pubsub_queue: Optional[Any] = None

    def _cache_local_object(self, oid: str, env) -> None:
        with self._local_lock:
            self._local_objects[oid] = env
            self._local_objects.move_to_end(oid)
            while len(self._local_objects) > 1024:
                self._local_objects.popitem(last=False)
            ev = self._local_pending.pop(oid, None)
        if ev is not None:
            ev.set()

    def _invalidate_local(self, oids) -> None:
        """Drop stale locally-cached envelopes (e.g. after their shm
        buffers were evicted + reconstructed: the head now holds fresh
        envelopes; the local copies point at dead buffers)."""
        with self._local_lock:
            for oid in oids:
                self._local_objects.pop(oid, None)

    def _release_pending(self, oids) -> None:
        with self._local_lock:
            evs = [self._local_pending.pop(oid, None) for oid in oids]
        for ev in evs:
            if ev is not None:
                ev.set()

    @property
    def shm(self):
        """Lazy client for the C++ shared-memory object plane (None if
        disabled or unavailable)."""
        if self._shm_tried:
            return self._shm
        self._shm_tried = True
        from .shm import connect_for_session

        self._shm = connect_for_session(self.session_dir)
        return self._shm

    # ------------------------------------------------------------------
    # connection
    # ------------------------------------------------------------------

    def connect_driver(self, node, namespace: str = ""):
        self.mode = MODE_DRIVER
        self._fn_exported.clear()
        self._export_keys.clear()
        if self._shm is not None:
            try:
                self._shm.disconnect()
            except Exception:
                pass
        self._shm = None
        self._shm_tried = False
        self.node = node
        self.io = node.io
        self.session_dir = node.session_dir
        self.namespace = namespace
        self.conn = self.io.run(self._open_conn(node.socket_path))
        info = self.request(
            {"t": "register_driver", "proto": protocol.PROTOCOL_VERSION}
        )
        self.node_id = info["node_id"]
        self.connected = True

    def connect_existing(self, socket_path: str, namespace: str = ""):
        """Attach as an ADDITIONAL driver to a running head — via the
        session unix socket (job submission, `init(address="auto")`) or a
        TCP host:port (remote drivers; reference: worker.py:1186 address
        resolution + util/client). Owns its own IO thread; the head
        outlives this client."""
        import os

        self.mode = MODE_DRIVER
        self._fn_exported.clear()
        self._export_keys.clear()
        if self._shm is not None:
            try:
                self._shm.disconnect()
            except Exception:
                pass
        self._shm = None
        self._shm_tried = False
        self.node = None
        self.io = EventLoopThread()
        self._owns_io = True
        # remote (TCP) drivers have no local session dir: no shm plane —
        # objects ride the socket inline and buffers are pulled via the head
        self.session_dir = (
            None if protocol.is_tcp_address(socket_path) else os.path.dirname(socket_path)
        )
        self.namespace = namespace
        self._remote_address = socket_path  # reconnect target (head restart)
        self.conn = self.io.run(self._open_conn(socket_path))
        info = self.request(
            {"t": "register_driver", "proto": protocol.PROTOCOL_VERSION}
        )
        self.node_id = info["node_id"]
        if os.environ.get("RAY_TPU_JOB_RUNTIME_ENV"):
            import json

            self.default_runtime_env = json.loads(os.environ["RAY_TPU_JOB_RUNTIME_ENV"])
        self.connected = True

    def connect_worker(
        self, socket_path: str, worker_id: str, io: EventLoopThread, conn, node_id=None
    ):
        self.mode = MODE_WORKER
        self.io = io
        self.conn = conn
        self.node_id = node_id
        self.connected = True

    async def _open_conn(self, socket_path: str) -> protocol.Connection:
        reader, writer = await protocol.open_stream(socket_path)

        async def handler(msg):
            return await self._handle_push(msg)

        conn = protocol.Connection(reader, writer, handler, name="head")
        conn.start()
        return conn

    async def _handle_push(self, msg):
        if msg.get("t") == "pub":
            self.dispatch_pub(msg)
            return None
        raise ValueError(f"driver got unexpected message {msg.get('t')}")

    def dispatch_pub(self, msg: dict) -> None:
        """Deliver a pushed channel message to local subscriber callbacks.
        Runs on the IO loop (or the worker's protocol loop) — callbacks run
        on ONE daemon dispatcher thread, preserving publish order (a thread
        per message could apply seq=1 after seq=2, stranding subscribers on
        a stale snapshot) and keeping user code off the protocol loop."""
        if not self._pubsub_callbacks.get(msg["channel"]):
            return
        with self._lock:
            if self._pubsub_queue is None:
                import queue as _queue

                self._pubsub_queue = _queue.SimpleQueue()
                threading.Thread(
                    target=self._pubsub_dispatch_loop, daemon=True, name="pubsub-cb"
                ).start()
        self._pubsub_queue.put(msg)

    def _pubsub_dispatch_loop(self):
        while True:
            msg = self._pubsub_queue.get()
            for cb in list(self._pubsub_callbacks.get(msg["channel"], ())):
                try:
                    cb(msg["seq"], msg["data"])
                except Exception:
                    logger.exception("pubsub callback failed for %s", msg["channel"])

    # ------------------------------------------------------------------
    # pubsub (reference: src/ray/pubsub; serve long-poll rides poll_channel)
    # ------------------------------------------------------------------

    def publish(self, channel: str, data) -> int:
        return self.request({"t": "publish", "channel": channel, "data": data})

    def subscribe(self, channel: str, callback) -> Tuple[int, Any]:
        """Register a push callback(seq, data); returns the (seq, data)
        snapshot at subscribe time (0, None if never published)."""
        self._pubsub_callbacks.setdefault(channel, []).append(callback)
        snap = self.request({"t": "subscribe", "channel": channel})
        return snap["seq"], snap["data"]

    def unsubscribe(self, channel: str) -> None:
        self._pubsub_callbacks.pop(channel, None)
        try:
            self.request({"t": "unsubscribe", "channel": channel})
        except Exception:
            pass

    def start_log_forwarding(self) -> None:
        """Print workers' stdout/stderr in this driver, prefixed with the
        worker id (reference: worker.py print redirection fed by the log
        monitor). Subscribes to the head's "__logs__" channel."""

        def on_log(seq, entry):
            prefix = f"({entry['worker_id']}) "
            text = entry["data"]
            for line in text.splitlines():
                print(prefix + line, flush=True)

        try:
            self.subscribe("__logs__", on_log)
        except Exception:
            pass  # logs are best-effort; never fail init over them

    def poll_channel(self, channel: str, last_seq: int = 0, timeout: float = 30.0):
        """Long-poll for a publish newer than last_seq. Returns (seq, data)
        or None on timeout (caller re-polls)."""
        reply = self.request(
            {"t": "poll_channel", "channel": channel, "last_seq": last_seq,
             "timeout": timeout},
            timeout=timeout + 10.0,
        )
        if reply.get("timeout"):
            return None
        return reply["seq"], reply["data"]

    def request(self, msg: dict, timeout: Optional[float] = None,
                **req_kwargs) -> Any:
        if self._dead_refs:
            self._drain_dead_refs()
        if not self.conn or self.conn.closed:
            # a remote driver whose head connection dropped (head crash +
            # restart-from-snapshot) re-registers at the same address
            # (reference: GCS reconnect, gcs_server.cc:130-178)
            if not self._try_reconnect():
                raise exceptions.RayTpuError(
                    "ray_tpu is not connected (call ray_tpu.init())"
                )
        return self.io.run(self.conn.request(msg, timeout, **req_kwargs))

    def _fetch_kwargs(self) -> dict:
        """Retransmit arming for idempotent head fetches (get_objects and
        friends): a lost reply re-executes the read instead of wedging the
        sync caller forever."""
        deadline_s = float(cfg.data_plane_request_deadline_s)
        if deadline_s <= 0:
            return {}
        return {
            "deadline_s": deadline_s,
            "retries": int(cfg.data_plane_request_retries),
        }

    async def _acked_push(self, msg: dict, what: str = "") -> None:
        """State-bearing push (result envelopes, refcount deltas, task
        submits) as an ACKED request riding the deadline/retransmit plane.
        These used to be fire-and-forget sends, and ONE silently lost
        put_objects frame stranded cluster state: the producer's results
        never reached the head, so every dependent's get_objects parked
        forever — the repartition-exchange wedge. The head dedups
        retransmits by rid (mutating types), so redelivery is safe. Falls
        back to fire-and-forget when deadlines are disabled."""
        if self.conn is None or self.conn.closed:
            return
        kw = self._fetch_kwargs()
        what = what or str(msg.get("t"))
        if not kw:
            await self.conn.send(msg)
            return
        try:
            await self.conn.request(msg, warn_tag=what, **kw)
        except exceptions.PlaneRequestTimeout:
            logger.error(
                "state push %r exhausted its retransmit budget; head "
                "state may lag until reconnect", what,
            )
            raise

    def plane_pending_summary(self):
        """Outstanding plane rids across EVERY connection this worker
        holds — the head conn plus direct task-lease and actor channels
        (a wedge can park on any of them). Rows carry the connection
        name; consumed by the tests' hang-guard dump."""
        out = []

        def _collect(conn):
            if conn is None or conn.closed:
                return
            for row in conn.pending_summary():
                row["conn"] = conn.name or "?"
                out.append(row)

        _collect(self.conn)
        for ch in list(self._task_channels.values()):
            for lease in list(ch.leases):
                _collect(lease.conn)
        for ach in list(self._actor_channels.values()):
            _collect(ach.conn)
        return out

    def _try_reconnect(self) -> bool:
        if self.io is None:
            return False
        try:
            return self.io.run(self._reconnect_async())
        except Exception:
            return False

    async def _reconnect_async(self) -> bool:
        """(io loop) redial + re-register against the head address. Used by
        the sync request() path AND proactively by channel consumers — the
        sync bypass can keep actor calls flowing with the head DOWN, so a
        user-thread request is not guaranteed to ever trigger reconnect."""
        if self._reconnecting:
            while self._reconnecting:  # single dialer; others wait on it
                await asyncio.sleep(0.2)
            return self.conn is not None and not self.conn.closed
        addr = getattr(self, "_remote_address", None)
        if not (self.connected and addr):
            return False
        self._reconnecting = True
        try:
            loop = asyncio.get_running_loop()
            deadline = loop.time() + cfg.head_reconnect_timeout_s
            while loop.time() < deadline and self.connected:
                try:
                    conn = await self._open_conn(addr)
                    info = await conn.request(
                        {"t": "register_driver",
                         "proto": protocol.PROTOCOL_VERSION},
                        10,
                    )
                except Exception:
                    await asyncio.sleep(0.5)
                    continue
                self.conn = conn
                self.node_id = info["node_id"]
                # a restarted head restores fn/cls exports from its
                # snapshot; clearing the memo keeps us correct even when
                # it could not
                self._fn_exported.clear()
                logger.warning("reconnected to head at %s", addr)
                return True
            return False
        finally:
            self._reconnecting = False

    # ------------------------------------------------------------------
    # batched head forwards (io-loop only; reference: task_event_buffer.h
    # batching — one head message per flush window, not per call)
    # ------------------------------------------------------------------

    def _enqueue_put(self, oid: str, env) -> None:
        """Thread-safe: io-loop producers (channel _finish) AND caller
        threads (sync bypass) append; the io loop flushes."""
        with self._batch_lock:
            self._put_batch[oid] = env
            n = len(self._put_batch) + len(self._record_batch)
        if threading.current_thread() is self.io.thread:
            self._schedule_flush(n)
        else:
            self._ensure_sweeper()

    def _enqueue_task_record(self, spec: dict, state: str, worker_id, node_id) -> None:
        with self._batch_lock:
            self._record_batch.append(
                {"spec": spec, "state": state, "worker_id": worker_id,
                 "node_id": node_id}
            )
            n = len(self._put_batch) + len(self._record_batch)
        if threading.current_thread() is self.io.thread:
            self._schedule_flush(n)
        else:
            self._ensure_sweeper()

    def _schedule_flush(self, n: int) -> None:
        if n >= 128:
            if self._flush_handle is not None:
                self._flush_handle.cancel()
                self._flush_handle = None
            asyncio.ensure_future(self._flush_batches())
            return
        if self._flush_handle is None:
            loop = asyncio.get_running_loop()
            self._flush_handle = loop.call_later(
                0.002, lambda: asyncio.ensure_future(self._flush_batches())
            )

    def _ensure_sweeper(self) -> None:
        """(any thread) make sure the io-loop sweeper is ticking. The
        sweeper amortizes caller-thread -> io-loop wakeups: the sync bypass
        produces a stash + a result forward PER CALL, and a call_soon wake
        for each would cost more than the bypass saves. One flag check per
        call, one loop wake per sweeper lifetime."""
        # the flag is only trustworthy for the CURRENT io loop: a previous
        # session's loop may have died before the sweeper's finally ran,
        # leaving the flag stuck True forever (symptom: stashes/batches
        # never flush after re-init)
        if self._sweeper_on and self._sweeper_loop is self.io.loop:
            return
        self._sweeper_on = True
        self._sweeper_loop = self.io.loop

        def _start():
            self._sweep_task = asyncio.ensure_future(self._sweep())

        try:
            self.io.loop.call_soon_threadsafe(_start)
        except RuntimeError:  # loop shut down
            self._sweeper_on = False

    async def _sweep(self):
        try:
            idle_ticks = 0
            while idle_ticks < 12:  # ~100ms of quiet, then stand down
                await asyncio.sleep(0.008)
                did = False
                if self._dead_refs:
                    self._drain_dead_refs()
                    did = True
                now = time.monotonic()
                for ch in list(self._actor_channels.values()):
                    if ch.flush_stale_stash(now):
                        did = True
                with self._batch_lock:
                    pending = bool(
                        self._put_batch or self._record_batch or self._ref_batch
                    )
                if pending:
                    await self._flush_batches()
                    did = True
                idle_ticks = 0 if did else idle_ticks + 1
        finally:
            self._sweeper_on = False
            # close the stand-down race: a producer that enqueued between
            # this sweep's last check and the flag reset saw _sweeper_on
            # True and did not wake the loop — re-arm if anything is pending
            if self.connected:
                with self._batch_lock:
                    pending = bool(
                        self._put_batch or self._record_batch or self._ref_batch
                    )
                if pending or self._dead_refs or any(
                    ch.stashed is not None
                    for ch in self._actor_channels.values()
                ):
                    self._ensure_sweeper()

    async def _flush_batches(self) -> None:
        if self._dead_refs:
            self._drain_dead_refs()
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        with self._batch_lock:
            puts, self._put_batch = list(self._put_batch.items()), {}
            recs, self._record_batch = self._record_batch, []
            refs, self._ref_batch = self._ref_batch, {}
        if self.conn is None or self.conn.closed:
            return
        try:
            # puts BEFORE records/refs: lineage entries must never point at
            # task records whose results the head hasn't seen, and a remove
            # must not precede the put carrying the caller's +1. Acked +
            # retransmitted: a lost put_objects frame strands every
            # dependent of these results (the repartition-exchange wedge)
            if puts:
                await self._acked_push(
                    {"t": "put_objects", "objects": puts}, what="put_objects"
                )
            if recs:
                await self._acked_push(
                    {"t": "record_tasks", "records": recs}, what="record_tasks"
                )
            if refs:
                await self._acked_push(
                    {"t": "remove_refs", "counts": refs}, what="remove_refs"
                )
        except Exception:
            pass  # conn died (or budget exhausted, already logged);
            # disconnect() settles local waiters

    # ------------------------------------------------------------------
    # bulk plane: direct node-to-node buffer pulls
    # ------------------------------------------------------------------

    def fetch_buffers_direct(self, node: str, refs) -> Optional[dict]:
        """Pull shm buffers STRAIGHT from the owning node (reference:
        object_manager.h:117 / pull_manager.h:52 — the head only resolves
        the location). `refs` are ShmBufferRefs (name + size; sizes are
        immutable once sealed, so the consumer can preallocate slab space).

        Paths, fastest first: (1) same-host — the peer's shm plane lives on
        this machine: read its slab directly, one copy into ours; (2) TCP —
        recv_into writable slab views (create_uninitialized), striping
        buffers >= bulk_stripe_min_bytes across bulk_stripe_sockets
        parallel READ_RANGE sockets and pipelining the rest on one socket.

        Returns {name: buffer | None-if-unknown-at-peer}, or None when no
        direct path exists / the pull failed midway (caller falls back to
        the head relay)."""
        info = self._peer_info_for(node)
        if not info or not info.get("addr"):
            return None
        if cfg.bulk_same_host:
            out = self._fetch_same_host(node, info, refs)
            if out is not None:
                return out
        with self._peer_lock:
            lock = self._peer_sock_locks.setdefault(node, threading.Lock())
        with lock:
            try:
                return self._fetch_over_sockets(node, info["addr"], refs)
            except Exception:
                self._drop_peer(node)
                return None

    def _peer_info_for(self, node: str) -> Optional[dict]:
        """Resolve (and cache) a peer's bulk address + shm session; the
        cache is dropped with _drop_peer, so a restarted agent's new port
        is re-resolved on the retry."""
        with self._peer_lock:
            info = self._peer_info.get(node)
        if info is not None:
            return info
        try:
            addrs = self.request(
                {"t": "buffer_addrs", "nodes": [node]}, timeout=30
            )
        except Exception:
            return None
        info = addrs.get(node)
        if not info:
            return None
        with self._peer_lock:
            info = self._peer_info.setdefault(node, info)
        return info

    def _fetch_same_host(self, node: str, info: dict, refs) -> Optional[dict]:
        """Colocated peer plane: serve buffers straight out of the peer
        node's own shm store (or mmap its spill files) — the bulk plane
        with ZERO copies and no socket. The returned views hold a
        process-shared ref on each entry (ObjectEntry.refs), so the peer
        store can neither evict nor spill them while the consumer reads;
        the view's finalizer releases the pin. None = path unavailable
        (plane not on this host, or the store was destroyed under us):
        try sockets."""
        from . import shm as shm_mod

        session = info.get("shm_session")
        if not session:
            return None
        with self._peer_lock:
            plane = self._peer_planes.get(node)
        if plane is None:
            plane = shm_mod.attach_peer_plane(session)
            if plane is None:
                return None
            with self._peer_lock:
                plane = self._peer_planes.setdefault(node, plane)
        resolved: Dict[str, Any] = {}
        hit = False
        for ref in refs:
            mv = plane.get(shm_mod.ShmBufferRef(name=ref.name, size=0))
            path = "direct"
            if mv is None:
                mv = plane.read_spilled(ref.name)
                path = "spilled"
            if mv is None:
                resolved[ref.name] = None
                continue
            hit = True
            resolved[ref.name] = mv
            _bulk_account(path, len(mv))
        if refs and not hit:
            # every ref missed: most likely we attached a fresh store
            # re-created after the peer died — don't trust the misses
            return None
        return resolved

    def _fetch_over_sockets(self, node: str, addr: str, refs) -> dict:
        """TCP pull with recv-into-slab destinations. Small buffers ride
        one socket with pipelined READ_RANGE requests; large ones stripe
        across parallel sockets. Raises on any transport failure (caller
        drops the peer and falls back to the relay)."""
        local = self.shm
        dests = []
        try:
            for ref in refs:
                pending = None
                if local is not None:
                    pending = local.create_uninitialized(ref.name, ref.size)
                dests.append(pending or _HeapDest(ref.name, ref.size))
            stripe_min = max(1, cfg.bulk_stripe_min_bytes)
            nstripes = max(1, cfg.bulk_stripe_sockets)
            small = [
                (r, d) for r, d in zip(refs, dests) if r.size < stripe_min
            ]
            big = [
                (r, d) for r, d in zip(refs, dests) if r.size >= stripe_min
            ]
            missing: set = set()
            if small:
                socks = self._checkout_sockets(node, addr, 1)
                try:
                    self._pull_pipelined(socks[0], small, missing)
                except BaseException:
                    self._close_sockets(socks)
                    raise
                self._checkin_sockets(node, socks)
            for ref, dest in big:
                n = min(nstripes, max(1, ref.size // stripe_min)) if ref.size else 1
                socks = self._checkout_sockets(node, addr, n)
                try:
                    self._pull_striped(socks, ref, dest, missing)
                except BaseException:
                    self._close_sockets(socks)
                    raise
                self._checkin_sockets(node, socks)
            resolved: Dict[str, Any] = {}
            for ref, dest in zip(refs, dests):
                if ref.name in missing:
                    dest.abort()
                    resolved[ref.name] = None
                    continue
                committed = dest.commit()
                if committed is not None and local is not None:
                    mv = local.get(committed)
                    if mv is None:  # evicted before we could map it
                        raise ConnectionError(
                            f"{ref.name} vanished from the local slab"
                        )
                    resolved[ref.name] = mv
                else:
                    resolved[ref.name] = dest.view  # heap fallback
            return resolved
        except BaseException:
            for dest in dests:
                try:
                    dest.abort()
                except Exception:
                    pass
            raise

    @staticmethod
    def _pull_pipelined(sock, pairs, missing: set) -> None:
        """Send ALL requests, then drain the replies in order — one RTT of
        latency for the whole batch instead of one per buffer."""
        from . import bulk

        sock.sendall(
            b"".join(
                bulk.pack_request(bulk.OP_READ_RANGE, r.name, 0, r.size)
                for r, _ in pairs
            )
        )
        for ref, dest in pairs:
            n = bulk.read_reply_size(sock)
            if n == bulk.MISSING:
                missing.add(ref.name)
                continue
            if n != ref.size:
                raise ConnectionError(
                    f"peer served {n} bytes for {ref.name} (want {ref.size})"
                )
            if ref.size:
                bulk.recv_exact_into(sock, dest.view)
            _bulk_account("direct", ref.size)

    @staticmethod
    def _pull_striped(socks, ref, dest, missing: set) -> None:
        """One large buffer across N parallel sockets: disjoint READ_RANGE
        stripes land concurrently in disjoint subviews of the destination
        slab mapping (recv_into releases the GIL, so stripes overlap)."""
        from . import bulk

        n = len(socks)
        if n == 1:
            rc = bulk.read_range_into(socks[0], ref.name, 0, dest.view)
            if rc == bulk.MISSING:
                missing.add(ref.name)
                return
            _bulk_account("direct", ref.size)
            return
        per = -(-ref.size // n)
        per += (-per) % (1 << 20)  # 1MB-align stripe bounds
        ranges = [
            (off, min(per, ref.size - off)) for off in range(0, ref.size, per)
        ]
        results: list = [None] * len(ranges)

        def _one(i, off, length):
            try:
                results[i] = bulk.read_range_into(
                    socks[i], ref.name, off, dest.view[off : off + length]
                )
            except BaseException as e:  # surfaced by the joiner below
                results[i] = e

        threads = [
            threading.Thread(
                target=_one, args=(i, off, length), daemon=True
            )
            for i, (off, length) in enumerate(ranges)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        codes = []
        for r in results:
            if isinstance(r, BaseException):
                raise r
            codes.append(r)
        if any(c == bulk.MISSING for c in codes):
            if all(c == bulk.MISSING for c in codes):
                missing.add(ref.name)
                return
            raise ConnectionError(
                f"peer lost {ref.name} mid-striped-pull"
            )
        _bulk_account("striped", ref.size)

    def _checkout_sockets(self, node: str, addr: str, n: int) -> list:
        """Take n sockets to `node` from the pool, dialing the shortfall."""
        from . import bulk

        with self._peer_lock:
            pool = self._peer_conns.setdefault(node, [])
            socks = [pool.pop() for _ in range(min(n, len(pool)))]
        try:
            while len(socks) < n:
                socks.append(bulk.connect(addr))
        except BaseException:
            self._close_sockets(socks)
            raise
        return socks

    def _checkin_sockets(self, node: str, socks: list) -> None:
        with self._peer_lock:
            self._peer_conns.setdefault(node, []).extend(socks)

    @staticmethod
    def _close_sockets(socks) -> None:
        for s in socks:
            try:
                s.close()
            except Exception:
                pass

    def _drop_peer(self, node: str) -> None:
        """Forget everything cached about a peer (sockets, resolved addr,
        attached plane): the next pull re-resolves from the head — THE
        re-resolution path after an agent restart rebinds its port."""
        with self._peer_lock:
            socks = self._peer_conns.pop(node, [])
            self._peer_info.pop(node, None)
            plane = self._peer_planes.pop(node, None)
        self._close_sockets(socks)
        if plane is not None:
            try:
                plane.disconnect()
            except Exception:
                pass

    # legacy name used by a few callers/tests
    def _drop_peer_socket(self, node: str) -> None:
        self._drop_peer(node)

    def send(self, msg: dict):
        if self.conn is None or self.conn.closed or self.io is None:
            return
        try:
            self.io.post(_swallow_conn_errors(self.conn.send(msg)))
        except RuntimeError:
            pass  # loop shut down

    def cancel_task(self, object_ref, force: bool = False) -> bool:
        """ray_tpu.cancel() entry (reference: python/ray/_private/worker.py
        cancel -> CoreWorker::CancelTask). Direct-path tasks are chased
        caller-side first (queued specs dropped, in-flight ones forwarded
        to their leased worker / actor); head-routed and already-recorded
        tasks go through the head, which also owns force=True (kill the
        worker)."""
        tid = object_ref.task_id()

        async def _try_channels():
            for ch in list(self._task_channels.values()):
                if ch.cancel(tid):
                    return True
            for ch in list(self._actor_channels.values()):
                if ch.cancel(tid):
                    return True
            return False

        found = False
        if self.io is not None and (self._task_channels or self._actor_channels):
            try:
                found = self.io.run(_try_channels(), timeout=10)
            except Exception:
                found = False
        if found and not force:
            return True
        try:
            head_found = self.request(
                {"t": "cancel_task", "task_id": tid, "force": bool(force)}
            )
        except Exception:
            head_found = False
        return bool(found or head_found)

    def send_ordered(self, msg: dict):
        """Fire-and-forget submit. Per-connection FIFO both on the asyncio
        send side and in the head's handler dispatch, so a later request()
        from this process observes its effects (the reference gets the same
        property from gRPC in-order delivery per channel)."""
        if self._dead_refs:
            self._drain_dead_refs()
        if self.conn is None or self.conn.closed or self.io is None:
            raise exceptions.RayTpuError("ray_tpu is not connected (call ray_tpu.init())")
        self.io.post(_swallow_conn_errors(self.conn.send(msg)))

    def disconnect(self):
        self.connected = False
        self.mode = None
        channels, self._actor_channels = dict(self._actor_channels), {}
        tchannels, self._task_channels = dict(self._task_channels), {}
        if self.io is not None:
            try:  # push batched result forwards out BEFORE resetting them
                self.io.run(self._flush_batches(), timeout=2)
            except Exception:
                pass
        # reset every cross-session transport bit: a stale flag/batch from
        # this session must not leak into the next init. (Stashed calls that
        # were never claimed are dropped here — shutdown beats fire-and-
        # forget calls still inside the stash window, same as the reference
        # dropping in-flight work at ray.shutdown.)
        self._sweeper_on = False
        self._sweeper_loop = None
        self._reconnecting = False
        self._remote_address = None
        with self._stash_lock:
            self._stash_by_oid.clear()
        with self._batch_lock:
            self._put_batch = {}
            self._record_batch = []
            self._ref_batch = {}
        self._flush_handle = None
        sweep_task, self._sweep_task = self._sweep_task, None
        if sweep_task is not None and self.io is not None:
            try:
                self.io.loop.call_soon_threadsafe(sweep_task.cancel)
            except RuntimeError:
                pass
        with self._peer_lock:
            peers, self._peer_conns = dict(self._peer_conns), {}
            planes, self._peer_planes = dict(self._peer_planes), {}
            self._peer_info.clear()
        for socks in peers.values():
            self._close_sockets(socks)
        for plane in planes.values():
            try:
                plane.disconnect()
            except Exception:
                pass
        if self.io is not None:
            for ch in list(channels.values()) + list(tchannels.values()):
                try:
                    self.io.run(ch.close(), timeout=2)
                except Exception:
                    pass
        self._pubsub_callbacks.clear()
        with self._local_lock:
            self._local_objects.clear()
            pending, self._local_pending = dict(self._local_pending), {}
        for ev in pending.values():
            ev.set()  # wake blocked get()s; they fall through to a
            # not-connected error instead of waiting forever
        self.conn = None
        if getattr(self, "_owns_io", False) and self.io is not None:
            try:
                self.io.stop()
            except Exception:
                pass
            self.io = None
            self._owns_io = False

    # ------------------------------------------------------------------
    # refcounting (reference_count.h:61 — simplified owner-side counting)
    # ------------------------------------------------------------------

    def merged_runtime_env(self, task_env: Optional[dict]) -> Optional[dict]:
        """Per-field merge of a task/actor runtime_env over the job-level
        default (reference semantics: env_vars union, task wins per key;
        other fields override wholesale)."""
        default = self.default_runtime_env
        if not default:
            return task_env
        if not task_env:
            return default
        merged = {**default, **task_env}
        if default.get("env_vars") or task_env.get("env_vars"):
            merged["env_vars"] = {
                **(default.get("env_vars") or {}),
                **(task_env.get("env_vars") or {}),
            }
        return merged

    def add_object_ref(self, object_id: str):
        if self.connected:
            self.send({"t": "add_refs", "counts": {object_id: 1}})

    def remove_object_ref(self, object_id: str, escaped: bool = True):
        """Called from ObjectRef.__del__ — which the GC can run at ANY
        allocation point, INCLUDING while this very thread already holds
        _local_lock or _batch_lock (observed: submit_task's Event()
        allocation collected a dead ref and self-deadlocked on
        _local_lock). Therefore this method takes NO locks: it parks the
        id on a lock-free deque that normal (non-__del__) contexts
        drain. _ensure_sweeper is flag-check + call_soon_threadsafe —
        itself lock-free — so a quiescent process still gets drained."""
        self._dead_refs.append((object_id, escaped))
        if self.connected and self.io is not None:
            try:
                self._ensure_sweeper()
            except Exception:
                pass

    def _drain_dead_refs(self) -> None:
        """Process refs whose __del__ parked them (regular calling context:
        locks are safe here). Mirrors the old inline remove logic."""
        drained, n = False, 0
        while True:
            try:
                object_id, escaped = self._dead_refs.popleft()
            except IndexError:
                break
            drained = True
            with self._local_lock:
                self._local_objects.pop(object_id, None)
            if not self.connected:
                continue
            # batched: a per-del io-loop wake costs more than the call
            with self._batch_lock:
                if not escaped and object_id in self._put_batch:
                    # the ref died before its result forward flushed AND was
                    # never pickled: no other process can know the id. The
                    # put (+1) and this remove (-1) cancel — drop BOTH and
                    # the head never hears about the object at all.
                    del self._put_batch[object_id]
                    continue
                self._ref_batch[object_id] = self._ref_batch.get(object_id, 0) + 1
                n = len(self._ref_batch)
        if drained and n and self.connected:
            if self.io is not None and threading.current_thread() is self.io.thread:
                try:
                    self._schedule_flush(n)
                except Exception:
                    self._ensure_sweeper()
            else:
                self._ensure_sweeper()

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------

    def put(self, value) -> "ObjectRef":
        from ..object_ref import ObjectRef

        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed.")
        from .config import GLOBAL_CONFIG as cfg

        oid = ObjectID.from_put(self.job_id).hex()
        env = serialization.serialize(value)
        # pin=True: put data has no lineage, so it must never be evicted
        env = serialization.externalize(
            env, self.shm, cfg.object_inline_limit_bytes, pin=True
        )
        # fire-and-forget: messages on one connection are handled in order,
        # so a later get() cannot observe the object missing; dropping the
        # ack makes put() bandwidth-bound instead of RTT-bound
        self.send_ordered(
            {"t": "put_object", "object_id": oid, "envelope": env, "initial_refs": 1}
        )
        return ObjectRef(oid, skip_adding_local_ref=True)

    def _bypass_sock(self, ch):
        """Per-(thread, actor) blocking socket to the actor worker's direct
        endpoint (the same one the async channel dials)."""
        import socket as _socket

        d = getattr(self._bypass_local, "socks", None)
        if d is None:
            d = self._bypass_local.socks = {}
        sock = d.get(ch.actor_id)
        if sock is None:
            addr = ch.direct_addr
            if protocol.is_tcp_address(addr):
                host, _, port = addr.rpartition(":")
                sock = _socket.create_connection((host, int(port)), timeout=60)
                sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            else:
                sock = _socket.socket(_socket.AF_UNIX)
                sock.settimeout(60)  # bounds CONNECT only (reset below)
                sock.connect(addr)
            # no recv deadline: like the async channel's conn.request, the
            # reply arrives when the method finishes — a >60s method is
            # healthy, not dead (worker death still surfaces as EOF)
            sock.settimeout(None)
            d[ch.actor_id] = sock
        return sock

    def _drop_bypass_sock(self, ch):
        d = getattr(self._bypass_local, "socks", None)
        sock = d.pop(ch.actor_id, None) if d else None
        if sock is not None:
            try:
                sock.close()
            except Exception:
                pass

    def _bypass_call(self, ch, spec: dict) -> None:
        """Execute a claimed stashed call ON THE CALLER THREAD over a
        blocking socket: no io-thread ping-pong, which on busy hosts costs
        more than the wire (the sync half of VERDICT's actor-call target).
        Settles every return id exactly once."""
        msg = {
            "t": "run_task",
            "task_id": spec["task_id"],
            "actor_id": ch.actor_id,
            "method": spec["method"],
            "args": {"env": spec["args"], "resolved": {}},
            "return_ids": spec["return_ids"],
            "trace_ctx": spec.get("trace_ctx"),
            "rid": -1,
        }
        sent = False
        try:
            sock = self._bypass_sock(ch)
            # plane framing both ways: the worker's direct server replies
            # through protocol.Connection, which may emit out-of-band
            # buffer-segment frames (big results) — the sync reader
            # understands them
            protocol.write_frame_sync(sock, msg)
            sent = True
            reply = protocol.read_frame_sync(sock)
        except Exception:
            self._drop_bypass_sock(ch)
            if not sent:
                # never reached the worker: the ordered channel can run it
                # (it re-resolves the route, e.g. across an actor restart)
                ch.deque.append(spec)
                self.io.loop.call_soon_threadsafe(ch.wake)
                return
            self._bypass_fail(ch, spec, "worker died mid-call")
            return
        value = reply.get("value") if reply.get("ok") else None
        if value is None or "results" not in value or value.get("lost_deps"):
            err = reply.get("error")
            self._bypass_fail(ch, spec, f"direct call failed: {err!r}")
            return
        for oid, env in zip(spec["return_ids"], value["results"]):
            self._cache_local_object(oid, env)
            self._enqueue_put(oid, env)  # thread-safe; sweeper flushes

    def _bypass_fail(self, ch, spec: dict, reason: str):
        from ..exceptions import ActorDiedError

        err = serialization.serialize(ActorDiedError(ch.actor_id, reason))
        err.is_error = True
        for oid in spec["return_ids"]:
            self._cache_local_object(oid, err)
            self._enqueue_put(oid, err)

    def get(self, refs, timeout: Optional[float] = None):
        from ..object_ref import ObjectRef

        is_single = isinstance(refs, ObjectRef)
        ref_list = [refs] if is_single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
        # sync bypass: stashed-at-submit calls run HERE on the caller thread
        if self._stash_by_oid:
            claimed = []
            for r in ref_list:
                with self._stash_lock:
                    entry = self._stash_by_oid.get(r.id)
                if entry is not None:
                    s = entry[0].claim_stash(entry[1])
                    if s is not None:
                        claimed.append((entry[0], s))
            if len(claimed) == 1 and timeout is None:
                self._bypass_call(*claimed[0])
            else:
                # a bounded get() must honor `timeout`: the blocking bypass
                # can't be interrupted, so route through the channel whose
                # event-wait can.
                # 2+ claims must PIPELINE: executing them serially here
                # deadlocks when one call's completion depends on another
                # (e.g. ranks of one collective) — hand them back to their
                # ordered channels instead
                for ch, s in claimed:
                    ch.deque.append(s)
                    self.io.loop.call_soon_threadsafe(ch.wake)
        # fast path: results of direct actor calls are cached locally (or in
        # flight — then wait on the local event) — no head round-trip for
        # the produce-then-get pattern
        envs: List[Any] = [None] * len(ref_list)
        missing: List[int] = []
        pending: List[Tuple[int, Any]] = []
        with self._local_lock:
            for i, r in enumerate(ref_list):
                env = self._local_objects.get(r.id)
                if env is not None:
                    envs[i] = _copy_envelope(env)
                    continue
                ev = self._local_pending.get(r.id)
                if ev is not None:
                    pending.append((i, ev))
                else:
                    missing.append(i)
        deadline = None if timeout is None else time.monotonic() + timeout
        for i, ev in pending:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            if os.environ.get("RAY_TPU_GET_HANG_DEBUG"):
                # forensics mode: periodically report which oid a stuck
                # get() waits on and the local bookkeeping around it
                waited = 0.0
                while not ev.wait(
                    20.0 if remaining is None else min(20.0, max(remaining - waited, 0.01))
                ):
                    waited += 20.0
                    with self._local_lock:
                        cur = self._local_pending.get(ref_list[i].id)
                    # raw stderr: pytest's logging plugin would swallow a
                    # logger record even under -s
                    chans = []
                    for key, ch in list(self._task_channels.items()):
                        try:
                            chans.append(
                                f"{key}: q={ch.queue.qsize()} resolving={sorted(ch._resolving)} "
                                f"acquiring={ch._acquiring} leases="
                                + str([
                                    (l.worker_id, l.inflight, sorted(l.inflight_tids))
                                    for l in ch.leases
                                ])
                            )
                        except Exception as e:  # noqa: BLE001
                            chans.append(f"{key}: <{e!r}>")
                    print(
                        f"get() stuck {waited:.0f}s on {ref_list[i].id}: "
                        f"cached={ref_list[i].id in self._local_objects} "
                        f"pending_event={cur is not None} same_event={cur is ev}\n"
                        f"  channels: {chans}",
                        file=sys.__stderr__, flush=True,
                    )
                    if remaining is not None and waited >= remaining:
                        raise exceptions.GetTimeoutError(
                            f"Get timed out after {timeout}s waiting for {ref_list[i].id}"
                        )
            elif not ev.wait(remaining):
                raise exceptions.GetTimeoutError(
                    f"Get timed out after {timeout}s waiting for {ref_list[i].id}"
                )
            with self._local_lock:
                env = self._local_objects.get(ref_list[i].id)
            if env is not None:
                envs[i] = _copy_envelope(env)
            else:
                missing.append(i)  # routed via the head after all
        def remaining():
            if deadline is None:
                return None
            return max(0.0, deadline - time.monotonic())

        if missing:
            fetched = self.request(
                {
                    "t": "get_objects",
                    "object_ids": [ref_list[i].id for i in missing],
                    "timeout": remaining(),
                },
                **self._fetch_kwargs(),
            )
            for i, env in zip(missing, fetched):
                envs[i] = env
        values = []
        for env, ref in zip(envs, ref_list):
            for attempt in range(3):
                try:
                    env = serialization.materialize(env, self.shm)
                    break
                except exceptions.ObjectLostError:
                    # buffers evicted/lost: ask the head to rebuild the
                    # object from its creating task's lineage, then refetch
                    # (reference: ObjectRecoveryManager resubmission)
                    if attempt == 2:
                        raise
                    ok = self.request(
                        {"t": "reconstruct_objects", "object_ids": [ref.id]}
                    )
                    if not ok.get(ref.id):
                        raise exceptions.ObjectLostError(ref.id) from None
                    env = self.request(
                        {"t": "get_objects", "object_ids": [ref.id],
                         "timeout": remaining()},
                        **self._fetch_kwargs(),
                    )[0]
            value = serialization.deserialize(env)
            if getattr(env, "is_error", False):
                if isinstance(value, exceptions.TaskError):
                    raise value.as_instanceof_cause()
                raise value
            values.append(value)
        return values[0] if is_single else values

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        from ..object_ref import ObjectRef

        refs = list(refs)
        if len(set(r.id for r in refs)) != len(refs):
            raise ValueError("wait() expects a list of unique ObjectRefs.")
        if num_returns > len(refs):
            raise ValueError("num_returns cannot exceed the number of refs")
        ready_ids, pending_ids = self.request(
            {
                "t": "wait_objects",
                "object_ids": [r.id for r in refs],
                "num_returns": num_returns,
                "timeout": timeout,
            }
        )
        by_id = {r.id: r for r in refs}
        return [by_id[i] for i in ready_ids], [by_id[i] for i in pending_ids]

    # ------------------------------------------------------------------
    # tasks
    # ------------------------------------------------------------------

    def _export_callable(self, obj, ns: str) -> str:
        # identity memo: re-pickling the same function on EVERY submit just
        # to recompute its content hash dominates the submit hot path. A
        # function's captured globals/closures therefore FREEZE at first
        # export — the reference has the same semantics (function_manager
        # exports once per function object and workers cache by hash).
        # Keyed per (object, ns) so 'fn' and 'cls' namespaces can't alias.
        try:
            memo = self._export_keys.get(obj)
        except TypeError:  # not weakref-able
            memo = None
        if memo is not None and ns in memo:
            return memo[ns]
        blob = cloudpickle.dumps(obj)
        key = hashlib.sha1(blob).hexdigest()
        with self._lock:
            if key not in self._fn_exported:
                self.request({"t": "kv_put", "ns": ns, "key": key, "value": blob, "overwrite": False})
                self._fn_exported[key] = True
        try:
            self._export_keys.setdefault(obj, {})[ns] = key
        except TypeError:
            pass
        return key

    def _prepare_args(self, args: tuple, kwargs: dict):
        """Replace top-level ObjectRefs with _ArgRef markers; collect deps."""
        from ..object_ref import ObjectRef

        deps: List[str] = []

        def conv(a):
            if isinstance(a, ObjectRef):
                deps.append(a.id)
                # the id escapes into a task spec WITHOUT the ref being
                # pickled (no __reduce__): mark it escaped by hand, or its
                # death could cancel the un-flushed result forward a
                # dependent task is about to resolve against the head
                a._escaped = True
                return _ArgRef(a.id)
            return a

        new_args = tuple(conv(a) for a in args)
        new_kwargs = {k: conv(v) for k, v in kwargs.items()}
        env = serialization.serialize((new_args, new_kwargs))
        # nested refs found during pickling are deps too (must exist at exec)
        for r in env.contained_refs:
            deps.append(r.id)
        return env, sorted(set(deps))

    def submit_task(
        self,
        function,
        args: tuple,
        kwargs: dict,
        *,
        name: str = "",
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: int = 0,
        scheduling_strategy=None,
        runtime_env: Optional[dict] = None,
        streaming: bool = False,
    ) -> List["ObjectRef"]:
        from ..object_ref import ObjectRef

        fn_key = self._export_callable(function, "fn")
        task_id = TaskID.for_task(self.job_id)
        return_ids = [ObjectID.for_return(task_id, i).hex() for i in range(num_returns)]
        env, deps = self._prepare_args(args, kwargs)
        from ..util import tracing

        with tracing.span_for_submission(
            f"task_submit.{name or getattr(function, '__name__', 'task')}",
            task_id=task_id.hex(),
        ):
            trace_ctx = tracing.inject_current_context()
        spec = {
            "task_id": task_id.hex(),
            "name": name,
            "fn_key": fn_key,
            "trace_ctx": trace_ctx,
            "args": env,
            "deps": deps,
            "return_ids": return_ids,
            "resources": resources,
            "max_retries": max_retries,
            "scheduling_strategy": scheduling_strategy,
            "runtime_env": self.merged_runtime_env(runtime_env),
        }
        if streaming:
            # a replayed generator would re-push yields over committed ids
            spec["streaming"] = True
            spec["max_retries"] = 0
        # Direct path (direct_task_transport.cc:588): push to a leased
        # worker, head out of the per-task loop. Head path for anything the
        # pooled-lease model can't serve: placement strategies, runtime
        # envs, TPU workers (non-pooled), streaming generators (yields ride
        # the worker->head conn; the head must own the task's lifecycle).
        if (
            cfg.direct_task_calls
            and not streaming
            and scheduling_strategy is None
            and not spec["runtime_env"]
            and not (resources or {}).get("TPU")
        ):
            if deps:
                self.send_ordered({"t": "add_refs", "counts": {d: 1 for d in deps}})
            key = tuple(sorted((resources or {"CPU": 1.0}).items()))
            with self._lock:
                ch = self._task_channels.get(key)
                if ch is None:
                    ch = self.io.run(self._make_task_channel(resources or {"CPU": 1.0}))
                    self._task_channels[key] = ch
            with self._local_lock:
                for oid in return_ids:
                    self._local_pending[oid] = threading.Event()
            self.io.loop.call_soon_threadsafe(ch.queue.put_nowait, spec)
        else:
            # fire-and-forget (FIFO per connection): submission is
            # serialization-bound, not RTT-bound; the head takes the
            # caller's +1 on each return id when it processes the submit
            self.send_ordered({"t": "submit_task", "spec": spec})
        return [ObjectRef(oid, skip_adding_local_ref=True) for oid in return_ids]

    async def _make_task_channel(self, resources: Dict[str, float]) -> "_TaskChannel":
        return _TaskChannel(self, dict(resources))

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------

    def create_actor(
        self,
        cls,
        args: tuple,
        kwargs: dict,
        *,
        name: Optional[str] = None,
        namespace: Optional[str] = None,
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_concurrency: int = 1,
        scheduling_strategy=None,
        lifetime: Optional[str] = None,
        runtime_env: Optional[dict] = None,
    ) -> str:
        cls_key = self._export_callable(cls, "cls")
        actor_id = ActorID.of(self.job_id).hex()
        env, deps = self._prepare_args(args, kwargs)
        spec = {
            "actor_id": actor_id,
            "cls_key": cls_key,
            "cls_name": getattr(cls, "__name__", str(cls)),
            "args": env,
            "deps": deps,
            "name": name,
            "namespace": namespace if namespace is not None else self.namespace,
            "resources": resources,
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "scheduling_strategy": scheduling_strategy,
            "lifetime": lifetime,
            "runtime_env": self.merged_runtime_env(runtime_env),
        }
        self.request({"t": "create_actor", "spec": spec})
        return actor_id

    def submit_actor_task(
        self,
        actor_id: str,
        method: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
    ) -> List["ObjectRef"]:
        from ..object_ref import ObjectRef

        task_id = TaskID.for_actor_task(ActorID.from_hex(actor_id))
        return_ids = [ObjectID.for_return(task_id, i).hex() for i in range(num_returns)]
        env, deps = self._prepare_args(args, kwargs)
        from ..util import tracing

        with tracing.span_for_submission(
            f"actor_submit.{method}", task_id=task_id.hex(), actor_id=actor_id
        ):
            trace_ctx = tracing.inject_current_context()
        spec = {
            "task_id": task_id.hex(),
            "actor_id": actor_id,
            "method": method,
            "trace_ctx": trace_ctx,
            "args": env,
            "deps": deps,
            "return_ids": return_ids,
        }
        if cfg.direct_actor_calls:
            # no up-front add_refs for RESULTS: the caller's +1 rides the
            # put_object that delivers them (initial_refs=1); the head
            # reconciles early remove_refs via its signed counters. Deps DO
            # get pinned here — the user may drop their ObjectRef right
            # after .remote(), and the channel still has to resolve them.
            if deps:
                self.send_ordered({"t": "add_refs", "counts": {d: 1 for d in deps}})
            with self._lock:  # two threads must not race in two channels
                ch = self._actor_channels.get(actor_id)
                if ch is None:
                    ch = self.io.run(self._make_channel(actor_id))
                    self._actor_channels[actor_id] = ch
            with self._local_lock:
                for oid in return_ids:
                    self._local_pending[oid] = threading.Event()
            # Sync bypass: on a completely quiet channel, DEFER the send —
            # an immediately-following get() (the sync call pattern) runs
            # the call on the CALLER thread over a blocking socket, skipping
            # two io-thread handoffs per call. A timer flushes unclaimed
            # stashes to the ordered queue so fire-and-forget still runs.
            if (
                not deps
                and not ch.head_routed
                and ch.direct_addr is not None
                and not ch.busy()
            ):
                spec["_stash_t"] = time.monotonic()
                with self._stash_lock:
                    if ch.stashed is None and not ch.busy():
                        ch.stashed = spec
                        for oid in return_ids:
                            self._stash_by_oid[oid] = (ch, spec)
                        self._ensure_sweeper()  # bounds an unclaimed stash
                        return [
                            ObjectRef(oid, skip_adding_local_ref=True)
                            for oid in return_ids
                        ]
            # ordered path: an unclaimed stash must flush FIRST (order)
            flush = ch.claim_stash()
            if flush is not None:
                ch.deque.append(flush)
            ch.deque.append(spec)
            self.io.loop.call_soon_threadsafe(ch.wake)
        else:
            self.send_ordered({"t": "submit_actor_task", "spec": spec})
        return [ObjectRef(oid, skip_adding_local_ref=True) for oid in return_ids]

    async def _make_channel(self, actor_id: str) -> "_ActorChannel":
        return _ActorChannel(self, actor_id)


global_worker = Worker()


# --------------------------------------------------------------------------
# task execution (the worker side of run_task)
# --------------------------------------------------------------------------


def resolve_task_args(args_msg: dict) -> Tuple[tuple, dict]:
    env: serialization.SerializedObject = args_msg["env"]
    resolved: Dict[str, serialization.SerializedObject] = args_msg["resolved"]
    env = serialization.materialize(env, global_worker.shm)
    args, kwargs = serialization.deserialize(env)
    lost: List[str] = []

    def conv(a):
        if isinstance(a, _ArgRef):
            dep_env = resolved.get(a.object_id)
            if dep_env is None:
                lost.append(a.object_id)
                return None
            try:
                dep_env = serialization.materialize(dep_env, global_worker.shm)
            except exceptions.ObjectLostError:
                # buffer gone (evicted): collect the OBJECT id — ALL lost
                # deps are reported together so the head reconstructs them
                # in one round
                lost.append(a.object_id)
                return None
            value = serialization.deserialize(dep_env)
            if getattr(dep_env, "is_error", False):
                raise value
            return value
        return a

    args = tuple(conv(a) for a in args)
    kwargs = {k: conv(v) for k, v in kwargs.items()}
    if lost:
        raise exceptions.LostDepsError(lost)
    return args, kwargs


def _stream_yields(fn, fn_name: str, args_msg: dict, return_ids: List[str]) -> dict:
    """Execute a streaming-generator task (reference: _raylet.pyx
    execute_streaming_generator + task_manager.cc HandleReportGeneratorItemReturns):
    each yielded value is serialized and pushed to the head's object
    directory IMMEDIATELY (consumers unblock per yield, not at task end);
    the task's own return resolves to a StreamDescriptor carrying the final
    count. Yields are pinned like actor results — a generator re-run is not
    side-effect safe, so there is no lineage to rebuild an evicted yield."""
    from ..exceptions import TaskError
    from ..object_ref import StreamDescriptor, stream_object_id
    from .config import GLOBAL_CONFIG as cfg
    from .ids import ObjectID

    task_id = ObjectID.from_hex(return_ids[0]).task_id().hex()
    try:
        args, kwargs = resolve_task_args(args_msg)
    except exceptions.LostDepsError:
        raise  # the caller converts this to a lost_deps reply
    except Exception as e:  # noqa: BLE001 — bad arg envelope is a USER error
        tb = traceback.format_exc()
        env = serialization.serialize(TaskError(fn_name, tb, e))
        env.is_error = True
        return {"results": [env]}
    count = 0
    try:
        gen = fn(*args, **kwargs)
        for value in gen:
            env = serialization.serialize(value)
            env = serialization.externalize(
                env, global_worker.shm, cfg.object_inline_limit_bytes, pin=True
            )
            # FIFO on the head conn: every yield lands in the directory
            # before the completion reply that follows them
            global_worker.send(
                {
                    "t": "put_object",
                    "object_id": stream_object_id(task_id, count),
                    "envelope": env,
                    "initial_refs": 1,
                    # ties this yield's baseline ref to the completion
                    # object's lifetime head-side
                    "stream_of": task_id,
                }
            )
            count += 1
    except Exception as e:  # noqa: BLE001 — mid-stream failure ends the stream
        tb = traceback.format_exc()
        err = e if isinstance(e, (exceptions.TaskError, exceptions.ActorError)) else TaskError(fn_name, tb, e)
        env = serialization.serialize(err)
        env.is_error = True  # consumed yields stay valid; the NEXT next() raises
        return {"results": [env]}
    env = serialization.serialize(StreamDescriptor(task_id, count))
    return {"results": [env]}


def execute_and_package(
    fn, fn_name: str, args_msg: dict, return_ids: List[str], pin_results: bool = False,
    streaming: bool = False,
) -> dict:
    """Run a task function and package results as envelopes.

    pin_results=True (actor methods): actor outputs have no lineage — the
    method ran against mutable state — so their shm buffers must never be
    LRU-evicted. Stateless task outputs stay evictable (reconstructible).

    Reference: _raylet.pyx:1630 execute_task_with_cancellation_handler.
    """
    if streaming:
        try:
            return _stream_yields(fn, fn_name, args_msg, return_ids)
        except exceptions.LostDepsError as e:
            return {"lost_deps": e.object_ids}
    try:
        try:
            args, kwargs = resolve_task_args(args_msg)
        except exceptions.LostDepsError as e:
            # dependency buffers were evicted: signal the head to rebuild
            # them from lineage and re-dispatch (not a user error, and not
            # a retry — reference: dependency resolution failure triggering
            # ObjectRecoveryManager)
            return {"lost_deps": e.object_ids}
        result = fn(*args, **kwargs)
        n = len(return_ids)
        if n == 0:
            return {"results": []}
        if n == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != n:
                raise ValueError(
                    f"Task {fn_name} set num_returns={n} but returned {len(values)} values"
                )
        from .config import GLOBAL_CONFIG as cfg

        envs = []
        for v in values:
            env = serialization.serialize(v)
            envs.append(
                serialization.externalize(
                    env, global_worker.shm, cfg.object_inline_limit_bytes,
                    pin=pin_results,
                )
            )
        return {"results": envs}
    except Exception as e:  # noqa: BLE001
        tb = traceback.format_exc()
        if isinstance(e, (exceptions.TaskError, exceptions.ActorError)):
            err: Exception = e
        else:
            err = exceptions.TaskError(fn_name, tb, e)
        env = serialization.serialize(err)
        env.is_error = True  # type: ignore[attr-defined]
        return {"results": [env for _ in return_ids] or [env]}


@atexit.register
def _shutdown_at_exit():
    w = global_worker
    if w.mode == MODE_DRIVER and w.node is not None:
        try:
            w.node.stop()
        except Exception:
            pass
