"""CoreWorker-lite: the per-process runtime shared by driver and workers.

Reference parity: src/ray/core_worker/core_worker.h:284 (CoreWorker) +
python/ray/_private/worker.py (global Worker singleton, connect/get/put/wait).
One instance per process; owns the control-plane connection, the ObjectRef
reference counting hooks, and task/actor submission. Unlike the reference
there is no separate in-process C++ library — the hot compute path on TPU is
a single compiled XLA program, so the orchestration runtime stays in Python
with the bulk-data plane (shared-memory store) in C++.
"""

from __future__ import annotations

import asyncio
import atexit
import hashlib
import os
import threading
import traceback
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from .. import exceptions
from . import protocol, serialization
from .config import GLOBAL_CONFIG as cfg
from .ids import ActorID, JobID, ObjectID, TaskID

MODE_DRIVER = "driver"
MODE_WORKER = "worker"


class EventLoopThread:
    """A background thread running an asyncio loop, with sync bridges."""

    def __init__(self, name="ray_tpu-io"):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout=None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def post(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)


@dataclass
class _ArgRef:
    """Placeholder for a top-level ObjectRef argument (replaced by its value
    at execution; nested refs stay refs — reference semantics)."""

    object_id: str


class Worker:
    """The global per-process runtime."""

    def __init__(self):
        self.mode: Optional[str] = None
        self.connected = False
        self.job_id = JobID.from_int(os.getpid() % (2**31))
        self.node_id: Optional[str] = None
        self.session_dir: Optional[str] = None
        self.io: Optional[EventLoopThread] = None
        self.conn: Optional[protocol.Connection] = None
        self.node = None  # driver-only: the Node supervisor
        self._fn_exported: Dict[str, bool] = {}
        self.current_actor = None
        self.current_actor_id: Optional[str] = None
        self.current_task_id: Optional[str] = None
        self.namespace: str = ""
        # job-level default runtime_env (tasks/actors inherit it when they
        # don't specify their own)
        self.default_runtime_env: Optional[dict] = None
        self._lock = threading.RLock()
        self._shm = None
        self._shm_tried = False

    @property
    def shm(self):
        """Lazy client for the C++ shared-memory object plane (None if
        disabled or unavailable)."""
        if self._shm_tried:
            return self._shm
        self._shm_tried = True
        from .shm import connect_for_session

        self._shm = connect_for_session(self.session_dir)
        return self._shm

    # ------------------------------------------------------------------
    # connection
    # ------------------------------------------------------------------

    def connect_driver(self, node, namespace: str = ""):
        self.mode = MODE_DRIVER
        self._fn_exported.clear()
        if self._shm is not None:
            try:
                self._shm.disconnect()
            except Exception:
                pass
        self._shm = None
        self._shm_tried = False
        self.node = node
        self.io = node.io
        self.session_dir = node.session_dir
        self.namespace = namespace
        self.conn = self.io.run(self._open_conn(node.socket_path))
        info = self.request({"t": "register_driver"})
        self.node_id = info["node_id"]
        self.connected = True

    def connect_existing(self, socket_path: str, namespace: str = ""):
        """Attach as an ADDITIONAL driver to a running head — via the
        session unix socket (job submission, `init(address="auto")`) or a
        TCP host:port (remote drivers; reference: worker.py:1186 address
        resolution + util/client). Owns its own IO thread; the head
        outlives this client."""
        import os

        self.mode = MODE_DRIVER
        self._fn_exported.clear()
        if self._shm is not None:
            try:
                self._shm.disconnect()
            except Exception:
                pass
        self._shm = None
        self._shm_tried = False
        self.node = None
        self.io = EventLoopThread()
        self._owns_io = True
        # remote (TCP) drivers have no local session dir: no shm plane —
        # objects ride the socket inline and buffers are pulled via the head
        self.session_dir = (
            None if protocol.is_tcp_address(socket_path) else os.path.dirname(socket_path)
        )
        self.namespace = namespace
        self.conn = self.io.run(self._open_conn(socket_path))
        info = self.request({"t": "register_driver"})
        self.node_id = info["node_id"]
        if os.environ.get("RAY_TPU_JOB_RUNTIME_ENV"):
            import json

            self.default_runtime_env = json.loads(os.environ["RAY_TPU_JOB_RUNTIME_ENV"])
        self.connected = True

    def connect_worker(
        self, socket_path: str, worker_id: str, io: EventLoopThread, conn, node_id=None
    ):
        self.mode = MODE_WORKER
        self.io = io
        self.conn = conn
        self.node_id = node_id
        self.connected = True

    async def _open_conn(self, socket_path: str) -> protocol.Connection:
        reader, writer = await protocol.open_stream(socket_path)

        async def handler(msg):
            return await self._handle_push(msg)

        conn = protocol.Connection(reader, writer, handler)
        conn.start()
        return conn

    async def _handle_push(self, msg):
        raise ValueError(f"driver got unexpected message {msg.get('t')}")

    def request(self, msg: dict, timeout: Optional[float] = None) -> Any:
        if not self.conn or self.conn.closed:
            raise exceptions.RayTpuError("ray_tpu is not connected (call ray_tpu.init())")
        return self.io.run(self.conn.request(msg, timeout))

    def send(self, msg: dict):
        if self.conn is None or self.conn.closed or self.io is None:
            return
        try:
            self.io.post(self.conn.send(msg))
        except RuntimeError:
            pass  # loop shut down

    def disconnect(self):
        self.connected = False
        self.mode = None
        self.conn = None
        if getattr(self, "_owns_io", False) and self.io is not None:
            try:
                self.io.stop()
            except Exception:
                pass
            self.io = None
            self._owns_io = False

    # ------------------------------------------------------------------
    # refcounting (reference_count.h:61 — simplified owner-side counting)
    # ------------------------------------------------------------------

    def merged_runtime_env(self, task_env: Optional[dict]) -> Optional[dict]:
        """Per-field merge of a task/actor runtime_env over the job-level
        default (reference semantics: env_vars union, task wins per key;
        other fields override wholesale)."""
        default = self.default_runtime_env
        if not default:
            return task_env
        if not task_env:
            return default
        merged = {**default, **task_env}
        if default.get("env_vars") or task_env.get("env_vars"):
            merged["env_vars"] = {
                **(default.get("env_vars") or {}),
                **(task_env.get("env_vars") or {}),
            }
        return merged

    def add_object_ref(self, object_id: str):
        if self.connected:
            self.send({"t": "add_refs", "counts": {object_id: 1}})

    def remove_object_ref(self, object_id: str):
        if self.connected:
            self.send({"t": "remove_refs", "counts": {object_id: 1}})

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------

    def put(self, value) -> "ObjectRef":
        from ..object_ref import ObjectRef

        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed.")
        from .config import GLOBAL_CONFIG as cfg

        oid = ObjectID.from_put(self.job_id).hex()
        env = serialization.serialize(value)
        env = serialization.externalize(env, self.shm, cfg.object_inline_limit_bytes)
        self.request({"t": "put_object", "object_id": oid, "envelope": env, "initial_refs": 1})
        return ObjectRef(oid, skip_adding_local_ref=True)

    def get(self, refs, timeout: Optional[float] = None):
        from ..object_ref import ObjectRef

        is_single = isinstance(refs, ObjectRef)
        ref_list = [refs] if is_single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
        envs = self.request(
            {"t": "get_objects", "object_ids": [r.id for r in ref_list], "timeout": timeout}
        )
        values = []
        for env in envs:
            env = serialization.materialize(env, self.shm)
            value = serialization.deserialize(env)
            if getattr(env, "is_error", False):
                raise value
            values.append(value)
        return values[0] if is_single else values

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        from ..object_ref import ObjectRef

        refs = list(refs)
        if len(set(r.id for r in refs)) != len(refs):
            raise ValueError("wait() expects a list of unique ObjectRefs.")
        if num_returns > len(refs):
            raise ValueError("num_returns cannot exceed the number of refs")
        ready_ids, pending_ids = self.request(
            {
                "t": "wait_objects",
                "object_ids": [r.id for r in refs],
                "num_returns": num_returns,
                "timeout": timeout,
            }
        )
        by_id = {r.id: r for r in refs}
        return [by_id[i] for i in ready_ids], [by_id[i] for i in pending_ids]

    # ------------------------------------------------------------------
    # tasks
    # ------------------------------------------------------------------

    def _export_callable(self, obj, ns: str) -> str:
        blob = cloudpickle.dumps(obj)
        key = hashlib.sha1(blob).hexdigest()
        with self._lock:
            if key not in self._fn_exported:
                self.request({"t": "kv_put", "ns": ns, "key": key, "value": blob, "overwrite": False})
                self._fn_exported[key] = True
        return key

    def _prepare_args(self, args: tuple, kwargs: dict):
        """Replace top-level ObjectRefs with _ArgRef markers; collect deps."""
        from ..object_ref import ObjectRef

        deps: List[str] = []

        def conv(a):
            if isinstance(a, ObjectRef):
                deps.append(a.id)
                return _ArgRef(a.id)
            return a

        new_args = tuple(conv(a) for a in args)
        new_kwargs = {k: conv(v) for k, v in kwargs.items()}
        env = serialization.serialize((new_args, new_kwargs))
        # nested refs found during pickling are deps too (must exist at exec)
        for r in env.contained_refs:
            deps.append(r.id)
        return env, sorted(set(deps))

    def submit_task(
        self,
        function,
        args: tuple,
        kwargs: dict,
        *,
        name: str = "",
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: int = 0,
        scheduling_strategy=None,
        runtime_env: Optional[dict] = None,
    ) -> List["ObjectRef"]:
        from ..object_ref import ObjectRef

        fn_key = self._export_callable(function, "fn")
        task_id = TaskID.for_task(self.job_id)
        return_ids = [ObjectID.for_return(task_id, i).hex() for i in range(num_returns)]
        env, deps = self._prepare_args(args, kwargs)
        spec = {
            "task_id": task_id.hex(),
            "name": name,
            "fn_key": fn_key,
            "args": env,
            "deps": deps,
            "return_ids": return_ids,
            "resources": resources,
            "max_retries": max_retries,
            "scheduling_strategy": scheduling_strategy,
            "runtime_env": self.merged_runtime_env(runtime_env),
        }
        # head takes the initial +1 on each return id at submit time
        self.request({"t": "add_refs", "counts": {oid: 1 for oid in return_ids}})
        self.request({"t": "submit_task", "spec": spec})
        return [ObjectRef(oid, skip_adding_local_ref=True) for oid in return_ids]

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------

    def create_actor(
        self,
        cls,
        args: tuple,
        kwargs: dict,
        *,
        name: Optional[str] = None,
        namespace: Optional[str] = None,
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_concurrency: int = 1,
        scheduling_strategy=None,
        lifetime: Optional[str] = None,
        runtime_env: Optional[dict] = None,
    ) -> str:
        cls_key = self._export_callable(cls, "cls")
        actor_id = ActorID.of(self.job_id).hex()
        env, deps = self._prepare_args(args, kwargs)
        spec = {
            "actor_id": actor_id,
            "cls_key": cls_key,
            "cls_name": getattr(cls, "__name__", str(cls)),
            "args": env,
            "deps": deps,
            "name": name,
            "namespace": namespace if namespace is not None else self.namespace,
            "resources": resources,
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "scheduling_strategy": scheduling_strategy,
            "lifetime": lifetime,
            "runtime_env": self.merged_runtime_env(runtime_env),
        }
        self.request({"t": "create_actor", "spec": spec})
        return actor_id

    def submit_actor_task(
        self,
        actor_id: str,
        method: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
    ) -> List["ObjectRef"]:
        from ..object_ref import ObjectRef

        task_id = TaskID.for_actor_task(ActorID.from_hex(actor_id))
        return_ids = [ObjectID.for_return(task_id, i).hex() for i in range(num_returns)]
        env, deps = self._prepare_args(args, kwargs)
        spec = {
            "task_id": task_id.hex(),
            "actor_id": actor_id,
            "method": method,
            "args": env,
            "deps": deps,
            "return_ids": return_ids,
        }
        self.request({"t": "add_refs", "counts": {oid: 1 for oid in return_ids}})
        self.request({"t": "submit_actor_task", "spec": spec})
        return [ObjectRef(oid, skip_adding_local_ref=True) for oid in return_ids]


global_worker = Worker()


# --------------------------------------------------------------------------
# task execution (the worker side of run_task)
# --------------------------------------------------------------------------


def resolve_task_args(args_msg: dict) -> Tuple[tuple, dict]:
    env: serialization.SerializedObject = args_msg["env"]
    resolved: Dict[str, serialization.SerializedObject] = args_msg["resolved"]
    env = serialization.materialize(env, global_worker.shm)
    args, kwargs = serialization.deserialize(env)

    def conv(a):
        if isinstance(a, _ArgRef):
            dep_env = resolved.get(a.object_id)
            if dep_env is None:
                raise exceptions.ObjectLostError(a.object_id)
            dep_env = serialization.materialize(dep_env, global_worker.shm)
            value = serialization.deserialize(dep_env)
            if getattr(dep_env, "is_error", False):
                raise value
            return value
        return a

    args = tuple(conv(a) for a in args)
    kwargs = {k: conv(v) for k, v in kwargs.items()}
    return args, kwargs


def execute_and_package(fn, fn_name: str, args_msg: dict, return_ids: List[str]) -> dict:
    """Run a task function and package results as envelopes.

    Reference: _raylet.pyx:1630 execute_task_with_cancellation_handler.
    """
    try:
        args, kwargs = resolve_task_args(args_msg)
        result = fn(*args, **kwargs)
        n = len(return_ids)
        if n == 0:
            return {"results": []}
        if n == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != n:
                raise ValueError(
                    f"Task {fn_name} set num_returns={n} but returned {len(values)} values"
                )
        from .config import GLOBAL_CONFIG as cfg

        envs = []
        for v in values:
            env = serialization.serialize(v)
            envs.append(
                serialization.externalize(env, global_worker.shm, cfg.object_inline_limit_bytes)
            )
        return {"results": envs}
    except Exception as e:  # noqa: BLE001
        tb = traceback.format_exc()
        if isinstance(e, (exceptions.TaskError, exceptions.ActorError)):
            err: Exception = e
        else:
            err = exceptions.TaskError(fn_name, tb, e)
        env = serialization.serialize(err)
        env.is_error = True  # type: ignore[attr-defined]
        return {"results": [env for _ in return_ids] or [env]}


@atexit.register
def _shutdown_at_exit():
    w = global_worker
    if w.mode == MODE_DRIVER and w.node is not None:
        try:
            w.node.stop()
        except Exception:
            pass
