"""Node memory monitor: detects host memory pressure for the OOM killer.

Reference parity: src/ray/common/memory_monitor.h:52 (MemoryMonitor) — the
reference samples /proc + cgroup limits on a timer inside the raylet and
invokes a kill callback above `memory_usage_threshold`. ray_tpu samples the
same sources (cgroup v2, then cgroup v1, then /proc/meminfo) from the head
(head node) and each node agent (remote nodes); the kill *policy* runs
centrally in the head (worker_killing_policy.h analogue) where the task
table lives.

Test hook: `cfg.memory_monitor_test_path` names a file holding
"<used_bytes> <total_bytes>" — when set, samples come from that file so
tests can stage pressure deterministically.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from .config import GLOBAL_CONFIG as cfg

_CGROUP_V2 = "/sys/fs/cgroup"
_CGROUP_V1_MEM = "/sys/fs/cgroup/memory"
# cgroup files report "max" (v2) or a huge sentinel (v1) when unlimited
_UNLIMITED_ABOVE = 1 << 60


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            raw = f.read().strip()
    except OSError:
        return None
    if raw == "max":
        return None
    try:
        n = int(raw)
    except ValueError:
        return None
    return None if n >= _UNLIMITED_ABOVE else n


def _stat_value(path: str, key: str) -> int:
    """One "key value" line from a cgroup stat file (0 if absent). Used to
    subtract reclaimable page cache from the usage counter — the raw
    cgroup counter includes file cache the kernel would reclaim long
    before OOM, and counting it would fire false-positive kills (the
    reference subtracts inactive_file the same way)."""
    try:
        with open(path) as f:
            for line in f:
                parts = line.split()
                if len(parts) == 2 and parts[0] == key:
                    return int(parts[1])
    except (OSError, ValueError):
        pass
    return 0


def _proc_meminfo() -> Tuple[int, int]:
    """(used, total) from /proc/meminfo, counting reclaimable page cache as
    free (MemAvailable), like the reference."""
    total = available = 0
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1]) * 1024
            elif line.startswith("MemAvailable:"):
                available = int(line.split()[1]) * 1024
    return max(0, total - available), total


class MemoryMonitor:
    """Samples (used_bytes, total_bytes) for this node."""

    def __init__(self):
        self.threshold = cfg.memory_usage_threshold

    def sample(self) -> Tuple[int, int]:
        test_path = cfg.memory_monitor_test_path
        if test_path:
            try:
                with open(test_path) as f:
                    used, total = f.read().split()
                return int(used), int(total)
            except (OSError, ValueError):
                return 0, 1
        # cgroup v2 (unified hierarchy)
        limit = _read_int(os.path.join(_CGROUP_V2, "memory.max"))
        if limit:
            used = _read_int(os.path.join(_CGROUP_V2, "memory.current")) or 0
            used -= _stat_value(os.path.join(_CGROUP_V2, "memory.stat"), "inactive_file")
            return max(0, used), limit
        # cgroup v1
        limit = _read_int(os.path.join(_CGROUP_V1_MEM, "memory.limit_in_bytes"))
        if limit:
            used = _read_int(
                os.path.join(_CGROUP_V1_MEM, "memory.usage_in_bytes")
            ) or 0
            used -= _stat_value(
                os.path.join(_CGROUP_V1_MEM, "memory.stat"), "total_inactive_file"
            )
            return max(0, used), limit
        return _proc_meminfo()

    def is_pressured(self) -> Tuple[bool, int, int]:
        used, total = self.sample()
        return (total > 0 and used / total >= self.threshold), used, total
