"""Head service: the control plane of a ray_tpu cluster.

Reference parity (collapsed, by design): the reference splits the control
plane into a GCS server (src/ray/gcs/gcs_server/gcs_server.cc:130-178 —
node/actor/job/KV/health managers), a per-node raylet
(src/ray/raylet/node_manager.h:117 — leases, worker pool, scheduling), and a
per-process CoreWorker (src/ray/core_worker/core_worker.h:284). On a TPU pod
the natural control-plane unit is the *host* (one Python process drives 4-8
chips via one XLA client; compute parallelism lives inside compiled SPMD
programs, not in process fan-out), so ray_tpu runs ONE asyncio head service
holding the GCS tables, the cluster scheduler, and the object directory, with
per-node worker pools hanging off it. This trades the reference's
multi-daemon fault isolation for a dramatically shorter hot path — the same
trade the reference itself makes inside a node via lease reuse
(direct_task_transport.cc:191).

Subcomponents kept 1:1 with the reference inventory (SURVEY §2.1):
  - KV store               <- GcsKVManager (store_client_kv.h)
  - ObjectDirectory        <- CoreWorkerMemoryStore + ownership directory
  - ActorManager           <- GcsActorManager (gcs_actor_manager.h:281)
  - NodeTable + Scheduler  <- ClusterTaskManager/ClusterResourceScheduler
                              (cluster_task_manager.h:42, hybrid policy)
  - PlacementGroupManager  <- GcsPlacementGroupManager (gcs_placement_group_manager.h:225)
  - WorkerPool             <- worker_pool.h:156 (lease reuse = idle pool)
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import shutil
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from . import faults, protocol
from .config import GLOBAL_CONFIG as cfg

logger = logging.getLogger(__name__)


# --------------------------------------------------------------------------
# Records
# --------------------------------------------------------------------------


@dataclass
class NodeRecord:
    node_id: str
    resources: Dict[str, float]
    available: Dict[str, float] = field(default_factory=dict)
    alive: bool = True
    labels: Dict[str, str] = field(default_factory=dict)
    # agent connection for REAL remote nodes (reference: the raylet's gRPC
    # channel, node_manager.h:117); None for the head node and for logical
    # resource-only nodes (autoscaler simulations)
    conn: Optional["protocol.Connection"] = None
    health_failures: int = 0
    probing: bool = False
    # last load report from the node's agent (ray_syncer analogue)
    load_report: Optional[Dict[str, Any]] = None
    # the node's peer-facing bulk plane listener (object_manager.h:117);
    # consumers dial it directly — the head only serves this location
    buffer_addr: Optional[str] = None

    def __post_init__(self):
        if not self.available:
            self.available = dict(self.resources)

    @property
    def remote(self) -> bool:
        return self.conn is not None


@dataclass
class WorkerRecord:
    worker_id: str
    node_id: str
    proc: Optional[subprocess.Popen] = None
    conn: Optional[protocol.Connection] = None
    state: str = "starting"  # starting | idle | busy | actor | dead
    actor_id: Optional[str] = None
    registered: Optional[asyncio.Future] = None
    num_running: int = 0
    pooled: bool = True
    health_failures: int = 0
    probing: bool = False
    # caller->worker push endpoint (unix path or host:port) for the direct
    # actor-call transport (direct_actor_task_submitter.h:67)
    direct_address: Optional[str] = None
    # set by the OOM killing policy so the task-failure path can surface an
    # OutOfMemoryError instead of a generic crash (worker_killing_policy.h)
    kill_reason: Optional[str] = None


@dataclass
class TaskRecord:
    spec: dict  # the wire-format task spec
    retries_left: int = 0
    resources: Dict[str, float] = field(default_factory=dict)
    node_id: Optional[str] = None
    state: str = "pending"  # pending|waiting_deps|scheduled|running|done|failed|cancelled
    deps_remaining: int = 0
    worker_id: Optional[str] = None
    # set by _h_cancel_task; queued records are dropped lazily when popped
    cancel_requested: bool = False
    # (state, wall-time) transitions — feeds the state API + `timeline()`
    # (reference: core_worker/task_event_buffer.h -> gcs_task_manager.h:61)
    events: List = field(default_factory=list)

    def mark(self, state: str):
        self.state = state
        self.events.append((state, time.time()))


@dataclass
class ActorRecord:
    actor_id: str
    spec: dict
    state: str = "pending"  # pending|starting|alive|restarting|dead
    worker_id: Optional[str] = None
    name: Optional[str] = None
    restarts_left: int = 0
    death_reason: str = ""
    # queued calls submitted while (re)starting
    backlog: List[dict] = field(default_factory=list)
    # set once the scheduler has reserved node resources for this actor
    # (autoscaler demand accounting: acquired != unmet)
    node_acquired: bool = False
    # serializes dep-resolution + send so per-caller submission order is
    # preserved (reference: actor_scheduling_queue.cc sequence numbers)
    send_lock: Optional[asyncio.Lock] = None


@dataclass
class BundleState:
    index: int
    resources: Dict[str, float]
    node_id: Optional[str] = None
    available: Dict[str, float] = field(default_factory=dict)


@dataclass
class PlacementGroupRecord:
    pg_id: str
    bundles: List[BundleState]
    strategy: str
    state: str = "pending"  # pending | created | removed
    name: Optional[str] = None
    ready_event: Optional[asyncio.Event] = None


def _advertise_host(bind_host: str) -> str:
    """The address peers should dial. For a wildcard bind, find this host's
    outbound IP (remote agents relay it to the workers they spawn — a
    loopback advert would make those workers dial themselves)."""
    if bind_host not in ("0.0.0.0", ""):
        return bind_host
    import socket

    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))  # no packet sent; picks the route
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _acquire(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


def _release(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) + v


# --------------------------------------------------------------------------
# Object directory
# --------------------------------------------------------------------------


class ObjectDirectory:
    """Owner-side object table: envelopes + availability events + refcounts.

    Reference: CoreWorkerMemoryStore (memory_store/memory_store.h:43) for
    small objects and the ownership table of reference_count.h:61. Buffers of
    large objects live in the shared-memory plane; only envelopes live here.
    """

    def __init__(self, on_free=None):
        self.objects: Dict[str, Any] = {}
        self.events: Dict[str, asyncio.Event] = {}
        self.refcounts: collections.Counter = collections.Counter()
        self.task_pins: collections.Counter = collections.Counter()
        self.errors: Dict[str, Any] = {}
        self.on_free = on_free  # called with the envelope when freed
        self.on_free_oid = None  # called with the object id when freed
        # oids with a wait_available coroutine between entry and wakeup.
        # Incremented SYNCHRONOUSLY before the first await — unlike
        # ev._waiters, which only gains the waiter one loop iteration
        # later (asyncio.wait_for wraps ev.wait() in ensure_future), so
        # _maybe_free can trust this counter where ev._waiters lies.
        # The PR-5..PR-10 lost-get_objects wedge lived in exactly that
        # gap: a transient refcount 0 popped the "waiterless" event, the
        # producer's put minted+set a NEW event, and the parked handler
        # then registered on the orphaned old one forever.
        self._waiting: collections.Counter = collections.Counter()
        # free generation per oid (bounded breadcrumb): bumped every time a
        # STORED envelope is actually freed. Lets wait_available distinguish
        # "not arrived yet" (park) from "freed out from under me" (raise, so
        # the get_objects handler can reconstruct from lineage or fail
        # loudly) — without this, the arrived-then-freed refcount interleave
        # (a consumer's add_refs borrow still in flight when the last
        # existing ref dropped) parks the getter forever and retransmits
        # just re-execute into the same void.
        self.freed_gen: Dict[str, int] = {}
        self._freed_order: collections.deque = collections.deque()
        self._freed_cap = 4096

    def _event(self, oid: str) -> asyncio.Event:
        ev = self.events.get(oid)
        if ev is None:
            ev = self.events[oid] = asyncio.Event()
        return ev

    def put(self, oid: str, envelope: Any):
        self.objects[oid] = envelope
        self._event(oid).set()

    def invalidate(self, oid: str):
        """Drop a stale envelope (its shm buffers were lost) so waiters
        block until reconstruction re-puts it. Refcounts are untouched."""
        self.objects.pop(oid, None)
        ev = self.events.get(oid)
        if ev is not None:
            ev.clear()

    def contains(self, oid: str) -> bool:
        return oid in self.objects

    async def wait_available(self, oid: str, timeout: Optional[float] = None):
        if oid in self.objects:
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        # snapshot the free generation: a bump DURING this wait means the
        # object existed and was freed under us — parking again would never
        # end (nothing re-puts a freed object except reconstruction, which
        # is the caller's job once we raise). Entry-time staleness (freed
        # long before this wait began) is the caller's to check via
        # freed_gen — snapshot semantics keep _reconstruct's own
        # wait_available from insta-raising on the very oid it is reviving.
        start_gen = self.freed_gen.get(oid, 0)
        self._waiting[oid] += 1  # BEFORE any await: guards the event entry
        try:
            while oid not in self.objects:
                if self.freed_gen.get(oid, 0) != start_gen:
                    from ..exceptions import ObjectLostError

                    raise ObjectLostError(oid)
                ev = self._event(oid)  # re-fetch: identity may have changed
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise asyncio.TimeoutError()
                await asyncio.wait_for(ev.wait(), remaining)
                if oid not in self.objects:
                    # stale wakeup: the envelope was freed/invalidated
                    # between set and wake — clear so the loop parks again
                    # instead of spinning (other loop-waiters re-check the
                    # same way, so clearing a shared event is safe)
                    ev.clear()
        finally:
            self._waiting[oid] -= 1
            if self._waiting[oid] <= 0:
                del self._waiting[oid]

    def get(self, oid: str):
        return self.objects[oid]

    def add_ref(self, oid: str, n: int = 1):
        self.refcounts[oid] += n

    def remove_ref(self, oid: str, n: int = 1):
        self.refcounts[oid] -= n
        self._maybe_free(oid)

    def pin(self, oid: str):
        self.task_pins[oid] += 1

    def unpin(self, oid: str):
        self.task_pins[oid] -= 1
        self._maybe_free(oid)

    def _maybe_free(self, oid: str):
        if self.refcounts[oid] <= 0 and self.task_pins[oid] <= 0:
            env = self.objects.pop(oid, None)
            if env is None and self.refcounts[oid] < 0:
                # a remove_refs outran its object's arrival (direct-path
                # results carry the caller's +1 on the put itself): keep
                # the debt so the late put reconciles to zero and frees
                self.task_pins.pop(oid, None)
                return
            # NEVER drop an event someone is parked on: a later put would
            # mint a NEW event and set that one, stranding the old waiters
            # forever (the direct-path free/put interleave hits this —
            # get_objects parks, a transient count reaches 0, the producer's
            # put lands after). ev._waiters ALONE is not enough: between
            # wait_available's entry and asyncio.wait_for scheduling the
            # ev.wait() waiter there is a full loop iteration where the
            # waiter is invisible — the root cause of the carried
            # lost-get_objects wedge — so the _waiting counter (bumped
            # synchronously before the first await) must hold the event
            # alive through that gap.
            ev = self.events.get(oid)
            if ev is not None and not ev._waiters and not self._waiting.get(oid):
                self.events.pop(oid, None)
            self.refcounts.pop(oid, None)
            self.task_pins.pop(oid, None)
            if env is not None:
                # a STORED envelope died: leave a bounded breadcrumb so a
                # parked (or future) getter can tell freed from not-yet-put,
                # and wake anyone currently parked so they observe the free
                # (their wait_available raises ObjectLostError and the
                # get_objects handler takes the reconstruction path)
                if oid not in self.freed_gen:
                    self._freed_order.append(oid)
                    while len(self._freed_order) > self._freed_cap:
                        self.freed_gen.pop(self._freed_order.popleft(), None)
                self.freed_gen[oid] = self.freed_gen.get(oid, 0) + 1
                if ev is not None and (ev._waiters or self._waiting.get(oid)):
                    ev.set()
            if env is not None and self.on_free is not None:
                self.on_free(env)
            if self.on_free_oid is not None:
                self.on_free_oid(oid, None)


# --------------------------------------------------------------------------
# Head
# --------------------------------------------------------------------------


class Head:
    def __init__(self, session_dir: str, head_node_resources: Dict[str, float]):
        self.session_dir = session_dir
        self.socket_path = os.path.join(session_dir, "head.sock")
        self.kv: Dict[str, Dict[str, bytes]] = collections.defaultdict(dict)
        self.objects = ObjectDirectory(on_free=self._free_shm_buffers)
        self.nodes: Dict[str, NodeRecord] = {}
        self.workers: Dict[str, WorkerRecord] = {}
        self.actors: Dict[str, ActorRecord] = {}
        self.named_actors: Dict[Tuple[str, str], str] = {}  # (namespace, name) -> actor_id
        # name -> {conn, functions, inflight: call_id->return_id, next_call}
        # (cross-language task execution; reference: cpp/src/ray/runtime
        # task_executor — C++ processes registering callables by name)
        self.cpp_executors: Dict[str, dict] = {}
        self.placement_groups: Dict[str, PlacementGroupRecord] = {}
        self.tasks: Dict[str, TaskRecord] = {}
        self.pending_queue: collections.deque = collections.deque()
        # demand shapes with no current placement; persists across pumps
        # (see _pump/_capacity_changed) so submit storms stay O(1) each.
        # Their tasks wait in _parked, OUT of pending_queue, so pumps stay
        # O(new work) even with a 100k-task unplaceable backlog
        self._blocked_sigs: Set[Any] = set()
        self._parked: Dict[Any, collections.deque] = {}
        # head-routed actor calls in flight: task_id -> worker_id, so
        # cancel_task can reach a call that has no TaskRecord
        self._actor_inflight: Dict[str, str] = {}
        # streaming-generator bookkeeping: the yields' baseline refs are
        # owned by the task's completion object — freeing it frees them
        # (reference: dynamic returns are freed with their generator ref)
        self._stream_children: Dict[str, List[str]] = {}  # task_id -> oids
        self._stream_completion: Dict[str, str] = {}  # completion oid -> task_id
        self.idle_workers: Dict[str, List[str]] = collections.defaultdict(list)
        self.server: Optional[asyncio.base_events.Server] = None
        self.tcp_server: Optional[asyncio.base_events.Server] = None
        self.tcp_address: Optional[str] = None
        self._worker_counter = 0
        self._client_conns: Set[protocol.Connection] = set()
        self._head_node_id = "node-head"
        self.nodes[self._head_node_id] = NodeRecord(self._head_node_id, dict(head_node_resources))
        self._shutdown = False
        # fire-and-forget control-plane coroutines (actor starts, actor-task
        # runs, PG scheduling, dispatches). Tracked so stop() cancels them —
        # an untracked pending task spews "Task was destroyed but it is
        # pending!" at interpreter exit and buries real close regressions.
        self._bg_tasks: Set[asyncio.Task] = set()
        self._max_task_workers: Dict[str, int] = {}
        self._spawning_task_workers: collections.Counter = collections.Counter()
        self._driver_conn: Optional[protocol.Connection] = None
        self.job_config: Dict[str, Any] = {}
        self._shm = None
        self._shm_tried = False
        # lineage: return-object id -> creating task id (stateless tasks
        # only; reference: task_manager.h:164 lineage pinning). Entries die
        # with their object's last reference.
        self.object_lineage: Dict[str, str] = {}
        # lineage of FREED objects (bounded): when a free retires a lineage
        # entry, the oid->task mapping moves here so a getter that lost the
        # refcount race (its add_refs borrow still in flight when the last
        # ref dropped) can re-run the creating task instead of wedging
        self._freed_lineage: "collections.OrderedDict[str, str]" = (
            collections.OrderedDict()
        )
        self._reconstructing: Dict[str, asyncio.Future] = {}
        self.objects.on_free_oid = self._on_object_freed
        # per-process metric snapshots: proc key -> {metric key -> snapshot}
        self.metrics_store: Dict[str, dict] = {}
        # serve flight-recorder snapshots (serve/telemetry.py): proc key ->
        # {"ts", "events", "dropped"}. Deliberately NOT pruned at conn
        # close — a reaped/crashed replica's last events are exactly the
        # post-mortem this store exists for; bounded by proc count instead.
        self.serve_events_store: Dict[str, dict] = {}
        # named-channel pubsub (reference: src/ray/pubsub publisher.h:307 /
        # subscriber.h:329; serve's long-poll rides the same channels,
        # serve/_private/long_poll.py:68). Per channel: latest (seq, data)
        # snapshot + push-subscribed connections + long-poll wakeup event.
        self.channels: Dict[str, Tuple[int, Any]] = {}
        self.channel_subscribers: Dict[str, Set[protocol.Connection]] = (
            collections.defaultdict(set)
        )
        self._channel_events: Dict[str, asyncio.Event] = {}
        self._channel_waiters: Dict[str, int] = {}
        self._push_tasks: Set[asyncio.Task] = set()
        # handler name -> {count, total_ms, max_ms} (event_stats.h analogue)
        self.event_stats: Dict[str, dict] = {}
        # object bytes relayed through the head (fetch_buffers fallback
        # path only — the direct node-to-node plane keeps this ~0)
        self.relay_bytes: int = 0
        # direct task leases: worker_id -> {conn, node_id, resources}
        # (direct_task_transport.cc:191 lease bookkeeping)
        self._task_leases: Dict[str, dict] = {}
        # dashboard observability: per-worker log rings + per-node load
        # history (reference: dashboard/modules/{log,reporter})
        self.log_ring: Dict[str, "collections.deque"] = {}
        self.node_history: Dict[str, "collections.deque"] = {}
        self._log_interest_until = 0.0
        # submitted jobs: submission_id -> record (entrypoint subprocess)
        self.jobs: Dict[str, dict] = {}
        self._prestart_tasks: List[asyncio.Task] = []

    def _spawn_bg(self, coro) -> asyncio.Task:
        """create_task with shutdown bookkeeping: stop() cancels whatever is
        still pending so nothing leaks past the event loop's lifetime."""
        task = asyncio.get_running_loop().create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def _shm_client(self):
        if not self._shm_tried:
            self._shm_tried = True
            from .shm import connect_for_session

            self._shm = connect_for_session(self.session_dir)
            if self._shm is not None:
                # one pretouch per machine: producers then run at memcpy
                # speed instead of paying first-touch faults per put
                self._shm.pretouch_async()
        return self._shm

    def _free_shm_buffers(self, env):
        from .serialization import shm_buffer_refs

        try:
            refs = shm_buffer_refs(env)
        except Exception:
            return
        if not refs:
            return
        by_node: Dict[str, List[str]] = collections.defaultdict(list)
        for r in refs:
            by_node[r.node or self._head_node_id].append(r.name)
        for node_id, names in by_node.items():
            node = self.nodes.get(node_id)
            if node is not None and node.remote:
                if not node.conn.closed:
                    try:
                        asyncio.get_running_loop().create_task(
                            node.conn.send({"t": "delete_buffers", "names": names})
                        )
                    except RuntimeError:
                        pass  # loop gone (shutdown)
                continue
            # head node AND logical nodes: workers share the head machine's
            # session shm plane, so delete locally
            shm = self._shm_client()
            if shm is not None:
                for n in names:
                    shm.delete(n)

    async def _h_buffer_addrs(self, conn, msg):
        """Owner-directed location lookup (pull_manager.h:52): where is each
        node's bulk plane? Consumers dial the addr directly (and, when the
        peer's shm session lives on THEIR machine, attach it instead of
        using TCP at all) and cache the answer; the head never sees the
        object bytes."""
        session = os.path.basename(self.session_dir)
        out = {}
        for nid in msg["nodes"]:
            node = self.nodes.get(nid)
            if node is None or not node.alive or not node.buffer_addr:
                out[nid] = None
                continue
            out[nid] = {
                "addr": node.buffer_addr,
                "shm_session": f"{session}_{nid}",
            }
        return out

    async def _h_fetch_buffers(self, conn, msg):
        """RELAY FALLBACK for cross-node pulls (consumers first try the
        owner's bulk plane via buffer_addrs; reference analogue:
        object_manager.h:117). Relayed bytes are counted — tests and the
        dashboard assert the bulk plane stays off the head."""
        node_id = msg.get("node") or self._head_node_id
        names: List[str] = msg["names"]
        node = self.nodes.get(node_id)
        if node is not None and node.remote:
            if not node.alive or node.conn.closed:
                return {name: None for name in names}
            try:
                got = await node.conn.request(
                    {"t": "read_buffers", "names": names}, timeout=60
                )
            except Exception:
                return {name: None for name in names}
            self.relay_bytes += sum(len(v) for v in got.values() if v)
            # re-wrap for the consumer leg: the agent's WireBuffers arrived
            # as out-of-band views; send them onward the same way instead
            # of re-pickling the payload inline
            return {
                name: None if v is None else protocol.WireBuffer(v)
                for name, v in got.items()
            }
        # head node and logical nodes share the head machine's shm plane:
        # serve slab views out-of-band, zero head-side copies
        shm = self._shm_client()
        out = {}
        for name in names:
            mv = None if shm is None else shm.get_or_spilled(name)
            out[name] = None if mv is None else protocol.WireBuffer(mv)
        return out

    async def start(self, tcp_host: Optional[str] = None, tcp_port: Optional[int] = None):
        """Listen on the session unix socket AND on TCP (the multi-host
        plane; reference: grpc_server.h:73). The bound host:port is written
        to <session_dir>/head_addr for discovery by `init(address=...)`."""
        # a stale socket file survives a crashed head whose session this
        # start is restoring; binding over it needs the unlink
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        self.server = await asyncio.start_unix_server(self._on_client, path=self.socket_path)
        self._shm_client()  # connect early: kicks off the slab pretouch
        if cfg.head_restore_path:
            try:
                self._load_snapshot(cfg.head_restore_path)
            except FileNotFoundError:
                logger.warning("no head snapshot at %s", cfg.head_restore_path)
            except Exception:
                # a corrupt/incompatible snapshot must not keep the head
                # from starting; whatever restored before the failure stays
                logger.exception(
                    "failed to restore head snapshot %s; starting fresh",
                    cfg.head_restore_path,
                )
        if cfg.head_snapshot_period_ms > 0:
            self._snapshot_task = asyncio.get_running_loop().create_task(
                self._snapshot_loop()
            )
        self._prestart_workers(self._head_node_id)
        if cfg.dashboard_enabled:
            from ..dashboard import Dashboard

            self.dashboard = Dashboard(self)
            addr = await self.dashboard.start(cfg.dashboard_host, cfg.dashboard_port)
            if addr:
                with open(os.path.join(self.session_dir, "dashboard_addr"), "w") as f:
                    f.write(addr)
        # liveness prober: a hung worker/agent keeps its socket open, so
        # connection-close detection alone misses it (reference:
        # gcs_health_check_manager.h:39 periodic health checks)
        self._health_task = asyncio.get_running_loop().create_task(self._health_loop())
        if cfg.memory_monitor_refresh_ms > 0:
            self._memory_task = asyncio.get_running_loop().create_task(
                self._memory_loop()
            )
        if cfg.log_to_driver:
            self._log_tail_task = asyncio.get_running_loop().create_task(
                self._log_tail_loop()
            )
        host = tcp_host if tcp_host is not None else cfg.head_tcp_host
        port = tcp_port if tcp_port is not None else cfg.head_tcp_port
        try:
            self.tcp_server = await asyncio.start_server(self._on_client, host=host, port=port)
        except OSError as e:
            logger.warning("head TCP listener failed (%s); single-host only", e)
            self.tcp_server = None
            return
        bound = self.tcp_server.sockets[0].getsockname()
        self.tcp_address = f"{_advertise_host(host)}:{bound[1]}"
        with open(os.path.join(self.session_dir, "head_addr"), "w") as f:
            f.write(self.tcp_address)

    # ------------------------------------------------------------------
    # persistence (reference: gcs_table_storage.h:252 + gcs_init_data.h —
    # periodic snapshot instead of per-write Redis mirroring: the metadata
    # volume is small and the fsync cost of per-write mirroring would sit
    # on the control hot path)
    # ------------------------------------------------------------------

    def _snapshot_path(self) -> str:
        return cfg.head_snapshot_path or os.path.join(self.session_dir, "head_state.pkl")

    def _write_snapshot(self):
        """Capture + write in one go (event-loop context only)."""
        self._write_state(self._snapshot_state())

    def _snapshot_state(self) -> dict:
        """Capture the state dict ON the event loop — mutations are loop-
        serialized, so capturing here (it's small metadata) avoids racing
        dict iteration against handlers; only the file IO leaves the loop."""
        state = {
            "version": 1,
            "time": time.time(),
            "session_id": os.path.basename(self.session_dir),
            "kv": {ns: dict(table) for ns, table in self.kv.items()},
            "named_actors": dict(self.named_actors),
            "actors": {
                aid: {
                    "actor_id": aid,
                    "name": rec.name,
                    "state": rec.state,
                    "spec": {
                        k: rec.spec.get(k)
                        for k in (
                            "actor_id", "cls_key", "cls_name", "name",
                            "namespace", "resources", "max_restarts",
                            "max_concurrency", "method_names", "lifetime",
                        )
                    },
                }
                for aid, rec in self.actors.items()
            },
            "jobs": {sid: self._job_view(j) for sid, j in self.jobs.items()},
            "placement_groups": {
                pid: {
                    "pg_id": pid,
                    "strategy": rec.strategy,
                    "name": rec.name,
                    "bundles": [dict(b.resources) for b in rec.bundles],
                }
                for pid, rec in self.placement_groups.items()
            },
        }
        return state

    def _write_state(self, state: dict):
        import pickle

        from .snapshot_store import store_for

        store_for(self._snapshot_path()).save(pickle.dumps(state))

    def _load_snapshot(self, target: str):
        """Reload metadata from a previous head's snapshot (any snapshot
        store: plain file, sqlite:// versioned db, gs:// object). Processes
        are gone: actors come back as DEAD records (name registry + specs
        kept so they are discoverable and re-creatable), jobs that were
        RUNNING are marked FAILED, the KV store (function/class exports
        included) is restored verbatim."""
        import pickle

        from .snapshot_store import store_for

        data = store_for(target).load()
        if data is None:
            raise FileNotFoundError(f"no snapshot in store {target!r}")
        state = pickle.loads(data)
        if state.get("version") != 1:
            raise ValueError(f"unsupported snapshot version {state.get('version')!r}")
        for ns, table in state.get("kv", {}).items():
            self.kv[ns].update(table)
        for aid, meta in state.get("actors", {}).items():
            self.actors[aid] = ActorRecord(
                actor_id=aid,
                spec=dict(meta["spec"] or {}),
                name=meta.get("name"),
                state="dead",
                death_reason="head restarted (restored from snapshot)",
            )
        self.named_actors.update(
            {tuple(k) if isinstance(k, list) else k: v
             for k, v in state.get("named_actors", {}).items()}
        )
        for sid, job in state.get("jobs", {}).items():
            job = dict(job)
            if job.get("status") == "RUNNING":
                job["status"] = "FAILED"
                job["message"] = "head restarted"
            job["proc"] = None
            self.jobs[sid] = job
        for pid, meta in state.get("placement_groups", {}).items():
            bundles = [
                BundleState(i, dict(b), available=dict(b))
                for i, b in enumerate(meta["bundles"])
            ]
            rec = PlacementGroupRecord(
                pg_id=pid,
                bundles=bundles,
                strategy=meta["strategy"],
                name=meta.get("name"),
                ready_event=asyncio.Event(),
            )
            self.placement_groups[pid] = rec
            # re-place on whatever capacity this cluster grows
            self._spawn_bg(self._schedule_pg(rec))
        logger.info(
            "restored head state from %s: %d kv namespaces, %d actors, %d jobs",
            target, len(state.get("kv", {})), len(state.get("actors", {})),
            len(state.get("jobs", {})),
        )

    async def _snapshot_loop(self):
        period = cfg.head_snapshot_period_ms / 1000.0
        loop = asyncio.get_running_loop()
        while not self._shutdown:
            await asyncio.sleep(period)
            try:
                state = self._snapshot_state()  # on-loop: race-free capture
                self._snapshot_inflight = loop.run_in_executor(
                    None, self._write_state, state
                )
                await self._snapshot_inflight
            except Exception:
                logger.exception("head snapshot failed")
            finally:
                self._snapshot_inflight = None

    async def _health_loop(self):
        period = cfg.health_check_period_ms / 1000.0
        loop = asyncio.get_running_loop()
        while not self._shutdown:
            await asyncio.sleep(period)
            # safety valve for the persistent blocked-shape memo: any
            # capacity transition that forgot to call _capacity_changed
            # costs at most one health period of scheduling delay. The
            # incremental probe (O(#shapes), promotes until the probe
            # misses) is sufficient to make progress — a bulk requeue here
            # would re-walk a 100k parked backlog every tick forever
            if self._blocked_sigs or self._parked:
                self._capacity_changed(bulk=False)
            for w in list(self.workers.values()):
                if w.state in ("dead", "starting") or w.conn is None or w.probing:
                    continue
                loop.create_task(self._probe(w, w.conn, self._declare_worker_hung(w)))
            for n in list(self.nodes.values()):
                if n.alive and n.remote and not n.conn.closed and not n.probing:
                    loop.create_task(self._probe(n, n.conn, self._declare_node_hung(n)))

    async def _probe(self, target, conn, on_dead):
        """One liveness probe. The timeout covers the SEND too — a hung peer
        can block the connection's send lock (e.g. mid-drain backpressure),
        and a probe stuck in send would otherwise never fail."""
        target.probing = True
        try:
            await asyncio.wait_for(
                conn.request({"t": "ping"}), cfg.health_check_period_ms / 1000.0
            )
            target.health_failures = 0
            on_dead.close()
        except Exception:
            target.health_failures += 1
            if target.health_failures >= cfg.health_check_failure_threshold:
                await on_dead
            else:
                on_dead.close()
        finally:
            target.probing = False

    # ------------------------------------------------------------------
    # OOM killing policy (reference: memory_monitor.h:52 sampling +
    # worker_killing_policy.h retriable-LIFO victim selection — kill the
    # newest retriable task first so older work survives pressure)
    # ------------------------------------------------------------------

    async def _memory_loop(self):
        from .memory_monitor import MemoryMonitor

        mon = MemoryMonitor()
        period = cfg.memory_monitor_refresh_ms / 1000.0
        while not self._shutdown:
            await asyncio.sleep(period)
            try:
                pressured, used, total = mon.is_pressured()
            except Exception:
                continue
            if pressured:
                await self._oom_kill(self._head_node_id, used, total)

    async def _h_memory_pressure(self, conn, msg):
        """A node agent's monitor reported pressure; run the policy there."""
        await self._oom_kill(msg["node_id"], msg["used"], msg["total"])

    # ------------------------------------------------------------------
    # worker log forwarding (reference: _private/log_monitor.py tails
    # per-process files and pushes lines to the driver for printing)
    # ------------------------------------------------------------------

    async def _publish_logs(self, worker_id: str, data: str):
        # bounded per-worker ring for the dashboard's log viewer
        # (reference: dashboard/modules/log — file tail over HTTP)
        ring = self.log_ring.get(worker_id)
        if ring is None:
            ring = self.log_ring[worker_id] = collections.deque(maxlen=400)
        for line in data.splitlines():
            ring.append(line)
        await self._h_publish(
            None, {"channel": "__logs__",
                   "data": {"worker_id": worker_id, "data": data}}
        )

    async def _h_worker_logs(self, conn, msg):
        """Remote agents forward their workers' output here."""
        await self._publish_logs(msg["worker_id"], msg["data"])

    async def _log_tail_loop(self):
        from . import log_tail

        log_dir = os.path.join(self.session_dir, "logs")
        offsets: Dict[str, int] = {}
        pending: Dict[str, tuple] = {}
        loop = asyncio.get_running_loop()
        while not self._shutdown:
            await asyncio.sleep(0.3)
            if not self._logs_wanted():
                # nobody listening: don't read content, but keep offsets at
                # the file ends — a later subscriber gets LIVE output, not
                # the accumulated backlog of the unsubscribed gap
                log_tail.fast_forward(log_dir, offsets)
                continue
            for worker_id, data in await loop.run_in_executor(
                None, log_tail.read_increments, log_dir, offsets, pending
            ):
                await self._publish_logs(worker_id, data)

    def _logs_wanted(self) -> bool:
        """True when a driver subscribed to __logs__ OR the dashboard's log
        viewer asked recently (interest expires so idle dashboards don't
        keep cross-host log traffic flowing forever)."""
        return bool(self.channel_subscribers.get("__logs__")) or (
            time.monotonic() < self._log_interest_until
        )

    async def _h_logs_wanted(self, conn, msg):
        """Agents poll this to gate their log forwarding (no subscribers ->
        no cross-host log traffic)."""
        return self._logs_wanted()

    async def _h_tail_logs(self, conn, msg):
        """Dashboard log viewer: last N buffered lines for one worker (and
        the list of workers with any buffered output). Requesting marks log
        interest for 30s so agents start forwarding."""
        self._log_interest_until = time.monotonic() + 30.0
        worker_id = msg.get("worker_id")
        out = {"workers": sorted(self.log_ring.keys())}
        if worker_id:
            ring = self.log_ring.get(worker_id)
            limit = int(msg.get("limit", 200))
            out["lines"] = list(ring)[-limit:] if ring else []
        return out

    async def _oom_kill(self, node_id: str, used: int, total: int):
        # per-node cooldown: the previous victim's memory takes time to
        # return to the OS, so killing once per sample would cascade through
        # the pool — but pressure on one node must not shield another
        now = time.monotonic()
        if not hasattr(self, "_oom_cooldowns"):
            self._oom_cooldowns: Dict[str, float] = {}
        if now < self._oom_cooldowns.get(node_id, 0.0):
            return
        victim: Optional[TaskRecord] = None
        # newest-first over running stateless tasks on the pressured node;
        # retriable tasks are preferred victims (their work is recoverable)
        for rec in reversed(list(self.tasks.values())):
            if rec.state != "running" or rec.node_id != node_id:
                continue
            w = self.workers.get(rec.worker_id or "")
            if w is None or w.state == "dead":
                continue
            if rec.retries_left > 0:
                victim = rec
                break
            if victim is None:
                victim = rec
        if victim is None:
            logger.warning(
                "node %s under memory pressure (%.0f%%) but no killable task "
                "worker found", node_id, 100.0 * used / max(total, 1),
            )
            # shorter cooldown than the kill path: rate-limits the warning
            # under sustained pressure with only unkillable work (actors),
            # while re-checking soon in case a killable task starts
            self._oom_cooldowns[node_id] = now + max(
                1.0, cfg.memory_monitor_refresh_ms / 1000.0
            )
            return
        w = self.workers[victim.worker_id]
        w.kill_reason = (
            f"worker OOM-killed on {node_id}: node memory {used}/{total} bytes "
            f"({100.0 * used / max(total, 1):.0f}%) exceeded "
            f"memory_usage_threshold={cfg.memory_usage_threshold}; task "
            f"{victim.spec['task_id']} was the newest "
            f"{'retriable' if victim.retries_left > 0 else 'running'} task"
        )
        logger.warning(w.kill_reason)
        self._oom_cooldowns[node_id] = now + max(
            2.0, 2 * cfg.memory_monitor_refresh_ms / 1000.0
        )
        # force-kill; the broken connection routes the running task through
        # _retry_or_fail, which surfaces kill_reason as OutOfMemoryError
        await self._terminate_worker(w, force=True)

    async def _declare_worker_hung(self, w: WorkerRecord):
        if w.state == "dead":
            return
        logger.warning("worker %s failed health checks; declaring dead", w.worker_id)
        # force-kill FIRST: a replacement (possibly TPU-owning) worker must
        # not start while the hung process may still hold the chips
        await self._terminate_worker(w, force=True, close_conn=False)
        await self._on_worker_death(w, reason="unresponsive (health prober)")
        if w.conn is not None:
            await w.conn.close()  # after death handling: reason stays accurate

    async def _declare_node_hung(self, n: NodeRecord):
        if not n.alive:
            return
        logger.warning("node %s failed health checks; declaring dead", n.node_id)
        # death handling first, then close (the close callback's
        # "connection closed" path is a guarded no-op afterwards)
        await self._on_node_death(n, reason="unresponsive (health prober)")
        await n.conn.close()

    async def stop(self):
        self._shutdown = True
        if getattr(self, "_health_task", None) is not None:
            self._health_task.cancel()
        if getattr(self, "_memory_task", None) is not None:
            self._memory_task.cancel()
        if getattr(self, "_log_tail_task", None) is not None:
            self._log_tail_task.cancel()
        if getattr(self, "_snapshot_task", None) is not None:
            self._snapshot_task.cancel()
        for t in list(self._prestart_tasks):
            t.cancel()  # no fresh workers after the kill sweep below
        # cancel fire-and-forget control-plane work (actor starts/calls,
        # PG scheduling, dispatches) and let the cancellations settle —
        # otherwise actor-heavy runs print "Task was destroyed but it is
        # pending!" at interpreter exit
        bg = [t for t in (self._bg_tasks | self._push_tasks) if not t.done()]
        for t in bg:
            t.cancel()
        if bg:
            await asyncio.gather(*bg, return_exceptions=True)
        for job in self.jobs.values():
            if job["status"] == "RUNNING":
                job["status"] = "STOPPED"
                self._terminate_job_proc(job["proc"])
        if cfg.head_snapshot_period_ms > 0:
            # an in-flight periodic write (executor thread: cancel doesn't
            # stop it) must land BEFORE the final write, or its stale state
            # would clobber the clean-shutdown snapshot
            inflight = getattr(self, "_snapshot_inflight", None)
            if inflight is not None:
                try:
                    await asyncio.wait_for(asyncio.shield(inflight), timeout=10)
                except Exception:
                    pass
            try:
                # final snapshot AFTER settling jobs: a clean shutdown must
                # not read as a crash (RUNNING -> FAILED) on restore
                self._write_snapshot()
            except Exception:
                pass
        for w in list(self.workers.values()):
            await self._kill_worker(w, reason="shutdown")
        for n in list(self.nodes.values()):
            if n.conn is not None and not n.conn.closed:
                try:
                    await n.conn.request({"t": "shutdown"}, timeout=2)
                except Exception:
                    pass
                await n.conn.close()
        if self.server is not None:
            self.server.close()
        if self.tcp_server is not None:
            self.tcp_server.close()
        if getattr(self, "dashboard", None) is not None:
            await self.dashboard.stop()
        # Close remaining client connections (incl. the driver's); 3.12's
        # Server.wait_closed would otherwise wait on them forever.
        for conn in list(self._client_conns):
            try:
                await conn.close()
            except Exception:
                pass
        # tear down the shared-memory plane
        shm = self._shm_client()
        if shm is not None:
            try:
                for env in self.objects.objects.values():
                    self._free_shm_buffers(env)
                shm.disconnect()
                from .shm import ShmClient

                ShmClient.destroy(os.path.basename(self.session_dir))
            except Exception:
                pass

    async def _on_client(self, reader, writer):
        conn: protocol.Connection = None  # type: ignore

        async def handler(msg):
            return await self.handle(conn, msg)

        async def on_close():
            self._client_conns.discard(conn)
            await self._on_conn_closed(conn)

        conn = protocol.Connection(reader, writer, handler, on_close)
        self._client_conns.add(conn)
        conn.start()

    async def _on_conn_closed(self, conn):
        # prune metric snapshots pushed over this connection (drivers AND
        # workers); doing it at conn-close means a racing in-flight push
        # can't resurrect the entry after an earlier prune
        for proc in getattr(conn, "_metric_procs", ()):
            self.metrics_store.pop(proc, None)
        for ch in getattr(conn, "_subscribed_channels", ()):
            subs = self.channel_subscribers.get(ch)
            if subs is not None:
                subs.discard(conn)
                if not subs:
                    del self.channel_subscribers[ch]
        # caller died holding direct task leases: reclaim the workers
        had_leases = bool(getattr(conn, "_task_leases", None))
        for wid in list(getattr(conn, "_task_leases", ())):
            self._drop_task_lease(wid)
            w = self.workers.get(wid)
            if w is not None and w.state != "dead":
                await self._return_leased_worker(w)
        if had_leases:
            self._capacity_changed(bulk=False)
        self._drop_cpp_executor(conn)
        for n in list(self.nodes.values()):
            if n.conn is conn and n.alive:
                await self._on_node_death(n, reason="agent connection closed")
        for w in list(self.workers.values()):
            if w.conn is conn and w.state != "dead":
                await self._on_worker_death(w, reason="connection closed")

    async def _on_node_death(self, node: NodeRecord, reason: str):
        """Agent died: the node and everything on it is gone (reference:
        GcsNodeManager node-death broadcast + NodeManager lease cleanup)."""
        if not node.alive:
            return
        node.alive = False
        if not self._shutdown:
            logger.warning("node %s died: %s", node.node_id, reason)
        for w in list(self.workers.values()):
            if w.node_id == node.node_id and w.state != "dead":
                # best effort: tell orphaned workers (agent-spawned procs
                # survive an agent SIGKILL) to exit, then run death handling
                if w.conn is not None and not w.conn.closed:
                    try:
                        await w.conn.send({"t": "shutdown"})
                    except Exception:
                        pass
                    await w.conn.close()
                await self._on_worker_death(w, reason=f"node died ({reason})")

    # ------------------------------------------------------------------
    # message handling
    # ------------------------------------------------------------------

    async def handle(self, conn, msg) -> Any:
        t = msg["t"]
        fn = getattr(self, f"_h_{t}", None)
        if fn is None:
            raise ValueError(f"unknown message type {t!r}")
        if faults.ACTIVE:
            delay = faults.handler_delay(t)
            if delay:
                await asyncio.sleep(delay)
        # per-handler latency/count accounting (reference: event_stats.h
        # instruments the asio loops); total-time includes awaits, so slow
        # entries here mean "long-running", busy_ms means "loop-hogging"
        start = time.perf_counter()
        try:
            return await fn(conn, msg)
        finally:
            dt = (time.perf_counter() - start) * 1000.0
            st = self.event_stats.get(t)
            if st is None:
                st = self.event_stats[t] = {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
            st["count"] += 1
            st["total_ms"] += dt
            if dt > st["max_ms"]:
                st["max_ms"] = dt

    async def _h_worker_kill_reason(self, conn, msg):
        """Why the head killed a worker (OOM policy), if it did. Direct-path
        callers consult this when a lease breaks mid-task so an OOM kill
        surfaces as OutOfMemoryError, not a generic crash (reference:
        worker_killing_policy.h + task failure cause plumbing)."""
        w = self.workers.get(msg["worker_id"])
        return w.kill_reason if w is not None else None

    async def _h_event_stats(self, conn, msg):
        return {
            t: dict(st, avg_ms=st["total_ms"] / max(1, st["count"]))
            for t, st in self.event_stats.items()
        }

    async def _h_object_stats(self, conn, msg):
        """Bulk-plane accounting: relayed bytes must stay ~0 when the
        direct node-to-node plane is healthy. bulk_* roll up the pushed
        per-process counters (bytes/pulls by path, relay fallbacks)."""
        out = {"relay_bytes": self.relay_bytes}
        try:
            from ray_tpu.util.metrics import merge_snapshots

            merged = merge_snapshots(self.metrics_store)
            for name, key in (
                ("bulk_plane_bytes_total", "bulk_bytes_by_path"),
                ("bulk_plane_pulls_total", "bulk_pulls_by_path"),
            ):
                m = merged.get(name)
                if m:
                    out[key] = {
                        (dict(tags).get("path", "") or "untagged"): v
                        for tags, v in m["values"].items()
                    }
            m = merged.get("bulk_plane_fallbacks_total")
            if m:
                out["bulk_fallbacks"] = sum(m["values"].values())
        except Exception:
            pass
        return out

    async def _h_debug_object(self, conn, msg):
        """Per-object directory introspection (ops/debugging)."""
        oid = msg["oid"]
        return {
            "present": self.objects.contains(oid),
            "refcount": self.objects.refcounts.get(oid, 0),
            "pins": self.objects.task_pins.get(oid, 0),
            "has_event": oid in self.objects.events,
            "lineage_task": self.object_lineage.get(oid),
        }

    # --- registration ---

    async def _h_register_driver(self, conn, msg):
        protocol.check_protocol_version(msg, "driver")
        self._driver_conn = conn
        return {"node_id": self._head_node_id, "job_config": self.job_config}

    async def _h_register_node(self, conn, msg):
        """A per-host agent joined over TCP (reference: raylet registration
        with GcsNodeManager). An agent whose previous connection is gone may
        RE-register under the same node id — the reconnect path after a head
        restart or a network blip (reference: raylet re-register against a
        restarted GCS, gcs_server.cc:130-178 init-from-stored-state)."""
        protocol.check_protocol_version(msg, f"node agent {msg.get('node_id')}")
        node_id = msg["node_id"]
        prev = self.nodes.get(node_id)
        if prev is not None and prev.alive and prev.conn is not None and not prev.conn.closed:
            raise ValueError(f"node id {node_id!r} already registered")
        self.nodes[node_id] = NodeRecord(
            node_id, dict(msg["resources"]), labels=msg.get("labels", {}), conn=conn,
            buffer_addr=msg.get("buffer_addr"),
        )
        # reconnect ordering is arbitrary: actors adopted BEFORE their node
        # re-registered must be charged against the fresh availability
        for rec in self.actors.values():
            if rec.state == "alive" and not rec.node_acquired:
                w = self.workers.get(rec.worker_id or "")
                if w is not None and w.node_id == node_id and w.state != "dead":
                    self._adopt_actor_resources(rec, node_id)
        self._prestart_workers(node_id)
        self._capacity_changed()
        return {"session": os.path.basename(self.session_dir),
                "session_dir": self.session_dir}

    def _prestart_workers(self, node_id: str):
        """Pre-warm the node's idle pool so first tasks skip the process
        cold start (interpreter spawn + register, ~0.5-2s). Reference:
        worker_pool.h:420 prestarts workers up to the soft limit."""
        n = cfg.worker_pool_prestart
        if n <= 0:
            return

        async def _one():
            w = await self._spawn_worker(node_id)
            try:
                await asyncio.wait_for(w.registered, cfg.worker_register_timeout_s)
            except asyncio.TimeoutError:
                await self._kill_worker(w, reason="prestart register timeout")
                return
            if w.state == "idle" and not self._shutdown:
                self.idle_workers[node_id].append(w.worker_id)
                # a worker joining adds EXECUTION slots, not node resource
                # capacity — the incremental probe suffices, and a bulk
                # requeue here would re-walk the whole parked backlog per
                # spawn (quadratic under worker churn)
                self._capacity_changed(bulk=False)

        async def _spawn_idle():
            # concurrent spawns: the pool warms in ONE cold-start interval,
            # and a hung worker doesn't serialize the rest
            await asyncio.gather(*(_one() for _ in range(n)), return_exceptions=True)

        # keep a strong reference (loop holds tasks weakly) and cancel at stop
        task = asyncio.get_running_loop().create_task(_spawn_idle())
        self._prestart_tasks.append(task)
        task.add_done_callback(lambda t: self._prestart_tasks.remove(t))

    async def _h_register_worker(self, conn, msg):
        protocol.check_protocol_version(msg, f"worker {msg.get('worker_id')}")
        w = self.workers.get(msg["worker_id"])
        if w is None:
            if not msg.get("adopt"):
                raise ValueError(f"unknown worker {msg['worker_id']}")
            # a SURVIVING worker re-registering after a head restart: the
            # process (and any actor state in it) is intact — re-adopt it
            # instead of forcing a cold respawn (reference: workers
            # re-register with a restarted GCS via the raylet)
            w = WorkerRecord(
                worker_id=msg["worker_id"],
                node_id=msg.get("node_id") or "",
                state="starting",
            )
            self.workers[w.worker_id] = w
        w.conn = conn
        w.direct_address = msg.get("direct_address")
        aid = msg.get("actor_id")
        if aid:
            w.state = "actor"
            w.actor_id = aid
            rec = self.actors.get(aid)
            if rec is not None and rec.state != "alive":
                # snapshot restore marked it dead; the live process proves
                # otherwise — revive the record so routes resolve again
                rec.state = "alive"
                rec.worker_id = w.worker_id
                rec.death_reason = None
                # a revived actor still OCCUPIES its node: without the
                # deduction the scheduler double-books the host
                self._adopt_actor_resources(rec, w.node_id)
        if w.state == "starting":
            w.state = "idle"
            if msg.get("adopt"):
                self.idle_workers[w.node_id].append(w.worker_id)
        if w.registered is not None and not w.registered.done():
            w.registered.set_result(None)
        # worker registration adds execution slots only (see prestart note):
        # incremental probe, not a bulk parked-backlog requeue
        self._capacity_changed(bulk=False)
        return {"node_id": w.node_id, "session_dir": self.session_dir}

    async def _h_get_actor_route(self, conn, msg):
        """Direct-transport route lookup: where does this actor live RIGHT
        NOW? Callers cache the answer and re-resolve on connection failure
        (actor restarts move it)."""
        rec = self.actors.get(msg["actor_id"])
        if rec is None:
            return None
        w = self.workers.get(rec.worker_id or "")
        return {
            "state": rec.state,
            "worker_id": rec.worker_id,
            "node_id": None if w is None else w.node_id,
            "address": None if w is None else w.direct_address,
            "death_reason": rec.death_reason,
        }

    # --- KV (GcsKVManager) ---

    async def _h_kv_put(self, conn, msg):
        ns = msg.get("ns", "")
        overwrite = msg.get("overwrite", True)
        table = self.kv[ns]
        if not overwrite and msg["key"] in table:
            return False
        table[msg["key"]] = msg["value"]
        return True

    async def _h_kv_get(self, conn, msg):
        return self.kv[msg.get("ns", "")].get(msg["key"])

    async def _h_kv_exists(self, conn, msg):
        return msg["key"] in self.kv[msg.get("ns", "")]

    async def _h_kv_del(self, conn, msg):
        return self.kv[msg.get("ns", "")].pop(msg["key"], None) is not None

    async def _h_kv_keys(self, conn, msg):
        prefix = msg.get("prefix", "")
        return [k for k in self.kv[msg.get("ns", "")] if k.startswith(prefix)]

    # --- objects ---

    def _on_object_freed(self, oid: str, _default=None):
        tid = self.object_lineage.pop(oid, None)
        if tid is not None and tid in self.tasks:
            # keep a bounded breadcrumb: a late getter revives the object
            # by re-running this task (stateless lineage only)
            self._freed_lineage[oid] = tid
            self._freed_lineage.move_to_end(oid)
            while len(self._freed_lineage) > 4096:
                self._freed_lineage.popitem(last=False)
        tid = self._stream_completion.pop(oid, None)
        if tid is not None:
            # the stream's terminal object died: release every yield's
            # baseline ref (consumers hold their own borrows)
            for child in self._stream_children.pop(tid, []):
                self.objects.remove_ref(child, 1)

    async def _h_put_object(self, conn, msg):
        oid = msg["object_id"]
        tid = msg.get("stream_of")
        if tid is not None:
            kids = self._stream_children.get(tid)
            if kids is None:
                # Late yield: it traveled on the worker's client conn while
                # the completion reply rode the head->worker request conn, so
                # the stream's terminal object was stored AND freed before
                # this put arrived. Registering it now would re-create
                # _stream_children for a dead stream and leak the baseline
                # ref forever. Store the envelope (a consumer may hold its
                # own borrow) but drop the baseline +1 immediately.
                self.objects.put(oid, msg["envelope"])
                self.objects.add_ref(oid, msg.get("initial_refs", 1))
                self.objects.remove_ref(oid, 1)
                return
            kids.append(oid)
        self.objects.put(oid, msg["envelope"])
        self.objects.add_ref(oid, msg.get("initial_refs", 1))
        # direct-transport results carry the caller's +1 here; if the caller
        # already dropped its ref (counter went negative), reconcile now
        self.objects._maybe_free(oid)

    async def _h_put_objects(self, conn, msg):
        """Batched put_object: direct-transport callers coalesce result
        forwards so the head pays one message per batch, not per call
        (reference: the task-event/object-report batching in
        core_worker/task_event_buffer.h)."""
        for oid, env in msg["objects"]:
            self.objects.put(oid, env)
            self.objects.add_ref(oid, 1)
            self.objects._maybe_free(oid)

    # ------------------------------------------------------------------
    # direct task transport: leases + post-hoc records
    # (reference: direct_task_transport.cc:588 lease-worker push, :191
    # lease reuse — the head grants a leased worker; the caller pushes
    # task specs straight to it and reuses the lease across tasks)
    # ------------------------------------------------------------------

    async def _h_request_task_lease(self, conn, msg):
        res = dict(msg.get("resources") or {"CPU": 1.0})
        nid = self._select_node(res, None)
        if nid is None:
            return None  # no capacity: caller queues via submit_task
        w = await self._lease_worker(
            nid, needs_tpu=res.get("TPU", 0) > 0,
            runtime_env=msg.get("runtime_env"),
        )
        if w is None or not w.direct_address:
            self._release_node(nid, res, None)
            if w is not None:  # un-dialable worker: back to the pool
                await self._return_leased_worker(w)
                self._capacity_changed(bulk=False)
            return None
        self._task_leases[w.worker_id] = {
            "conn": conn, "node_id": nid, "resources": res,
        }
        if not hasattr(conn, "_task_leases"):
            conn._task_leases = set()
        conn._task_leases.add(w.worker_id)
        return {
            "worker_id": w.worker_id, "address": w.direct_address,
            "node_id": w.node_id,
        }

    def _drop_task_lease(self, worker_id: str) -> None:
        """Release the lease's node resources + caller bookkeeping (the
        worker itself is settled separately — it may be dead)."""
        lease = self._task_leases.pop(worker_id, None)
        if lease is None:
            return
        s = getattr(lease["conn"], "_task_leases", None)
        if s is not None:
            s.discard(worker_id)
        self._release_node(lease["node_id"], lease["resources"], None)

    async def _return_leased_worker(self, w: WorkerRecord) -> None:
        if w.state != "busy":
            return
        if w.pooled:
            w.state = "idle"
            self.idle_workers[w.node_id].append(w.worker_id)
        else:
            await self._kill_worker(w, reason="direct lease done")

    async def _h_release_task_lease(self, conn, msg):
        wid = msg["worker_id"]
        self._drop_task_lease(wid)
        w = self.workers.get(wid)
        if w is not None:
            await self._return_leased_worker(w)
        # AFTER the lease drop, regardless of worker state: the node
        # capacity was freed by _drop_task_lease even when the worker died
        # mid-lease, and parked tasks that now fit must not wait for the
        # health valve
        self._capacity_changed(bulk=False)
        return True

    async def _h_record_tasks(self, conn, msg):
        """Post-hoc records for direct-pushed tasks: lineage (so evicted
        results reconstruct through the normal scheduler) + observability
        (state API / timeline). Best-effort and batched, like the
        reference's task event buffer (task_event_buffer.h ->
        gcs_task_manager.h:61)."""
        for r in msg["records"]:
            spec = r["spec"]
            rec = self.tasks.get(spec["task_id"])
            if rec is None:
                rec = TaskRecord(
                    spec=spec,
                    resources=spec.get("resources") or {"CPU": 1.0},
                )
                self.tasks[spec["task_id"]] = rec
            rec.node_id = r.get("node_id")
            rec.worker_id = r.get("worker_id")
            rec.retries_left = spec.get("max_retries", 0)
            rec.mark(r["state"])
            for oid in spec["return_ids"]:
                self.object_lineage[oid] = spec["task_id"]
        return True

    async def _h_get_objects(self, conn, msg):
        from ..exceptions import ObjectLostError

        ids: List[str] = msg["object_ids"]
        timeout = msg.get("timeout")
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for oid in ids:
            # freed before this get even arrived (e.g. a retransmitted
            # attempt landing after the refcount race resolved the wrong
            # way): recover up front — wait_available would park forever
            if not self.objects.contains(oid) and self.objects.freed_gen.get(oid):
                await self._recover_freed(oid)
            for attempt in range(2):
                remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                try:
                    await self.objects.wait_available(oid, remaining)
                    break
                except asyncio.TimeoutError:
                    from ..exceptions import GetTimeoutError

                    raise GetTimeoutError(
                        f"Get timed out after {timeout}s waiting for object {oid}"
                    ) from None
                except ObjectLostError:
                    # freed while we waited: the last existing ref dropped
                    # with this getter's borrow still in flight. Re-run the
                    # creator from lineage (or fail loudly) — never re-park.
                    if attempt > 0:
                        raise
                    await self._recover_freed(oid)
            out.append(self.objects.get(oid))
        return out

    async def _recover_freed(self, oid: str):
        """A getter raced the free of `oid`: revive it by re-running its
        creating task (lineage breadcrumb survives the free), or raise
        ObjectLostError so the caller gets a fast, loud, typed failure
        instead of an unbounded park. Recoveries count in
        protocol.PLANE_STATS['freed_object_recoveries']."""
        from ..exceptions import ObjectLostError

        if oid not in self.object_lineage:
            tid = self._freed_lineage.get(oid)
            if tid is None or tid not in self.tasks:
                logger.warning(
                    "get_objects hit freed object %s with no lineage to "
                    "re-run; surfacing ObjectLostError", oid,
                )
                raise ObjectLostError(oid)
            self.object_lineage[oid] = tid
        logger.warning(
            "get_objects hit freed object %s (refcount race: a borrow was "
            "in flight when the last ref dropped); re-running task %s from "
            "lineage", oid, self.object_lineage[oid],
        )
        await self._reconstruct(oid)
        protocol._stat("freed_object_recoveries")

    async def _wait_dep_available(self, oid: str):
        """wait_available with the freed-object recovery path: entry-time
        staleness (freed before this wait began) and mid-wait frees both
        route through lineage re-execution instead of parking forever."""
        from ..exceptions import ObjectLostError

        if not self.objects.contains(oid) and self.objects.freed_gen.get(oid):
            await self._recover_freed(oid)
        try:
            await self.objects.wait_available(oid)
        except ObjectLostError:
            await self._recover_freed(oid)
            await self.objects.wait_available(oid)

    async def _h_wait_objects(self, conn, msg):
        ids: List[str] = msg["object_ids"]
        num_returns = msg["num_returns"]
        timeout = msg.get("timeout")
        # at most num_returns ids come back ready (reference ray.wait
        # contract) — input order breaks ties among already-ready objects
        ready = [oid for oid in ids if self.objects.contains(oid)][:num_returns]
        if len(ready) < num_returns:
            pending = {
                asyncio.ensure_future(self.objects.wait_available(oid)): oid
                for oid in ids
                if not self.objects.contains(oid)
            }
            deadline = None if timeout is None else time.monotonic() + timeout
            try:
                while len(ready) < num_returns and pending:
                    remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                    if remaining is not None and remaining == 0.0:
                        break
                    done, _ = await asyncio.wait(
                        pending.keys(), timeout=remaining, return_when=asyncio.FIRST_COMPLETED
                    )
                    if not done:
                        break
                    for fut in done:
                        oid = pending.pop(fut)
                        # a waiter can now finish exceptionally (object
                        # freed mid-wait raises ObjectLostError): a lost
                        # object is NOT ready — report it as pending
                        if fut.exception() is None:
                            ready.append(oid)
            finally:
                for fut in pending:
                    fut.cancel()
        # a FIRST_COMPLETED batch can deliver several at once: re-cap
        ready_set = set(ready)
        ready_list = [oid for oid in ids if oid in ready_set][:num_returns]
        ready_set = set(ready_list)
        return ready_list, [oid for oid in ids if oid not in ready_set]

    # --- cross-language object exchange (JSON-codec clients, cpp/client/;
    # reference: the msgpack cross-language serialization the C++/Java
    # worker APIs use, cpp/src/ray/runtime) ---

    async def _h_xput_object(self, conn, msg):
        """Put from a non-Python client: "raw" = base64 bytes (stored as
        Python bytes), "json" = a JSON value. Stored as a normal envelope,
        so Python consumers just ray_tpu.get() it."""
        import base64

        from .serialization import serialize

        if msg.get("format") == "raw":
            value = base64.b64decode(msg["data"])
        else:
            value = msg.get("value")
        oid = msg["object_id"]
        self.objects.put(oid, serialize(value))
        self.objects.add_ref(oid, msg.get("initial_refs", 1))
        return oid

    async def _h_xget_objects(self, conn, msg):
        """Get for a non-Python client: values come back as JSON when they
        are JSON-representable, base64-tagged bytes otherwise."""
        import base64

        from .serialization import deserialize, materialize

        envs = await self._h_get_objects(conn, msg)
        out = []
        loop = asyncio.get_running_loop()
        for env in envs:
            # materialize OFF the loop: fetching cross-node buffers performs
            # a blocking round-trip back through this very event loop, so
            # doing it inline would deadlock the whole control plane
            def _load(env=env):
                e = materialize(env, self._shm_client())
                return e, deserialize(e)

            env, value = await loop.run_in_executor(None, _load)
            if getattr(env, "is_error", False):
                out.append({"format": "error", "error": repr(value)})
            elif isinstance(value, bytes):
                out.append({"format": "raw", "data": base64.b64encode(value).decode()})
            else:
                out.append({"format": "json", "value": value})
        return out

    # --- cross-language task execution (cpp/client Executor; reference:
    # cpp/src/ray/runtime task execution — the C++ worker registers named
    # functions and the runtime pushes calls to it) ---

    async def _h_register_cpp_executor(self, conn, msg):
        protocol.check_protocol_version(msg, f"cpp executor {msg.get('name')}")
        name = msg["name"]
        prev = self.cpp_executors.get(name)
        if prev is not None and not prev["conn"].closed:
            raise ValueError(f"cpp executor {name!r} already registered")
        conn._cpp_executor_name = name
        self.cpp_executors[name] = {
            "conn": conn,
            "functions": list(msg.get("functions") or []),
            "inflight": {},
            "next_call": 0,
        }
        return {"name": name}

    async def _h_list_cpp_executors(self, conn, msg):
        return {
            name: rec["functions"]
            for name, rec in self.cpp_executors.items()
            if not rec["conn"].closed
        }

    async def _h_cpp_call(self, conn, msg):
        """Python -> C++ call: push {fn, args} to the named executor; its
        cpp_result lands in the object directory under return_id, so the
        caller's ordinary get() resolves it."""
        rec = self.cpp_executors.get(msg["executor"])
        if rec is None or rec["conn"].closed:
            raise ValueError(f"no live cpp executor {msg['executor']!r}")
        return_id = msg["return_id"]
        rec["next_call"] += 1
        call_id = rec["next_call"]
        # register BEFORE the send: the await can yield to the read loop,
        # and an instant cpp_result must find its inflight entry — but
        # unwind on send failure (the closed flag lags the actual death),
        # or the +1 and entry would leak an error object nobody holds
        self.objects.add_ref(return_id, 1)
        rec["inflight"][call_id] = return_id
        try:
            await rec["conn"].send(
                {"t": "cpp_exec", "call_id": call_id, "fn": msg["fn"],
                 "args": msg.get("args") or []}
            )
        except Exception:
            rec["inflight"].pop(call_id, None)
            self.objects.remove_ref(return_id, 1)
            raise
        return return_id

    async def _h_cpp_result(self, conn, msg):
        from .serialization import serialize

        rec = self.cpp_executors.get(getattr(conn, "_cpp_executor_name", "") or "")
        if rec is None or rec["conn"] is not conn:
            return
        return_id = rec["inflight"].pop(msg["call_id"], None)
        if return_id is None:
            return
        # the caller may have dropped its ref while the call ran: the
        # refcount entry is gone, and storing now would leak the envelope
        # forever (no decrement will ever arrive)
        if return_id not in self.objects.refcounts:
            return
        if msg.get("ok"):
            env = serialize(msg.get("value"))
        else:
            from ..exceptions import CrossLanguageError

            env = serialize(CrossLanguageError(str(msg.get("error"))))
            env.is_error = True  # type: ignore[attr-defined]
        self.objects.put(return_id, env)

    def _drop_cpp_executor(self, conn) -> None:
        """Executor connection died: surface every in-flight call as an
        error object (callers are parked in get())."""
        from .serialization import serialize

        name = getattr(conn, "_cpp_executor_name", None)
        rec = self.cpp_executors.get(name or "")
        if rec is None or rec["conn"] is not conn:
            return
        del self.cpp_executors[name]
        if rec["inflight"]:
            from ..exceptions import CrossLanguageError

            env = serialize(
                CrossLanguageError(f"cpp executor {name!r} died mid-call")
            )
            env.is_error = True  # type: ignore[attr-defined]
            for return_id in rec["inflight"].values():
                if return_id in self.objects.refcounts:  # see _h_cpp_result
                    self.objects.put(return_id, env)
            rec["inflight"].clear()

    async def _h_add_refs(self, conn, msg):
        for oid, n in msg["counts"].items():
            self.objects.add_ref(oid, n)

    async def _h_remove_refs(self, conn, msg):
        for oid, n in msg["counts"].items():
            self.objects.remove_ref(oid, n)

    async def _h_free_objects(self, conn, msg):
        for oid in msg["object_ids"]:
            self.objects.refcounts[oid] = 0
            self.objects._maybe_free(oid)

    # --- tasks ---

    async def _h_submit_task(self, conn, msg):
        spec = msg["spec"]
        # the caller's +1 on each return id, folded into the submit message
        for oid in spec["return_ids"]:
            self.objects.add_ref(oid, 1)
            self.object_lineage[oid] = spec["task_id"]
        rec = TaskRecord(
            spec=spec,
            retries_left=spec.get("max_retries", 0),
            resources=spec.get("resources") or {"CPU": 1.0},
        )
        self.tasks[spec["task_id"]] = rec
        if spec.get("streaming"):
            self._stream_completion[spec["return_ids"][0]] = spec["task_id"]
            # pre-register the children list so a yield arriving AFTER the
            # completion object was freed (different conn, no FIFO guarantee)
            # is distinguishable from a live stream in _h_put_object
            self._stream_children.setdefault(spec["task_id"], [])
        for oid in spec.get("deps", []):
            self.objects.pin(oid)
        rec._resolve_task = self._spawn_bg(self._resolve_and_enqueue(rec))

    async def _resolve_and_enqueue(self, rec: TaskRecord):
        if rec.cancel_requested:
            # cancelled before this coroutine first ran, or re-entered via
            # the lost_deps re-dispatch path after a cancel: settle (the
            # _finish_cancel no-ops if the pending-branch already did)
            self._finish_cancel(rec)
            return
        rec.mark("waiting_deps")
        try:
            for oid in rec.spec.get("deps", []):
                await self._wait_dep_available(oid)
        except asyncio.CancelledError:
            return  # _finish_cancel cancelled us; returns already settled
        except Exception as e:
            # unrecoverable dep (freed with no lineage): settle the returns
            # with the typed error — parking here would strand every getter.
            # Dep pins stay held (a cancel racing this path may unpin via
            # _finish_cancel; double-unpinning could free live objects)
            rec.mark("failed")
            self._fail_task_returns(rec.spec, e)
            return
        if rec.cancel_requested:
            self._finish_cancel(rec)
            return
        rec.mark("pending")
        # known-blocked shape: park silently; the next capacity change
        # requeues everything (keeps a same-shape submit storm O(1) each)
        sig = rec._sig = self._demand_sig(rec)
        if sig in self._blocked_sigs:
            self._parked.setdefault(sig, collections.deque()).append(rec)
            return
        self.pending_queue.append(rec)
        self._pump()

    # --- lineage reconstruction (object_recovery_manager.h:41) ---

    async def _h_reconstruct_objects(self, conn, msg):
        """A consumer hit ObjectLostError (shm eviction / node death): re-run
        the creating tasks and wait until the objects exist again."""
        results = {}
        for oid in msg["object_ids"]:
            try:
                await self._reconstruct(oid)
                results[oid] = True
            except Exception:
                results[oid] = False
        return results

    async def _reconstruct(self, oid: str):
        fut = self._reconstructing.get(oid)
        if fut is not None:
            return await fut
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._reconstructing[oid] = fut
        try:
            tid = self.object_lineage.get(oid)
            if tid is None:
                # the free retired the live lineage entry; the bounded
                # breadcrumb (_on_object_freed) may still know the creator
                tid = self._freed_lineage.get(oid)
                if tid is not None:
                    self.object_lineage[oid] = tid
            rec = self.tasks.get(tid or "")
            if rec is None:
                from ..exceptions import ObjectLostError

                raise ObjectLostError(oid)
            # deps whose ENVELOPES are gone must be reconstructed first
            # (deps with stale buffers surface as lost_deps at execution
            # and loop back through here)
            for dep in rec.spec.get("deps", []):
                if not self.objects.contains(dep):
                    await self._reconstruct(dep)
            for rid in rec.spec["return_ids"]:
                self.objects.invalidate(rid)
            for dep in rec.spec.get("deps", []):
                self.objects.pin(dep)
            rec.retries_left = max(rec.retries_left, rec.spec.get("max_retries", 0))
            await self._resolve_and_enqueue(rec)
            await self.objects.wait_available(oid)
            fut.set_result(True)
        except Exception as e:
            fut.set_exception(e)
            raise
        finally:
            self._reconstructing.pop(oid, None)
            if not fut.done():
                # this task died mid-reconstruction (e.g. head shutdown);
                # concurrent waiters on the shared future must not hang —
                # set a real exception, NOT cancel(): CancelledError would
                # escape the waiters' `except Exception` handlers and
                # strand their clients without a reply
                from ..exceptions import ObjectLostError

                fut.set_exception(ObjectLostError(oid))
            if fut.done() and fut.exception() is not None:
                # the future may never be awaited by anyone else
                fut.exception()  # mark retrieved

    # --- actors ---

    async def _h_create_actor(self, conn, msg):
        spec = msg["spec"]
        aid = spec["actor_id"]
        rec = ActorRecord(
            actor_id=aid,
            spec=spec,
            name=spec.get("name"),
            restarts_left=spec.get("max_restarts", 0),
        )
        if rec.name:
            key = (spec.get("namespace", ""), rec.name)
            prev = self.actors.get(self.named_actors.get(key, ""))
            if prev is not None and prev.state != "dead":
                raise ValueError(f"Actor name {rec.name!r} already taken")
            # dead holders (incl. snapshot-restored metadata) are replaceable
            self.named_actors[key] = aid
        self.actors[aid] = rec
        for oid in spec.get("deps", []):
            self.objects.pin(oid)
        self._spawn_bg(self._start_actor(rec))

    async def _start_actor(self, rec: ActorRecord):
        if rec.state == "dead":
            return  # killed while queued for (re)start — stay dead
        rec.state = "starting"
        rec.node_acquired = False
        # a restart must not leave the PREVIOUS incarnation's worker id
        # visible: a concurrent kill would otherwise release resources
        # against the old worker's node
        rec.worker_id = None
        spec = rec.spec
        strategy = spec.get("scheduling_strategy")
        resources = dict(spec.get("resources") or {})

        def release_here():
            # release against the node id THIS start acquired (the kill
            # path can only release once worker_id is assigned; these two
            # are mutually exclusive via node_acquired)
            if rec.node_acquired:
                rec.node_acquired = False
                self._release_node(node_id, resources, strategy)

        for oid in spec.get("deps", []):
            await self._wait_dep_available(oid)
        node_id = await self._acquire_node(resources, strategy)
        if rec.state == "dead":
            # kill_actor landed during the waits above (worker not yet
            # assigned, so the kill path couldn't release this acquisition)
            self._release_node(node_id, resources, strategy)
            return
        rec.node_acquired = True  # stop counting as unmet autoscaler demand
        w = await self._spawn_worker(
            node_id,
            dedicated_actor_id=rec.actor_id,
            runtime_env=spec.get("runtime_env"),
            needs_tpu=resources.get("TPU", 0) > 0,
        )
        if rec.state == "dead":
            # killed during the spawn await, before worker_id was visible
            # to the kill path: release here and reap the fresh worker
            release_here()
            await self._kill_worker(w, reason="actor killed during start")
            return
        rec.worker_id = w.worker_id  # visible to the kill path from here on
        try:
            await asyncio.wait_for(w.registered, cfg.worker_register_timeout_s)
        except asyncio.TimeoutError:
            pass
        if rec.state == "dead":
            # killed mid-registration: _h_kill_actor saw worker_id and
            # released (node_acquired guard makes a second release a no-op)
            release_here()
            await self._kill_worker(w, reason="actor killed during start")
            return
        if w.state not in ("idle", "starting") or w.conn is None:
            rec.state = "dead"
            rec.death_reason = "worker failed to start"
            release_here()
            return
        w.state = "actor"
        try:
            await w.conn.request(
                {
                    "t": "start_actor",
                    "actor_id": rec.actor_id,
                    "cls_key": spec["cls_key"],
                    "args": self._resolve_args(spec),
                    "max_concurrency": spec.get("max_concurrency", 1),
                }
            )
        except Exception as e:  # init failed (or killed mid-init)
            if rec.state != "dead":
                rec.state = "dead"
                rec.death_reason = f"__init__ failed: {e!r}"
            self._release_actor_node(rec, w)
            await self._kill_worker(w, reason="actor init failed")
            await self._fail_backlog(rec)
            return
        if rec.state == "dead":  # killed while __init__ was running
            await self._kill_worker(w, reason="actor killed during start")
            return
        rec.state = "alive"
        backlog, rec.backlog = rec.backlog, []
        for call in backlog:
            self._spawn_bg(self._run_actor_task(rec, call))

    async def _h_submit_actor_task(self, conn, msg):
        spec = msg["spec"]
        for oid in spec["return_ids"]:
            self.objects.add_ref(oid, 1)
        rec = self.actors.get(spec["actor_id"])
        from ..exceptions import ActorDiedError

        if rec is None:
            # submits are fire-and-forget: surface the error through the
            # return objects, not the (absent) reply channel
            self._fail_task_returns(spec, ActorDiedError(spec["actor_id"], "unknown actor"))
            return
        for oid in spec.get("deps", []):
            self.objects.pin(oid)
        if rec.state == "dead":
            for oid in spec.get("deps", []):
                self.objects.unpin(oid)
            self._fail_task_returns(spec, ActorDiedError(rec.actor_id, rec.death_reason))
            return
        if rec.state in ("pending", "starting", "restarting"):
            rec.backlog.append(spec)
            return
        self._spawn_bg(self._run_actor_task(rec, spec))

    async def _run_actor_task(self, rec: ActorRecord, spec: dict):
        from ..exceptions import ActorDiedError

        if rec.send_lock is None:
            rec.send_lock = asyncio.Lock()
        async with rec.send_lock:
            for oid in spec.get("deps", []):
                await self._wait_dep_available(oid)
            w = self.workers.get(rec.worker_id or "")
            if w is None or w.conn is None or w.conn.closed:
                self._fail_task_returns(spec, ActorDiedError(rec.actor_id, "actor worker gone"))
                return
            # visible to cancel_task while the call is in flight (actor
            # calls have no TaskRecord; see _cancel_actor_call)
            self._actor_inflight[spec["task_id"]] = w.worker_id
            reply_fut = asyncio.ensure_future(
                w.conn.request(
                    {
                        "t": "run_task",
                        "task_id": spec["task_id"],
                        "actor_id": rec.actor_id,
                        "method": spec["method"],
                        "args": self._resolve_args(spec),
                        "return_ids": spec["return_ids"],
                        "trace_ctx": spec.get("trace_ctx"),
                    }
                )
            )
        try:
            reply = await reply_fut
            for _ in range(3):
                lost = reply.get("lost_deps")
                if not lost:
                    break
                # dep buffers evicted before the actor read them: the user
                # method never ran, so reconstruct + resend is side-effect
                # safe (same contract as the stateless-task path)
                for oid in lost:
                    await self._reconstruct(oid)
                w = self.workers.get(rec.worker_id or "")
                if w is None or w.conn is None or w.conn.closed:
                    raise ConnectionError("actor worker gone during reconstruction")
                reply = await w.conn.request(
                    {
                        "t": "run_task",
                        "task_id": spec["task_id"],
                        "actor_id": rec.actor_id,
                        "method": spec["method"],
                        "args": self._resolve_args(spec),
                        "return_ids": spec["return_ids"],
                        "trace_ctx": spec.get("trace_ctx"),
                    }
                )
            if "results" not in reply:
                raise RuntimeError(f"unrecoverable deps for {spec['task_id']}")
        except Exception as e:
            # Worker died mid-call (restart path handles backlog) or deps
            # were unrecoverable: fail the returns so consumers never hang.
            self._fail_task_returns(spec, ActorDiedError(rec.actor_id, repr(e)))
            return
        finally:
            self._actor_inflight.pop(spec["task_id"], None)
            for oid in spec.get("deps", []):
                self.objects.unpin(oid)
        self._store_task_results(spec, reply)

    async def _fail_backlog(self, rec: ActorRecord):
        from ..exceptions import ActorDiedError

        backlog, rec.backlog = rec.backlog, []
        for spec in backlog:
            self._fail_task_returns(spec, ActorDiedError(rec.actor_id, rec.death_reason))

    def _unregister_name(self, rec: ActorRecord):
        """Remove the name ONLY if it still maps to this actor — a dead
        holder's name may have been legitimately taken by a replacement
        (e.g. after a snapshot restore), and killing the stale record must
        not unregister the live one."""
        key = (rec.spec.get("namespace", ""), rec.name)
        if self.named_actors.get(key) == rec.actor_id:
            self.named_actors.pop(key, None)

    async def _h_get_named_actor(self, conn, msg):
        key = (msg.get("namespace", ""), msg["name"])
        aid = self.named_actors.get(key)
        if aid is None:
            raise ValueError(f"Failed to look up actor with name {msg['name']!r}")
        rec = self.actors[aid]
        return {"actor_id": aid, "spec_meta": {k: rec.spec.get(k) for k in ("cls_name", "method_names")}}

    async def _h_kill_actor(self, conn, msg):
        rec = self.actors.get(msg["actor_id"])
        if rec is None:
            return False
        rec.restarts_left = 0 if msg.get("no_restart", True) else rec.restarts_left
        rec.state = "dead"
        rec.death_reason = "killed via kill_actor"
        if rec.name:
            self._unregister_name(rec)
        w = self.workers.get(rec.worker_id or "")
        # release the actor's node resources NOW: state is already "dead",
        # so the worker-death path's release is skipped — without this the
        # resources leak and pending actors starve (deadlock under kill-
        # and-replace loops like Tune teardown / Serve scale-down)
        self._release_actor_node(rec, w)
        if w is not None:
            await self._kill_worker(w, reason="actor killed")
        await self._fail_backlog(rec)
        return True

    def _adopt_actor_resources(self, rec: ActorRecord, node_id: str) -> None:
        """Charge a re-adopted (head-restart survivor) actor against its
        node's availability — the inverse of _release_actor_node."""
        node = self.nodes.get(node_id)
        if node is None or rec.node_acquired:
            return
        _acquire(node.available, dict(rec.spec.get("resources") or {}))
        rec.node_acquired = True

    def _release_actor_node(self, rec: ActorRecord, w: Optional[WorkerRecord]):
        """Idempotently return an actor's acquired node resources
        (node_acquired guards double release across the kill and
        worker-death paths)."""
        if not rec.node_acquired or w is None:
            return
        rec.node_acquired = False
        self._release_node(
            w.node_id,
            dict(rec.spec.get("resources") or {}),
            rec.spec.get("scheduling_strategy"),
        )

    async def _h_actor_state(self, conn, msg):
        rec = self.actors.get(msg["actor_id"])
        return None if rec is None else rec.state

    # --- placement groups ---

    async def _h_create_placement_group(self, conn, msg):
        spec = msg["spec"]
        bundles = [BundleState(i, dict(b), available=dict(b)) for i, b in enumerate(spec["bundles"])]
        rec = PlacementGroupRecord(
            pg_id=spec["pg_id"],
            bundles=bundles,
            strategy=spec.get("strategy", "PACK"),
            name=spec.get("name"),
            ready_event=asyncio.Event(),
        )
        self.placement_groups[rec.pg_id] = rec
        self._spawn_bg(self._schedule_pg(rec))

    async def _schedule_pg(self, rec: PlacementGroupRecord):
        while rec.state == "pending" and not self._shutdown:
            if self._try_place_pg(rec):
                rec.state = "created"
                rec.ready_event.set()
                # tasks targeting this PG may have parked while it was
                # pending — their sigs become placeable exactly now
                self._capacity_changed(bulk=False)
                return
            await asyncio.sleep(0.05)

    def _try_place_pg(self, rec: PlacementGroupRecord) -> bool:
        """All-or-nothing bundle placement (bundle_scheduling_policy.cc analogue)."""
        nodes = [n for n in self.nodes.values() if n.alive]
        avail = {n.node_id: dict(n.available) for n in nodes}
        assignment: List[Tuple[BundleState, str]] = []
        strategy = rec.strategy

        def place(bundle, node_ids):
            for nid in node_ids:
                if _fits(avail[nid], bundle.resources):
                    _acquire(avail[nid], bundle.resources)
                    assignment.append((bundle, nid))
                    return True
            return False

        node_ids = [n.node_id for n in nodes]
        used_nodes: List[str] = []
        for b in rec.bundles:
            if strategy in ("PACK", "STRICT_PACK"):
                order = used_nodes + [n for n in node_ids if n not in used_nodes]
            elif strategy in ("SPREAD", "STRICT_SPREAD"):
                fresh = [n for n in node_ids if n not in used_nodes]
                order = fresh + (used_nodes if strategy == "SPREAD" else [])
            else:
                order = node_ids
            if not place(b, order):
                return False
            nid = assignment[-1][1]
            if nid not in used_nodes:
                used_nodes.append(nid)
        if strategy == "STRICT_PACK" and len({nid for _, nid in assignment}) > 1:
            return False
        if strategy == "STRICT_SPREAD" and len({nid for _, nid in assignment}) < len(rec.bundles):
            return False
        for b, nid in assignment:
            b.node_id = nid
            _acquire(self.nodes[nid].available, b.resources)
        return True

    async def _h_pg_ready(self, conn, msg):
        rec = self.placement_groups.get(msg["pg_id"])
        if rec is None:
            raise ValueError("unknown placement group")
        timeout = msg.get("timeout")
        # timeout=0 is a state POLL: wait_for(coro, 0) raises TimeoutError
        # before the fresh event.wait() coroutine can even observe a set
        # event, so check the flag directly first
        if rec.ready_event.is_set():
            return True
        if timeout == 0:
            return False
        try:
            await asyncio.wait_for(rec.ready_event.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def _h_remove_placement_group(self, conn, msg):
        rec = self.placement_groups.pop(msg["pg_id"], None)
        if rec is None:
            return False
        if rec.state == "created":
            for b in rec.bundles:
                if b.node_id:
                    # return only what the PG still holds
                    held = {k: v - (b.resources[k] - b.available.get(k, 0.0)) for k, v in b.resources.items()}
                    _release(self.nodes[b.node_id].available, held)
            # bundle resources returned to their nodes: parked tasks may fit
            self._capacity_changed(bulk=False)
        rec.state = "removed"
        return True

    async def _h_pg_table(self, conn, msg):
        out = {}
        for pid, rec in self.placement_groups.items():
            out[pid] = {
                "state": rec.state,
                "strategy": rec.strategy,
                "bundles": [
                    {"index": b.index, "resources": b.resources, "node_id": b.node_id}
                    for b in rec.bundles
                ],
            }
        return out

    # --- cluster info / nodes ---

    async def _h_add_node(self, conn, msg):
        node_id = msg["node_id"]
        self.nodes[node_id] = NodeRecord(node_id, dict(msg["resources"]), labels=msg.get("labels", {}))
        self._capacity_changed()
        return node_id

    async def _h_remove_node(self, conn, msg):
        rec = self.nodes.get(msg["node_id"])
        if rec is None:
            return False
        rec.alive = False
        for w in list(self.workers.values()):
            if w.node_id == rec.node_id:
                await self._kill_worker(w, reason="node removed")
        if rec.remote and not rec.conn.closed:
            try:
                await rec.conn.request({"t": "shutdown"}, timeout=2)
            except Exception:
                pass
            await rec.conn.close()
        return True

    async def _h_pending_demands(self, conn, msg):
        """Unfulfilled resource demands: queued tasks + unscheduled actors +
        pending placement-group bundles (reference: LoadMetrics fed to the
        autoscaler from GCS resource reports, autoscaler.py:172)."""
        demands: List[Dict[str, float]] = []
        for rec in self.pending_queue:
            demands.append(dict(rec.resources))
        for dq in self._parked.values():
            for rec in dq:
                demands.append(dict(rec.resources))
        for a in self.actors.values():
            if a.state in ("pending", "starting") and not a.node_acquired:
                res = dict(a.spec.get("resources") or {})
                if res:  # zero-resource actors place anywhere: no demand
                    demands.append(res)
        bundles = []
        for pg in self.placement_groups.values():
            if pg.state == "pending":
                bundles.append([dict(b.resources) for b in pg.bundles])
        return {"demands": demands, "pg_bundles": bundles}

    async def _h_cluster_resources(self, conn, msg):
        total: Dict[str, float] = collections.Counter()
        avail: Dict[str, float] = collections.Counter()
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.resources.items():
                    total[k] += v
                for k, v in n.available.items():
                    avail[k] += v
        return {"total": dict(total), "available": dict(avail)}

    async def _h_resource_report(self, conn, msg):
        """Fold an agent's periodic load report into the node table
        (reference: ray_syncer resource gossip landing in GCS)."""
        node = self.nodes.get(msg["node_id"])
        if node is not None:
            node.load_report = msg["report"]
            self._record_node_history(msg["node_id"], msg["report"])

    def _record_node_history(self, node_id: str, report: dict) -> None:
        """Bounded per-node time series feeding the dashboard's resource
        sparklines (reference: dashboard/modules/reporter metrics)."""
        hist = self.node_history.get(node_id)
        if hist is None:
            hist = self.node_history[node_id] = collections.deque(maxlen=150)
        hist.append(
            {
                "ts": report.get("ts", time.time()),
                "load_1m": report.get("load_1m"),
                "mem_frac": (
                    report.get("mem_used", 0) / report["mem_total"]
                    if report.get("mem_total")
                    else None
                ),
                "workers": report.get("workers"),
            }
        )

    async def _h_node_history(self, conn, msg):
        # the head node has no agent reporting for it: sample locally on
        # each poll (dashboard ticks ~2s — plenty for a sparkline)
        try:
            from .memory_monitor import MemoryMonitor

            used, total = MemoryMonitor().sample()
            self._record_node_history(
                self._head_node_id,
                {
                    "ts": time.time(),
                    "load_1m": os.getloadavg()[0],
                    "mem_used": used,
                    "mem_total": total,
                    "workers": sum(
                        1 for w in self.workers.values() if w.state != "dead"
                    ),
                },
            )
        except Exception:
            pass
        return {nid: list(h) for nid, h in self.node_history.items()}

    async def _h_nodes(self, conn, msg):
        return [
            {
                "node_id": n.node_id,
                "alive": n.alive,
                "resources": n.resources,
                "available": n.available,
                "labels": n.labels,
                "load_report": n.load_report,
            }
            for n in self.nodes.values()
        ]

    async def _h_list_actors(self, conn, msg):
        return [
            {
                "actor_id": a.actor_id,
                "state": a.state,
                "name": a.name,
                "class_name": a.spec.get("cls_name"),
                "worker_id": a.worker_id,
            }
            for a in self.actors.values()
        ]

    async def _h_ping(self, conn, msg):
        return "pong"

    async def _h_profile_worker(self, conn, msg):
        """On-demand profiling of a live worker (reference:
        dashboard/modules/reporter/profile_manager.py). Forwards the request
        to the worker's own sampler (worker_main._profile) and relays the
        collapsed-stack / allocation report back to the caller."""
        wid = msg.get("worker_id")
        w = self.workers.get(wid or "")
        if w is None or w.conn is None or w.conn.closed or w.state == "dead":
            raise ValueError(f"no live worker {wid!r}")
        duration = min(60.0, float(msg.get("duration_s", 2.0)))
        return await asyncio.wait_for(
            w.conn.request(
                {
                    "t": "profile",
                    "kind": msg.get("kind", "cpu"),
                    "duration_s": duration,
                    # floor keeps the sampler from busy-spinning the GIL
                    # inside the very worker it's observing
                    "interval_s": max(0.001, float(msg.get("interval_s", 0.01))),
                }
            ),
            timeout=duration + 30.0,
        )

    # ------------------------------------------------------------------
    # pubsub (reference: src/ray/pubsub — long-poll publisher/subscriber
    # for object-location/actor/node/log channels; serve's config push,
    # serve/_private/long_poll.py:68, is the same mechanism)
    # ------------------------------------------------------------------

    async def _h_publish(self, conn, msg):
        ch = msg["channel"]
        seq, _ = self.channels.get(ch, (0, None))
        seq += 1
        self.channels[ch] = (seq, msg["data"])
        # wake long-pollers (they loop and re-check the seq)
        ev = self._channel_events.pop(ch, None)
        if ev is not None:
            ev.set()
        # push to streaming subscribers (strong task refs: the loop holds
        # tasks weakly, and a dropped push would silently strand a
        # latest-snapshot subscriber on stale data)
        loop = asyncio.get_running_loop()
        for c in list(self.channel_subscribers.get(ch, ())):
            if c.closed:
                self.channel_subscribers[ch].discard(c)
                continue
            task = loop.create_task(
                self._push_one(c, {"t": "pub", "channel": ch, "seq": seq,
                                   "data": msg["data"]})
            )
            self._push_tasks.add(task)
            task.add_done_callback(self._push_tasks.discard)
        return seq

    @staticmethod
    async def _push_one(conn, msg):
        try:
            await conn.send(msg)
        except Exception:
            pass  # conn died mid-push; conn-close cleanup drops the sub

    async def _h_subscribe(self, conn, msg):
        ch = msg["channel"]
        self.channel_subscribers[ch].add(conn)
        if not hasattr(conn, "_subscribed_channels"):
            conn._subscribed_channels = set()
        conn._subscribed_channels.add(ch)
        seq, data = self.channels.get(ch, (0, None))
        return {"seq": seq, "data": data}

    async def _h_unsubscribe(self, conn, msg):
        ch = msg["channel"]
        subs = self.channel_subscribers.get(ch)
        if subs is not None:
            subs.discard(conn)
            if not subs:
                del self.channel_subscribers[ch]
        if hasattr(conn, "_subscribed_channels"):
            conn._subscribed_channels.discard(ch)
        return True

    async def _h_poll_channel(self, conn, msg):
        """Long-poll: return (seq, data) as soon as seq > last_seq, or
        {"timeout": True} after `timeout` seconds (client re-polls)."""
        ch = msg["channel"]
        last = msg.get("last_seq", 0)
        timeout = msg.get("timeout", 30.0)
        deadline = time.monotonic() + timeout
        while True:
            seq, data = self.channels.get(ch, (0, None))
            if seq > last:
                return {"seq": seq, "data": data}
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {"seq": last, "timeout": True}
            ev = self._channel_events.setdefault(ch, asyncio.Event())
            self._channel_waiters[ch] = self._channel_waiters.get(ch, 0) + 1
            try:
                # no shield: cancelling Event.wait() is side-effect free, and
                # shielding would leak one pending waiter per poll timeout
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                return {"seq": last, "timeout": True}
            finally:
                # last waiter out drops the Event — churning channel names
                # that time out without a publish must not grow head memory
                n = self._channel_waiters.get(ch, 1) - 1
                if n <= 0:
                    self._channel_waiters.pop(ch, None)
                    if self._channel_events.get(ch) is ev and not ev.is_set():
                        self._channel_events.pop(ch, None)
                else:
                    self._channel_waiters[ch] = n

    # ------------------------------------------------------------------
    # state API + observability (reference: dashboard/state_aggregator.py,
    # experimental/state/api.py; task events: gcs_task_manager.h:61)
    # ------------------------------------------------------------------

    async def _h_cancel_task(self, conn, msg):
        """Cancel a task (reference: python/ray/_private/worker.py
        ray.cancel -> CoreWorker::CancelTask). Queued tasks are dropped and
        their returns resolve to TaskCancelledError; running tasks get the
        cancellation raised asynchronously in the executing worker thread;
        force=True kills the worker process instead. Returns True when the
        cancel took effect (False: unknown/already finished)."""
        tid = msg["task_id"]
        rec = self.tasks.get(tid)
        if rec is None:
            return await self._cancel_actor_call(tid, msg.get("force", False))
        if rec.state in ("done", "failed", "cancelled"):
            return False
        rec.cancel_requested = True
        if rec.state in ("pending", "waiting_deps"):
            # sits in pending_queue/_parked (or a dep/retry wait): finish
            # now, the queues drop the record lazily when they pop it
            self._finish_cancel(rec)
            return True
        if rec.state == "scheduled":
            return True  # _dispatch_task checks the flag before pushing
        # running
        w = self.workers.get(rec.worker_id or "")
        if w is not None and w.state != "dead":
            if msg.get("force"):
                # the 'running' state may be a LAGGED batched record for a
                # direct-pushed task that already finished — ask the worker
                # whether it is actually executing this task before killing
                # it (the probe itself async-cancels when it is)
                running = "executing"
                if w.conn is not None and not w.conn.closed:
                    try:
                        running = await w.conn.request(
                            {"t": "cancel_task", "task_id": tid}, timeout=5
                        )
                    except Exception:
                        running = "executing"  # conn broken: the kill is moot/safe
                if not running:
                    return False
                if running == "queued":
                    # dispatched but never started: the worker flagged it
                    # for drop-before-run — cancel took effect; killing the
                    # worker would only murder whatever OTHER task is on
                    # its executor thread
                    return True
                await self._kill_worker(w, reason=f"task {tid} force-cancelled")
            elif w.conn is not None and not w.conn.closed:
                try:
                    await w.conn.send({"t": "cancel_task", "task_id": tid})
                except Exception:
                    pass
        return True

    async def _cancel_actor_call(self, tid: str, force: bool) -> bool:
        """Cancel a head-routed actor method call — these have no
        TaskRecord. Backlogged (actor still starting/restarting): drop the
        spec and settle its returns. In flight on the actor's worker:
        forward so the worker raises in the executing thread. force is
        deliberately ignored for actor calls (killing the worker would
        destroy actor state; reference rejects force on actor tasks)."""
        from ..exceptions import TaskCancelledError

        for a in self.actors.values():
            for spec in a.backlog:
                if spec["task_id"] == tid:
                    a.backlog.remove(spec)
                    for oid in spec.get("deps", []):
                        self.objects.unpin(oid)
                    self._fail_task_returns(
                        spec, TaskCancelledError(f"task {tid} was cancelled")
                    )
                    return True
        wid = self._actor_inflight.get(tid)
        if wid:
            w = self.workers.get(wid)
            if w is not None and w.state != "dead" and w.conn is not None:
                try:
                    await w.conn.send({"t": "cancel_task", "task_id": tid})
                except Exception:
                    pass
                return True
        return False

    def _finish_cancel(self, rec: TaskRecord):
        from ..exceptions import TaskCancelledError

        if rec.state == "cancelled":
            return  # idempotent: racing paths must not double-unpin deps
        rec.mark("cancelled")
        for oid in rec.spec.get("deps", []):
            self.objects.unpin(oid)
        self._fail_task_returns(
            rec.spec,
            TaskCancelledError(f"task {rec.spec.get('task_id')} was cancelled"),
        )
        t = getattr(rec, "_resolve_task", None)
        if t is not None and t is not asyncio.current_task():
            # a dep-waiting coroutine would otherwise park on
            # wait_available forever if the dep never materializes
            t.cancel()

    async def _h_task_count(self, conn, msg):
        # O(1) backlog probe: stress monitors must not pay the O(n) pickle
        # of list_tasks just to watch a 100k-task queue fill
        return len(self.tasks)

    async def _h_list_tasks(self, conn, msg):
        # limit=0 means "all" (client-side filters need the full set)
        limit = msg.get("limit", 1000)
        items = list(self.tasks.items())
        if limit:
            items = items[-limit:]
        out = []
        for tid, t in items:
            out.append(
                {
                    "task_id": tid,
                    "name": t.spec.get("name") or t.spec.get("fn_key", ""),
                    "state": t.state,
                    "node_id": t.node_id,
                    "worker_id": t.worker_id,
                    "events": list(t.events),
                    "retries_left": t.retries_left,
                }
            )
        return out

    async def _h_list_objects(self, conn, msg):
        limit = msg.get("limit", 1000)  # 0 = all
        out = []
        from .serialization import shm_buffer_names

        items = list(self.objects.objects.items())
        if limit:
            items = items[:limit]
        for oid, env in items:
            try:
                size = env.total_bytes()
            except Exception:
                size = 0
            try:
                in_shm = bool(shm_buffer_names(env))
            except Exception:
                in_shm = False
            out.append(
                {
                    "object_id": oid,
                    "size_bytes": size,
                    "refcount": int(self.objects.refcounts.get(oid, 0)),
                    "pins": int(self.objects.task_pins.get(oid, 0)),
                    "is_error": bool(getattr(env, "is_error", False)),
                    "in_shm": in_shm,
                }
            )
        return out

    async def _h_list_workers(self, conn, msg):
        return [
            {
                "worker_id": w.worker_id,
                "node_id": w.node_id,
                "state": w.state,
                "actor_id": w.actor_id,
                "pid": w.proc.pid if w.proc else None,
            }
            for w in self.workers.values()
        ]

    async def _h_timeline(self, conn, msg):
        """Chrome-tracing events (reference: python/ray/_private/profiling.py
        `ray timeline`): one complete event per task run + instant events
        for failures."""
        events = []
        for tid, t in self.tasks.items():
            times = dict(t.events)
            start = times.get("running")
            if start is None:
                continue
            end = times.get("done") or times.get("failed") or time.time()
            events.append(
                {
                    "name": t.spec.get("name") or t.spec.get("fn_key", "task"),
                    "cat": "task",
                    "ph": "X",
                    "ts": start * 1e6,
                    "dur": (end - start) * 1e6,
                    "pid": t.node_id or "?",
                    "tid": t.worker_id or "?",
                    "args": {"task_id": tid, "state": t.state},
                }
            )
        return events

    # ------------------------------------------------------------------
    # job submission (reference: dashboard/modules/job/job_manager.py —
    # JobSupervisor subprocess per submission; collapsed onto the head)
    # ------------------------------------------------------------------

    async def _h_submit_job(self, conn, msg):
        import uuid as _uuid

        sid = msg.get("submission_id") or f"raysubmit_{_uuid.uuid4().hex[:16]}"
        if sid in self.jobs:
            raise ValueError(f"submission_id {sid!r} already exists")
        runtime_env = msg.get("runtime_env") or {}
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"job-{sid}.log")
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self.socket_path
        env["RAY_TPU_SUBMISSION_ID"] = sid
        if runtime_env:
            # the job's runtime_env (env_vars included) is the DEFAULT for
            # every task/actor the job driver submits (reference: job-level
            # runtime_env semantics)
            import json as _json

            env["RAY_TPU_JOB_RUNTIME_ENV"] = _json.dumps(dict(runtime_env))
        for k, v in (runtime_env.get("env_vars") or {}).items():
            env[k] = str(v)
        # the job runs a fresh interpreter: the cluster's code (this package)
        # must stay importable, MERGED with any user-supplied PYTHONPATH
        from .spawn import child_pythonpath, framework_root

        # framework root FIRST (a stale vendored ray_tpu must not shadow
        # the cluster's), then the user's PYTHONPATH with its normal
        # precedence over site-packages, then this process's sys.path
        env["PYTHONPATH"] = child_pythonpath(
            [framework_root()], inherited=env.get("PYTHONPATH")
        )
        cwd = os.getcwd()
        loop = asyncio.get_running_loop()
        if runtime_env.get("working_dir"):
            cwd = await loop.run_in_executor(
                None, self._stage_dir, runtime_env["working_dir"]
            )
            env["PYTHONPATH"] = cwd + os.pathsep + env["PYTHONPATH"]
        for mod in runtime_env.get("py_modules") or []:
            staged = await loop.run_in_executor(None, self._stage_dir, mod)
            mod_path = staged if os.path.isdir(staged) else os.path.dirname(staged)
            env["PYTHONPATH"] = mod_path + os.pathsep + env["PYTHONPATH"]
        logf = open(log_path, "ab")
        # own session/process group: stop_job must reach grandchildren of the
        # shell (compound entrypoints), not just /bin/sh
        proc = subprocess.Popen(
            msg["entrypoint"],
            shell=True,
            env=env,
            cwd=cwd,
            stdout=logf,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        logf.close()
        self.jobs[sid] = {
            "submission_id": sid,
            "entrypoint": msg["entrypoint"],
            "status": "RUNNING",
            "proc": proc,
            "log_path": log_path,
            "start_time": time.time(),
            "end_time": None,
            "metadata": msg.get("metadata") or {},
        }
        self._spawn_bg(self._watch_job(sid))
        return sid

    async def _watch_job(self, sid: str):
        job = self.jobs[sid]
        code = await asyncio.get_running_loop().run_in_executor(None, job["proc"].wait)
        if job["status"] == "STOPPED":
            pass  # stop_job already settled it
        else:
            job["status"] = "SUCCEEDED" if code == 0 else "FAILED"
        job["end_time"] = time.time()
        job["exit_code"] = code

    def _job_view(self, job: dict) -> dict:
        return {k: v for k, v in job.items() if k != "proc"}

    async def _h_job_status(self, conn, msg):
        job = self.jobs.get(msg["submission_id"])
        if job is None:
            raise ValueError(f"no such job {msg['submission_id']!r}")
        return job["status"]

    async def _h_job_info(self, conn, msg):
        job = self.jobs.get(msg["submission_id"])
        if job is None:
            raise ValueError(f"no such job {msg['submission_id']!r}")
        return self._job_view(job)

    async def _h_list_jobs(self, conn, msg):
        return [self._job_view(j) for j in self.jobs.values()]

    async def _h_job_logs(self, conn, msg):
        job = self.jobs.get(msg["submission_id"])
        if job is None:
            raise ValueError(f"no such job {msg['submission_id']!r}")
        try:
            with open(job["log_path"], "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    async def _h_stop_job(self, conn, msg):
        job = self.jobs.get(msg["submission_id"])
        if job is None:
            raise ValueError(f"no such job {msg['submission_id']!r}")
        if job["status"] == "RUNNING":
            job["status"] = "STOPPED"
            self._terminate_job_proc(job["proc"])
            self._spawn_bg(self._escalate_kill(job["proc"]))
        return True

    # ------------------------------------------------------------------
    # head:// storage plane (reference: the role object storage / a redis-
    # backed GCS plays for air checkpoints — here a chunked tar transfer
    # onto the head host's stable storage dir; train/storage.py is the
    # client). Keys are sanitized relative paths; payloads stream in
    # bounded chunks so a multi-GB checkpoint never lands in one message.
    # ------------------------------------------------------------------

    def _stor_path(self, key: str) -> str:
        root = os.path.abspath(cfg.head_storage_dir)
        norm = os.path.normpath(key)
        if norm.startswith("..") or os.path.isabs(norm) or not norm or norm == ".":
            raise ValueError(f"bad storage key {key!r}")
        return os.path.join(root, norm + ".tar")

    _STOR_UPLOAD_IDLE_S = 3600.0  # reap sessions abandoned by dead clients
    _STOR_REAP_PERIOD_S = 300.0

    def _stor_reap_sessions(self):
        """Close + delete upload/read sessions idle past the reap window,
        and sweep orphaned .up-* tmp files (e.g. from a previous head
        crash). Lazy + rate-limited from stor_begin; the filesystem walk
        runs in an executor so the control loop never blocks on it."""
        now = time.time()
        if now - getattr(self, "_stor_last_reap", 0.0) < self._STOR_REAP_PERIOD_S:
            return
        self._stor_last_reap = now
        for token, (f, tmp, _path, last) in list(
            getattr(self, "_stor_uploads", {}).items()
        ):
            if now - last > self._STOR_UPLOAD_IDLE_S:
                del self._stor_uploads[token]
                f.close()
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        for token, (f, last) in list(getattr(self, "_stor_reads", {}).items()):
            if now - last > self._STOR_UPLOAD_IDLE_S:
                del self._stor_reads[token]
                f.close()
        live_tmp = {t[1] for t in getattr(self, "_stor_uploads", {}).values()}
        root = os.path.abspath(cfg.head_storage_dir)

        def _sweep():
            for dirpath, _dirs, files in os.walk(root):
                for name in files:
                    p = os.path.join(dirpath, name)
                    if ".up-" in name and p not in live_tmp:
                        try:
                            if now - os.path.getmtime(p) > self._STOR_UPLOAD_IDLE_S:
                                os.remove(p)
                        except OSError:
                            pass

        self._spawn_bg(asyncio.to_thread(_sweep))

    async def _h_stor_begin(self, conn, msg):
        import uuid as _uuid

        path = self._stor_path(msg["key"])  # validates the key up front
        if not hasattr(self, "_stor_uploads"):
            self._stor_uploads = {}
        self._stor_reap_sessions()
        token = _uuid.uuid4().hex
        tmp = f"{path}.up-{token}"
        os.makedirs(os.path.dirname(tmp), exist_ok=True)
        self._stor_uploads[token] = (open(tmp, "wb"), tmp, path, time.time())
        return token

    async def _h_stor_chunk(self, conn, msg):
        f, tmp, path, _last = self._stor_uploads[msg["token"]]
        self._stor_uploads[msg["token"]] = (f, tmp, path, time.time())
        await asyncio.get_running_loop().run_in_executor(None, f.write, msg["data"])
        return True

    async def _h_stor_end(self, conn, msg):
        f, tmp, path, _last = self._stor_uploads.pop(msg["token"])
        f.close()
        os.replace(tmp, path)
        return True

    async def _h_stor_size(self, conn, msg):
        try:
            return os.path.getsize(self._stor_path(msg["key"]))
        except FileNotFoundError:
            return None

    async def _h_stor_open(self, conn, msg):
        """Open a read session: the held fd pins ONE version of the object
        (os.replace swaps the directory entry, not the open inode), so a
        download that races a concurrent overwrite still sees a consistent
        snapshot instead of interleaved bytes. Returns (token, size) or
        None when absent."""
        import uuid as _uuid

        path = self._stor_path(msg["key"])
        if not hasattr(self, "_stor_reads"):
            self._stor_reads = {}
        self._stor_reap_sessions()  # download-heavy workloads reap too
        try:
            f = open(path, "rb")
        except FileNotFoundError:
            return None
        token = _uuid.uuid4().hex
        f.seek(0, os.SEEK_END)
        size = f.tell()
        self._stor_reads[token] = (f, time.time())
        return token, size

    async def _h_stor_read(self, conn, msg):
        f, _last = self._stor_reads[msg["token"]]
        self._stor_reads[msg["token"]] = (f, time.time())
        offset, size = msg["offset"], msg["size"]

        def _read():
            f.seek(offset)
            return f.read(size)

        return await asyncio.get_running_loop().run_in_executor(None, _read)

    async def _h_stor_close(self, conn, msg):
        entry = self._stor_reads.pop(msg["token"], None)
        if entry is not None:
            entry[0].close()
        return True

    async def _h_stor_del(self, conn, msg):
        path = self._stor_path(msg["key"])

        def _del():
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
            # a key may also be a PREFIX of per-file keys (workflow sync
            # lays out <wf>/meta.json, <wf>/steps/... as individual objects)
            shutil.rmtree(path[: -len(".tar")], ignore_errors=True)

        # off-loop: deleting a multi-GB prefix must not stall the control
        # plane (reference: GCS store ops never run on the main loop)
        await asyncio.get_running_loop().run_in_executor(None, _del)
        return True

    async def _h_stor_list(self, conn, msg):
        root = os.path.abspath(cfg.head_storage_dir)
        norm = os.path.normpath(msg["prefix"])
        if norm.startswith("..") or os.path.isabs(norm) or not norm or norm == ".":
            raise ValueError(f"bad storage prefix {msg['prefix']!r}")
        prefix = os.path.join(root, norm)
        if not os.path.isdir(prefix):
            return []
        out = []
        for name in sorted(os.listdir(prefix)):
            if name.endswith(".tar") and ".up-" not in name:
                out.append(name[: -len(".tar")])
            elif os.path.isdir(os.path.join(prefix, name)):
                out.append(name)
        return out

    async def _h_report_data_stats(self, conn, msg):
        """Driver-reported Dataset execution stats (reference: the data
        module's StatsActor feeding the dashboard's DataHead). Bounded ring:
        the dashboard shows recent executions, not history."""
        if not hasattr(self, "_data_stats"):
            from collections import deque

            self._data_stats = deque(maxlen=50)
        self._data_stats.append(msg["stats"])
        return True

    async def _h_data_stats(self, conn, msg):
        return list(getattr(self, "_data_stats", ()))

    async def _h_get_package(self, conn, msg):
        """Serve an uploaded working-dir package's bytes to a node agent so
        pkg:// runtime envs stage on remote nodes too (reference:
        runtime_env_agent downloading from GCS object storage —
        _private/runtime_env/packaging.py download_and_unpack_package)."""
        name = msg["name"]
        if "/" in name or ".." in name or not name:
            raise ValueError(f"bad package name {name!r}")
        path = os.path.join(self.session_dir, "packages", name)
        loop = asyncio.get_running_loop()

        def _read():
            with open(path, "rb") as f:
                return f.read()

        try:
            return await loop.run_in_executor(None, _read)
        except FileNotFoundError:
            raise ValueError(f"no such uploaded package {name!r}") from None

    async def _h_delete_job(self, conn, msg):
        """Remove a TERMINAL job's record (reference: job_head.py DELETE
        /api/jobs/{id} — running jobs must be stopped first)."""
        job = self.jobs.get(msg["submission_id"])
        if job is None:
            raise ValueError(f"no such job {msg['submission_id']!r}")
        if job["status"] in ("PENDING", "RUNNING"):
            raise ValueError(
                f"job {msg['submission_id']!r} is {job['status']}; stop it first"
            )
        del self.jobs[msg["submission_id"]]
        return True

    async def _escalate_kill(self, proc, grace_s: float = 3.0):
        """SIGTERM then, if the group ignores it, SIGKILL (reference:
        JobSupervisor stop escalation)."""
        import signal

        await asyncio.sleep(grace_s)
        if proc.poll() is None:
            self._terminate_job_proc(proc, sig=signal.SIGKILL)

    @staticmethod
    def _terminate_job_proc(proc, sig=None):
        import signal

        sig = sig if sig is not None else signal.SIGTERM
        try:  # whole process group (start_new_session at spawn)
            os.killpg(os.getpgid(proc.pid), sig)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                proc.send_signal(sig)
            except Exception:
                pass

    async def _h_push_metrics(self, conn, msg):
        # snapshots merged per (process, metric); aggregation happens at read
        if conn.closed:
            return  # connection already torn down: don't resurrect pruned state
        if not hasattr(conn, "_metric_procs"):
            conn._metric_procs = set()
        conn._metric_procs.add(msg["proc"])
        self.metrics_store[msg["proc"]] = {"ts": time.time(), "metrics": msg["metrics"]}

    async def _h_get_metrics(self, conn, msg):
        return dict(self.metrics_store)

    async def _h_push_serve_events(self, conn, msg):
        # pushes are DELTAS (events past the proc's last pushed seq, see
        # serve/telemetry.py flush_events): append by seq, bounded per
        # proc — the head's window can outlive the pusher's local ring
        prev = self.serve_events_store.get(msg["proc"])
        events = msg.get("events", [])
        if prev is not None and events:
            last = prev["events"][-1].get("seq", 0) if prev["events"] else 0
            fresh = [e for e in events if e.get("seq", 0) > last]
            if fresh:
                merged = prev["events"] + fresh
            else:
                # seq RESTARTED under a reused proc key (pid reuse, or a
                # rebuilt recorder): a non-empty batch entirely at-or-
                # below the stored seq is a new generation — replace, or
                # the new process's recorder would never reach the head
                merged = list(events)
        else:
            merged = list(events) if events else (
                prev["events"] if prev is not None else []
            )
        self.serve_events_store[msg["proc"]] = {
            "ts": time.time(),
            "events": merged[-8192:],
            "dropped": msg.get("dropped", 0),
        }
        # proc-count bound: prefer evicting entries stale for a while
        # (their post-mortem window has had time to be read); a crashed
        # replica's FINAL snapshot must not be the first thing churn
        # evicts, so fresh-but-silent entries go only when nothing stale
        # remains
        while len(self.serve_events_store) > 256:
            now = time.time()
            stale = [p for p, v in self.serve_events_store.items()
                     if now - v["ts"] > 900.0]
            pool = stale or list(self.serve_events_store)
            oldest = min(pool,
                         key=lambda p: self.serve_events_store[p]["ts"])
            del self.serve_events_store[oldest]

    async def _h_get_serve_events(self, conn, msg):
        return dict(self.serve_events_store)

    # ------------------------------------------------------------------
    # scheduling + worker pool
    # ------------------------------------------------------------------

    def _select_node(self, resources: Dict[str, float], strategy) -> Optional[str]:
        """Hybrid policy (hybrid_scheduling_policy.h:50): prefer the head/local
        node below the utilization threshold, else least-utilized feasible."""
        if isinstance(strategy, dict) and strategy.get("type") == "placement_group":
            pg = self.placement_groups.get(strategy["pg_id"])
            if pg is None or pg.state != "created":
                return None
            idx = strategy.get("bundle_index", -1)
            bundles = pg.bundles if idx == -1 else [pg.bundles[idx]]
            for b in bundles:
                if _fits(b.available, resources):
                    _acquire(b.available, resources)
                    return b.node_id
            return None
        if isinstance(strategy, dict) and strategy.get("type") == "node_affinity":
            n = self.nodes.get(strategy["node_id"])
            if n is not None and n.alive and _fits(n.available, resources):
                _acquire(n.available, resources)
                return n.node_id
            if strategy.get("soft"):
                pass  # fall through to hybrid
            else:
                return None
        candidates = []
        for n in self.nodes.values():
            if n.alive and _fits(n.available, resources):
                used = sum(
                    1 - (n.available.get(k, 0) / v) for k, v in n.resources.items() if v
                ) / max(1, len(n.resources))
                candidates.append((used, n.node_id != self._head_node_id, n.node_id))
        if not candidates:
            return None
        if strategy == "SPREAD":
            candidates.sort(key=lambda c: c[0])
        else:
            head = [c for c in candidates if not c[1] and c[0] < cfg.scheduler_spread_threshold]
            if head:
                candidates = head
            else:
                candidates.sort(key=lambda c: c[0])
        nid = candidates[0][2]
        _acquire(self.nodes[nid].available, resources)
        return nid

    async def _acquire_node(self, resources: Dict[str, float], strategy=None) -> str:
        while True:
            nid = self._select_node(resources, strategy)
            if nid is not None:
                return nid
            await asyncio.sleep(0.02)

    def _release_node(self, node_id: str, resources: Dict[str, float], strategy=None):
        if isinstance(strategy, dict) and strategy.get("type") == "placement_group":
            pg = self.placement_groups.get(strategy["pg_id"])
            if pg is not None and pg.state == "created":
                idx = strategy.get("bundle_index", -1)
                bundles = pg.bundles if idx == -1 else [pg.bundles[idx]]
                for b in bundles:
                    if b.node_id == node_id:
                        _release(b.available, resources)
                        return
            return
        n = self.nodes.get(node_id)
        if n is not None:
            _release(n.available, resources)

    @staticmethod
    def _demand_sig(rec: TaskRecord):
        strategy = rec.spec.get("scheduling_strategy")
        return (
            tuple(sorted(rec.resources.items())),
            strategy if isinstance(strategy, str) else repr(strategy),
        )

    def _capacity_changed(self, bulk: bool = True):
        """Cluster capacity moved: previously-unplaceable demand shapes may
        fit now. Two regimes, because requeue cost must match the size of
        the capacity event, or a 100k-task parked backlog melts the head:

        - bulk=True (node joined/registered ONLY — the sites that add node
          resource capacity): rare, arbitrarily large capacity — requeue
          EVERYTHING and re-pump.
        - bulk=False (everything else: lease released, worker registered/
          died, PG created/removed, safety valve): probe each parked
          shape's HEAD and keep promoting until the probe misses —
          O(#shapes + #promoted) per event, never O(parked tasks).

        Submit paths must NOT call this; they call _pump() (or park
        directly when their shape is known-blocked)."""
        if bulk:
            if self._parked:
                for dq in self._parked.values():
                    self.pending_queue.extend(dq)
                self._parked.clear()
            self._blocked_sigs.clear()
            self._pump()
            return
        for sig in list(self._parked):
            dq = self._parked[sig]
            promoted_any = False
            # keep promoting this shape until the probe misses: a freed
            # lease can be bigger than one task (e.g. {CPU: 4} released
            # over 1-CPU parked tasks) and under-promoting serializes the
            # node until the next capacity event
            while dq:
                head = dq[0]
                if head.state == "cancelled":
                    dq.popleft()  # cancelled while parked: drop lazily
                    continue
                # _select_node ACQUIRES capacity on success — dispatch the
                # head directly on the returned node rather than requeueing
                # it for _pump (which would acquire a second time and leak
                # the probe's acquisition, wedging the node as full)
                nid = self._select_node(head.resources, head.spec.get("scheduling_strategy"))
                if nid is None:
                    break
                dq.popleft()
                promoted_any = True
                self._dispatch_on(head, nid)
            if not dq:
                # deque gone (promoted out, or emptied purely by dropping
                # cancelled records): the sig MUST unblock too, else new
                # same-shape submits keep parking despite free capacity and
                # only recover at the next health-valve tick
                del self._parked[sig]
                self._blocked_sigs.discard(sig)
            if promoted_any:
                # unblock so new same-shape submits pump normally; a
                # placement miss simply re-blocks. Whatever stays parked
                # does so because the probe just missed — only as much
                # work unparks as capacity arrived
                self._blocked_sigs.discard(sig)
        if self.pending_queue:
            self._pump()

    def _pump(self):
        if self._shutdown:
            return
        # demand signatures that already failed: with thousands of queued
        # same-shape tasks, one placement miss proves the rest can't place
        # either. Blocked shapes PARK out of the queue until
        # _capacity_changed requeues them, so both a same-shape submit
        # storm AND later unrelated submits cost O(1) each — the per-pass
        # memo alone still melted the head quadratically at many_tasks
        # scale (each new submit re-walked the whole backlog)
        blocked: Set[Any] = self._blocked_sigs
        while self.pending_queue:
            rec = self.pending_queue.popleft()
            if rec.state == "cancelled":
                continue  # cancelled while queued: drop lazily
            # sig cached on the record: a parked backlog is rescanned many
            # times and the tuple/sort/repr per record dominates the scan
            sig = getattr(rec, "_sig", None)
            if sig is None:
                sig = rec._sig = self._demand_sig(rec)
            if sig in blocked:
                self._parked.setdefault(sig, collections.deque()).append(rec)
                continue
            nid = self._select_node(rec.resources, rec.spec.get("scheduling_strategy"))
            if nid is None:
                blocked.add(sig)
                self._parked.setdefault(sig, collections.deque()).append(rec)
                continue
            self._dispatch_on(rec, nid)

    def _dispatch_on(self, rec: TaskRecord, nid: str):
        """Hand a task whose node capacity is ALREADY acquired (by
        _select_node) to the dispatch coroutine — the single handshake for
        both the pump and the parked-promotion path."""
        rec.node_id = nid
        rec.mark("scheduled")
        self._spawn_bg(self._dispatch_task(rec))

    async def _release_dispatch(self, rec: TaskRecord, w: Optional[WorkerRecord]):
        """Give back everything _dispatch_task holds: the node capacity
        acquired at scheduling and (if leased) the worker — then probe the
        parked backlog. The single teardown for the normal finally, the
        cancel short-circuits, and any future exit path."""
        self._release_node(rec.node_id, rec.resources, rec.spec.get("scheduling_strategy"))
        if w is not None and w.state == "busy":
            if w.pooled:
                w.state = "idle"
                self.idle_workers[w.node_id].append(w.worker_id)
            else:
                await self._kill_worker(w, reason="lease done")
        # probe even with no worker to return: the released NODE capacity
        # alone can unblock parked tasks
        self._capacity_changed(bulk=False)

    async def _dispatch_task(self, rec: TaskRecord):
        if rec.cancel_requested:
            # cancelled between scheduling and dispatch: give the acquired
            # capacity back and settle the returns
            await self._release_dispatch(rec, None)
            self._finish_cancel(rec)
            return
        w = await self._lease_worker(
            rec.node_id,
            needs_tpu=rec.resources.get("TPU", 0) > 0,
            runtime_env=rec.spec.get("runtime_env"),
        )
        if w is None:
            self._release_node(rec.node_id, rec.resources, rec.spec.get("scheduling_strategy"))
            await self._retry_or_fail(rec, RuntimeError("failed to lease a worker"))
            return
        if rec.cancel_requested:
            # cancelled during the lease await (state was still
            # "scheduled", so _h_cancel_task relies on this check)
            await self._release_dispatch(rec, w)
            self._finish_cancel(rec)
            return
        rec.worker_id = w.worker_id
        rec.mark("running")
        spec = rec.spec
        try:
            reply = await w.conn.request(
                {
                    "t": "run_task",
                    "task_id": spec["task_id"],
                    "fn_key": spec["fn_key"],
                    "args": self._resolve_args(spec),
                    "return_ids": spec["return_ids"],
                    "trace_ctx": spec.get("trace_ctx"),
                    "streaming": spec.get("streaming", False),
                }
            )
        except Exception as e:
            await self._retry_or_fail(rec, e)
            return
        finally:
            await self._release_dispatch(rec, w)
        if reply.get("lost_deps"):
            # dep buffers were evicted under the worker: rebuild them from
            # lineage and re-dispatch this task (pins stay held; not a retry)
            for oid in reply["lost_deps"]:
                try:
                    await self._reconstruct(oid)
                except Exception as e:
                    await self._retry_or_fail(rec, e)
                    return
            await self._resolve_and_enqueue(rec)
            return
        for oid in spec.get("deps", []):
            self.objects.unpin(oid)
        self._store_task_results(spec, reply)
        rec.mark("done")

    async def _retry_or_fail(self, rec: TaskRecord, error: Exception):
        from ..exceptions import OutOfMemoryError, WorkerCrashedError

        if rec.cancel_requested:
            # a cancelled task never retries; a force-kill's broken conn
            # lands here and must surface as cancellation, not a crash
            self._finish_cancel(rec)
            return
        w = self.workers.get(rec.worker_id or "")
        if w is not None and w.kill_reason:
            error = OutOfMemoryError(w.kill_reason)
        if rec.retries_left > 0 and not self._shutdown:
            rec.retries_left -= 1
            await asyncio.sleep(cfg.task_retry_delay_ms / 1000.0)
            rec.mark("pending")
            self.pending_queue.append(rec)
            self._pump()
            return
        rec.mark("failed")
        for oid in rec.spec.get("deps", []):
            self.objects.unpin(oid)
        if isinstance(error, OutOfMemoryError):
            self._fail_task_returns(rec.spec, error)
        else:
            self._fail_task_returns(rec.spec, WorkerCrashedError(f"task failed: {error!r}"))

    def _fail_task_returns(self, spec: dict, error: Exception):
        from .serialization import serialize

        env = serialize(error)
        env.is_error = True  # type: ignore[attr-defined]
        for oid in spec["return_ids"]:
            self.objects.put(oid, env)

    def _store_task_results(self, spec: dict, reply: dict):
        envs = reply["results"]
        for oid, env in zip(spec["return_ids"], envs):
            self.objects.put(oid, env)
            # returns start with one reference held by the submitting frontend's ObjectRef
            self.objects.add_ref(oid, 0)

    def _resolve_args(self, spec: dict) -> dict:
        """Attach resolved dependency envelopes to an argument payload."""
        deps = {}
        for oid in spec.get("deps", []):
            if self.objects.contains(oid):
                deps[oid] = self.objects.get(oid)
        return {"env": spec["args"], "resolved": deps}

    async def _lease_worker(
        self, node_id: str, needs_tpu: bool = False, runtime_env: Optional[dict] = None
    ) -> Optional[WorkerRecord]:
        pooled = not needs_tpu and not runtime_env
        if pooled:
            idle = self.idle_workers[node_id]
            while idle:
                wid = idle.pop()
                w = self.workers.get(wid)
                if w is not None and w.state == "idle" and w.conn and not w.conn.closed:
                    w.state = "busy"
                    return w
        w = await self._spawn_worker(node_id, runtime_env=runtime_env, needs_tpu=needs_tpu)
        w.pooled = pooled
        try:
            await asyncio.wait_for(w.registered, cfg.worker_register_timeout_s)
        except asyncio.TimeoutError:
            await self._kill_worker(w, reason="register timeout")
            return None
        if w.state != "idle":
            return None
        w.state = "busy"
        return w

    async def _spawn_worker(
        self,
        node_id: str,
        dedicated_actor_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        needs_tpu: bool = False,
    ) -> WorkerRecord:
        self._worker_counter += 1
        worker_id = f"worker-{self._worker_counter}"
        w = WorkerRecord(worker_id=worker_id, node_id=node_id, actor_id=dedicated_actor_id)
        w.registered = asyncio.get_running_loop().create_future()
        self.workers[worker_id] = w
        node = self.nodes.get(node_id)
        if node is not None and node.remote:
            # remote node: the agent spawns; the worker dials us back over TCP
            try:
                await node.conn.request(
                    {
                        "t": "spawn_worker",
                        "worker_id": worker_id,
                        "head_address": self.tcp_address,
                        "runtime_env": runtime_env,
                        "needs_tpu": needs_tpu,
                    }
                )
            except Exception as e:
                logger.warning("agent spawn failed on %s: %r", node_id, e)
                w.state = "dead"
                if not w.registered.done():
                    w.registered.set_result(None)
            return w
        env = dict(os.environ)
        env["RAY_TPU_SOCKET"] = self.socket_path
        env["RAY_TPU_WORKER_ID"] = worker_id
        env["RAY_TPU_NODE_ID"] = node_id
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        user_env_vars = (runtime_env or {}).get("env_vars") or {}
        for k, v in user_env_vars.items():
            env[k] = str(v)
        # working_dir / py_modules: stage into the session dir (content-hash
        # cached) and point the worker at the staged copies (reference:
        # _private/runtime_env/working_dir.py + the per-node runtime-env
        # agent, runtime_env_agent.py:161 — collapsed into spawn here)
        cwd = os.getcwd()
        extra_paths = []
        if runtime_env:
            loop = asyncio.get_running_loop()
            if runtime_env.get("working_dir"):
                # stage off-loop: a large copy must not stall cluster RPC
                cwd = await loop.run_in_executor(
                    None, self._stage_dir, runtime_env["working_dir"]
                )
                extra_paths.append(cwd)
            for mod in runtime_env.get("py_modules") or []:
                staged = await loop.run_in_executor(None, self._stage_dir, mod)
                # a staged single-file module is importable via its parent
                extra_paths.append(staged if os.path.isdir(staged) else os.path.dirname(staged))
        if extra_paths:
            # workers run -S, so PYTHONPATH must carry the full driver
            # sys.path (site-packages included), with staged dirs first and
            # any user-specified PYTHONPATH in between
            from .spawn import child_pythonpath

            env["PYTHONPATH"] = child_pythonpath(
                extra_paths,
                inherited=env["PYTHONPATH"] if "PYTHONPATH" in user_env_vars else None,
            )
        argv = [sys.executable, "-m", "ray_tpu._private.worker_main"]
        log_file = None
        if cfg.log_to_driver:
            # per-worker log file, tailed by _log_tail_loop and pushed to
            # drivers over the "__logs__" pubsub channel (reference:
            # _private/log_monitor.py tail + worker.py print redirection)
            log_dir = os.path.join(self.session_dir, "logs")
            os.makedirs(log_dir, exist_ok=True)
            log_file = open(os.path.join(log_dir, f"{worker_id}.out"), "ab")
        if needs_tpu:
            # TPU workers get the full interpreter (site hooks may register
            # the PJRT plugin) and inherit JAX_PLATFORMS as-is.
            env.pop("JAX_PLATFORMS", None)
        else:
            # Non-TPU workers must not grab the chips: exactly one process per
            # host may own them. Overwrite (not setdefault) — the inherited
            # value may name a TPU plugin platform whose registration hook
            # lives in `site` packages, which -S below skips. Also skip `site`
            # (-S) — site hooks can be arbitrarily slow — and hand down the
            # driver's sys.path instead.
            if "JAX_PLATFORMS" not in user_env_vars:
                env["JAX_PLATFORMS"] = "cpu"
            if not extra_paths:
                # always hand down sys.path: with -S and only a user
                # PYTHONPATH the child could not even import ray_tpu
                from .spawn import child_pythonpath

                env["PYTHONPATH"] = child_pythonpath(
                    inherited=env["PYTHONPATH"]
                    if "PYTHONPATH" in user_env_vars
                    else None,
                )
            argv.insert(1, "-S")
        if log_file is not None:
            env["PYTHONUNBUFFERED"] = "1"  # prints reach the tail promptly
            w.proc = subprocess.Popen(
                argv, env=env, cwd=cwd, stdout=log_file, stderr=subprocess.STDOUT
            )
            log_file.close()  # child holds its own fd
        else:
            w.proc = subprocess.Popen(argv, env=env, cwd=cwd)
        return w

    def _stage_dir(self, src: str) -> str:
        from .staging import stage_into

        return stage_into(self.session_dir, src)

    async def _kill_worker(self, w: WorkerRecord, reason: str = ""):
        if w.state == "dead":
            return
        w.state = "dead"
        await self._terminate_worker(w)
        if w.worker_id in self.idle_workers[w.node_id]:
            self.idle_workers[w.node_id].remove(w.worker_id)

    async def _terminate_worker(
        self, w: WorkerRecord, force: bool = False, close_conn: bool = True
    ):
        """Tear down the worker's connection and process (local or via its
        node agent). Idempotent; independent of record state."""
        if close_conn and w.conn is not None:
            await w.conn.close()
        if w.proc is not None and w.proc.poll() is None:
            try:
                w.proc.kill() if force else w.proc.terminate()
            except Exception:
                pass
        elif w.proc is None:
            # remote worker: the owning agent holds the process handle
            node = self.nodes.get(w.node_id)
            if node is not None and node.remote and not node.conn.closed:
                try:
                    await node.conn.request(
                        {"t": "kill_worker", "worker_id": w.worker_id, "force": force},
                        timeout=5,
                    )
                except Exception:
                    pass

    async def _on_worker_death(self, w: WorkerRecord, reason: str):
        if w.state == "dead":
            return
        was_actor = w.actor_id
        w.state = "dead"
        self._drop_task_lease(w.worker_id)  # frees the lease's node share
        if w.worker_id in self.idle_workers[w.node_id]:
            self.idle_workers[w.node_id].remove(w.worker_id)
        # actor restart path
        for rec in self.actors.values():
            if rec.worker_id == w.worker_id and rec.state in ("alive", "starting"):
                if self._shutdown:
                    rec.state = "dead"
                    continue
                self._release_actor_node(rec, w)
                if rec.restarts_left != 0:
                    if rec.restarts_left > 0:
                        rec.restarts_left -= 1
                    rec.state = "restarting"
                    await asyncio.sleep(cfg.actor_restart_delay_ms / 1000.0)
                    self._spawn_bg(self._start_actor(rec))
                else:
                    rec.state = "dead"
                    rec.death_reason = f"worker died ({reason})"
                    if rec.name:
                        self._unregister_name(rec)
                    await self._fail_backlog(rec)
        _ = was_actor
        if not self._shutdown:
            # the dropped lease / released actor node share may unblock
            # parked tasks
            self._capacity_changed(bulk=False)
