"""Control-plane wire protocol: length-prefixed pickled dicts.

Reference parity: src/ray/rpc (GrpcServer/GrpcClient) + src/ray/protobuf.
The reference uses gRPC because its control plane spans hosts and languages;
here the same framing rides two transports: unix domain sockets intra-host
(drivers/workers on the head machine) and TCP inter-host (per-host agents,
remote workers, remote drivers). Bulk data prefers the shared-memory object
plane; cross-node buffers are pulled through the head (see serialization).

Message = dict with "t" (type). Requests carry "rid"; replies are
{"t": "reply", "rid", "ok", "value"|"error"}.
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import logging
import pickle
import struct
import threading
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from . import faults

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<Q")
_NSEG = struct.Struct("<I")
MAX_MSG = 1 << 40

# Out-of-band framing (frame format v2): a length word with this bit set
# announces that the frame carries raw buffer segments AFTER the pickle
# body. Layout:
#   <Q (len(seg_hdr + body)) | _OOB_FLAG>  seg_hdr  body  seg0 seg1 ...
#   seg_hdr = <I nseg> + nseg * <Q seg_size>
# The body is pickled at protocol 5 with a buffer_callback that extracts
# every PickleBuffer larger than _SEG_INLINE_MAX; the receiver reads the
# segments into their own buffers and hands them to pickle.loads(buffers=)
# — big payloads (fetch_buffers relays, task args/returns) never pass
# through pickle's in-band copy on either side. JSON frames (cross-language
# clients) are never OOB.
_OOB_FLAG = 1 << 63
_SEG_INLINE_MAX = 64 * 1024

# Wire-format version, carried in every registration message and checked by
# the head (reference: the protobuf schema + gRPC service versioning of
# src/ray/protobuf). Bump whenever message shapes change incompatibly —
# cross-version control planes must fail fast with a clear error, not
# corrupt state mid-protocol (mixed versions happen when a multi-host
# deployment upgrades hosts one at a time). v3: out-of-band buffer
# segments on the plane framing (older peers would misread the flagged
# length word as an oversized frame).
PROTOCOL_VERSION = 3

# Handler types that may PARK indefinitely waiting for cluster events and
# only read state — safe (and necessary) to cancel when their connection
# dies. Everything else runs to completion even if the peer is gone.
# reconstruct_objects is deliberately NOT here: it pins deps and mutates
# task records across awaits, so cancelling it mid-flight would leak pins.
PARKABLE_TYPES = frozenset(
    {"poll_channel", "get_objects", "wait_objects", "pg_ready", "xget_objects"}
)

# Idempotency contract for retransmit (reference: Ray's task-retry rule —
# only side-effect-free work re-executes freely). Handlers here only READ
# state (or park waiting for it), so a retransmitted request simply
# re-executes; this is also the recovery mechanism for the lost-wakeup
# wedge, where the ORIGINAL handler may be parked forever on an orphaned
# event and only a fresh execution can answer. Retransmit-armed requests of
# any OTHER type are deduplicated by rid on the receiving side instead
# (see Connection._read_loop): the duplicate is dropped while the original
# executes, or answered from a bounded reply cache once it finished.
IDEMPOTENT_TYPES = PARKABLE_TYPES | frozenset(
    {
        "ping",
        "kv_get",
        "get_actor_route",
        "list_nodes",
        "list_actors",
        "list_tasks",
        "list_objects",
        "cluster_resources",
        "available_resources",
    }
)

# Replies kept per connection for rid dedup of retransmit-armed mutating
# requests; small — only such requests (rare today) land here.
_REPLY_CACHE_CAP = 512

# Per-attempt waits back off exponentially up to this multiple of the base
# deadline, so a slow-but-alive peer isn't hammered.
_BACKOFF_CAP = 8.0

# Process-wide recovery accounting, importable by tests without the metrics
# stack (the head runs in the driver process, so a test sees head-side
# increments here too). Mirrored into util/metrics counters when available.
_STATS_LOCK = threading.Lock()
PLANE_STATS = {
    "retries": 0,  # retransmits sent
    "recovered": 0,  # requests answered only after >= 1 retransmit
    "duplicate_replies": 0,  # replies whose rid was already answered/abandoned
    "deadline_timeouts": 0,  # requests that exhausted deadline + retries
    "dedup_hits": 0,  # receiver-side duplicate requests suppressed
    # head-side: get_objects hit an already-freed object and the head
    # re-ran its creating task from lineage instead of parking forever
    "freed_object_recoveries": 0,
}


def _stat(name: str, n: int = 1) -> None:
    with _STATS_LOCK:
        PLANE_STATS[name] += n


def reset_plane_stats() -> None:
    """Test hook: zero the counters (they are process-lifetime otherwise)."""
    with _STATS_LOCK:
        for k in PLANE_STATS:
            PLANE_STATS[k] = 0


def _metric(counter_fn_name: str, tags: Optional[dict] = None) -> None:
    """Best-effort mirror into util/metrics; never breaks the plane."""
    try:
        from ray_tpu.util import metrics as _m

        getattr(_m, counter_fn_name)().inc(tags=tags)
    except Exception:
        pass


def check_protocol_version(msg: dict, peer: str) -> None:
    got = msg.get("proto", 1)
    if got != PROTOCOL_VERSION:
        raise ConnectionError(
            f"{peer} speaks control-plane protocol v{got}, this head speaks "
            f"v{PROTOCOL_VERSION}; upgrade all hosts to the same ray_tpu "
            f"version before joining them to one cluster"
        )


def is_tcp_address(address: str) -> bool:
    """'host:port' (TCP) vs a filesystem path (unix socket)."""
    if address.startswith(("/", ".")):
        return False
    host, sep, port = address.rpartition(":")
    return bool(sep) and port.isdigit() and bool(host)


def parse_tcp_address(address: str) -> Tuple[str, int]:
    host, _, port = address.rpartition(":")
    return host, int(port)


async def open_stream(address: str):
    """Open (reader, writer) to a head/agent at a unix path or host:port."""
    if is_tcp_address(address):
        host, port = parse_tcp_address(address)
        return await asyncio.open_connection(host, port)
    return await asyncio.open_unix_connection(address)


CODEC_PICKLE = "pickle"
CODEC_JSON = "json"


class WireBuffer:
    """Marks a buffer for OUT-OF-BAND transport on the plane framing: the
    bytes ride as a raw segment after the pickle body (sender writes the
    view straight to the socket; receiver's pickle.loads hands back a
    readonly view of the received segment). Unwraps to the plain buffer on
    load — handlers upstream see bytes/memoryview exactly as before.
    memoryview itself is not picklable, which is why this wrapper exists."""

    __slots__ = ("view",)

    def __init__(self, data):
        self.view = data if isinstance(data, memoryview) else memoryview(data)

    def __len__(self):
        return self.view.nbytes

    def __reduce_ex__(self, protocol):
        if protocol >= 5:
            return (_wire_load, (pickle.PickleBuffer(self.view),))
        return (_wire_load, (bytes(self.view),))


def _wire_load(buf):
    # bytes (in-band / legacy protocol) or a readonly memoryview of an
    # out-of-band segment — both satisfy every buffer consumer downstream
    return buf


def _parse_oob(payload):
    """Split an OOB frame's first block into (segment sizes, pickle body)."""
    (nseg,) = _NSEG.unpack_from(payload, 0)
    sizes = [
        _LEN.unpack_from(payload, _NSEG.size + i * _LEN.size)[0]
        for i in range(nseg)
    ]
    body = memoryview(payload)[_NSEG.size + nseg * _LEN.size :]
    return sizes, body


async def read_msg(reader: asyncio.StreamReader) -> Tuple[dict, str]:
    """Returns (msg, codec). Frames are pickle by default; a body whose
    first byte is '{' is a JSON frame from a cross-language client (the
    C++ API, cpp/client/) — unambiguous because pickle protocol >= 2
    always starts with 0x80. Replies go back in the codec of the request
    (reference: the protobuf wire format serves every worker language).
    A length word with _OOB_FLAG set carries raw buffer segments after the
    pickle body (see the framing comment at the top)."""
    hdr = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(hdr)
    oob = bool(n & _OOB_FLAG)
    n &= ~_OOB_FLAG
    if n > MAX_MSG:
        raise ConnectionError(f"oversized frame: {n}")
    body = await reader.readexactly(n)
    if oob:
        sizes, pbody = _parse_oob(body)
        segs = [await reader.readexactly(s) for s in sizes]
        return pickle.loads(pbody, buffers=segs), CODEC_PICKLE
    if body[:1] == b"{":
        import json

        return json.loads(body), CODEC_JSON
    return pickle.loads(body), CODEC_PICKLE


def _json_safe(value):
    """Best-effort JSON view of a reply value for cross-language clients
    (bytes -> base64 under a tag; unknown objects -> repr)."""
    import base64

    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, bytes):
        return {"__b64__": base64.b64encode(value).decode()}
    if isinstance(value, dict):
        return {str(k): _json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    return repr(value)


def _frame_parts(msg: dict, codec: str = CODEC_PICKLE) -> list:
    """Frame `msg` as a list of bytes-like parts for a vectored write.
    Large WireBuffer payloads become out-of-band segments: the raw views go
    straight from their source buffer (often an shm mapping) to the socket
    — no pickle in-band copy of the bulk bytes."""
    if codec == CODEC_JSON:
        import json

        body = json.dumps(_json_safe(msg)).encode()
        return [_LEN.pack(len(body)), body]
    segs: list = []

    def _extract(pb) -> bool:
        mv = pb.raw()
        if mv.nbytes <= _SEG_INLINE_MAX:
            return True  # small: serialize in-band, not worth a segment
        segs.append(mv)
        return False

    body = pickle.dumps(msg, protocol=5, buffer_callback=_extract)
    if not segs:
        return [_LEN.pack(len(body)), body]
    seg_hdr = _NSEG.pack(len(segs)) + b"".join(
        _LEN.pack(s.nbytes) for s in segs
    )
    return [
        _LEN.pack((len(seg_hdr) + len(body)) | _OOB_FLAG),
        seg_hdr,
        body,
        *segs,
    ]


def _frame(msg: dict, codec: str = CODEC_PICKLE) -> bytes:
    return b"".join(_frame_parts(msg, codec))


async def send_msg(writer: asyncio.StreamWriter, msg: dict) -> None:
    writer.writelines(_frame_parts(msg))
    await writer.drain()


def _recv_exact_sync(sock, size: int) -> bytearray:
    buf = bytearray(size)
    view = memoryview(buf)
    got = 0
    while got < size:
        n = sock.recv_into(view[got:])
        if n == 0:
            raise ConnectionError("peer closed mid-frame")
        got += n
    return buf


def write_frame_sync(sock, msg: dict) -> None:
    """Blocking-socket twin of send_msg (the worker bypass channel)."""
    for part in _frame_parts(msg):
        sock.sendall(part)


def read_frame_sync(sock) -> dict:
    """Blocking-socket twin of read_msg, OOB-aware (pickle frames only —
    the bypass channel is python-to-python)."""
    (n,) = _LEN.unpack(bytes(_recv_exact_sync(sock, _LEN.size)))
    oob = bool(n & _OOB_FLAG)
    n &= ~_OOB_FLAG
    if n > MAX_MSG:
        raise ConnectionError(f"oversized frame: {n}")
    body = _recv_exact_sync(sock, n)
    if oob:
        sizes, pbody = _parse_oob(body)
        segs = [_recv_exact_sync(sock, s) for s in sizes]
        return pickle.loads(pbody, buffers=segs)
    return pickle.loads(bytes(body))


class Connection:
    """A bidirectional message channel with request/response correlation.

    Both sides can issue requests and receive pushes. `handler(msg)` is called
    for every inbound non-reply message; if the message has a "rid", the
    handler's return value (or raised exception) is sent back as the reply.
    """

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        handler: Callable[[dict], Awaitable[Any]],
        on_close: Optional[Callable[[], Awaitable[None]]] = None,
        name: str = "",
    ):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.on_close = on_close
        # role tag ("head", "worker:<id>", ...): names this connection in
        # hang dumps and lets fault injection black-hole one link by name
        self.name = name
        self._rid_counter = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        # retry/attempt state per outstanding rid, for pending_summary()
        # hang dumps and the warn watchdog
        self._pending_meta: Dict[int, dict] = {}
        # correlation lock: registration in request() and the pop in
        # _read_loop/_close mutate _pending from (potentially) different
        # threads during shutdown teardowns; a plain dict race here is the
        # classic way a reply crosses its registration and is dropped as
        # "unknown rid". All loop-side paths take it too — it is never
        # contended in steady state, so the cost is one uncontended acquire.
        self._corr_lock = threading.Lock()
        # receiver-side rid dedup for retransmit-armed MUTATING requests:
        # rids whose original dispatch is still executing (duplicates are
        # dropped — the original will reply), and a bounded cache of
        # finished replies (duplicates get the cached reply re-sent, the
        # handler never re-executes)
        self._dedup_inflight: set = set()
        self._reply_cache: "collections.OrderedDict[int, tuple]" = (
            collections.OrderedDict()
        )
        self._send_lock = asyncio.Lock()
        self._closed = False
        self._reader_task: Optional[asyncio.Task] = None
        # sticky peer codec: once a JSON frame arrives, pushes (pubsub,
        # kill notices) go back as JSON too — a cross-language subscriber
        # must never receive a pickle frame it can't parse
        self.codec = CODEC_PICKLE
        # in-flight PARKABLE handler tasks, cancelled at close — otherwise
        # a blocked handler (e.g. a parked long-poll) outlives its
        # connection and is "destroyed but pending" at loop teardown.
        # Non-parkable (state-mutating) dispatches are left to run to
        # completion: cancelling e.g. kill_actor mid-flight would strand
        # half-applied state transitions.
        self._dispatch_tasks: set = set()

    def start(self):
        self._reader_task = asyncio.get_running_loop().create_task(self._read_loop())
        return self

    async def _read_loop(self):
        try:
            while True:
                msg, codec = await read_msg(self.reader)
                if codec == CODEC_JSON:
                    self.codec = CODEC_JSON
                if msg.get("t") == "reply":
                    with self._corr_lock:
                        fut = self._pending.pop(msg["rid"], None)
                        meta = self._pending_meta.pop(msg["rid"], None)
                    if fut is None or fut.done():
                        # duplicate or late reply: the rid was already
                        # answered (a retransmit raced its original) or
                        # abandoned (caller timed out). Drop it — the
                        # request future was completed exactly once — and
                        # count, so recovery noise stays observable.
                        _stat("duplicate_replies")
                        _metric("data_plane_duplicate_replies_counter")
                        logger.debug(
                            "dropped duplicate/late reply rid=%s on %s",
                            msg.get("rid"), self.name or "conn",
                        )
                    elif msg["ok"]:
                        if meta is not None and meta.get("attempt", 0) > 0:
                            meta["recovered"] = True
                        fut.set_result(msg.get("value"))
                    else:
                        fut.set_exception(msg["error"])
                else:
                    rid = msg.get("rid")
                    if (
                        rid is not None
                        and "attempt" in msg
                        and msg.get("t") not in IDEMPOTENT_TYPES
                    ):
                        # retransmit-armed mutating request: execute at
                        # most once per rid on this connection
                        if rid in self._dedup_inflight:
                            _stat("dedup_hits")
                            continue  # original still executing; it replies
                        cached = self._reply_cache.get(rid)
                        if cached is not None:
                            _stat("dedup_hits")
                            reply, rcodec = cached
                            asyncio.get_running_loop().create_task(
                                self._send_quiet(reply, rcodec)
                            )
                            continue
                        self._dedup_inflight.add(rid)
                    task = asyncio.get_running_loop().create_task(
                        self._dispatch(msg, codec)
                    )
                    if msg.get("t") in PARKABLE_TYPES:
                        self._dispatch_tasks.add(task)
                        task.add_done_callback(self._dispatch_tasks.discard)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            await self._close()

    async def _dispatch(self, msg: dict, codec: str = CODEC_PICKLE):
        rid = msg.get("rid")
        dedup = (
            rid is not None
            and "attempt" in msg
            and msg.get("t") not in IDEMPOTENT_TYPES
        )
        try:
            try:
                result = await self.handler(msg)
                reply = {"t": "reply", "rid": rid, "ok": True, "value": result}
            except Exception as e:  # noqa: BLE001 - errors propagate to the peer
                if rid is None:
                    return
                err = repr(e) if codec == CODEC_JSON else e
                reply = {"t": "reply", "rid": rid, "ok": False, "error": err}
            if rid is None:
                return
            if dedup:
                # cache BEFORE any fault/send so a retransmit arriving
                # after a dropped reply is answered from here — the
                # mutating handler ran exactly once
                self._reply_cache[rid] = (reply, codec)
                while len(self._reply_cache) > _REPLY_CACHE_CAP:
                    self._reply_cache.popitem(last=False)
            action = faults.reply_action(msg.get("t")) if faults.ACTIVE else None
            if action == "drop":
                return  # simulated lost reply frame; request side must recover
            await self._send_quiet(reply, codec)
            if action == "dup":
                await self._send_quiet(reply, codec)
        finally:
            if dedup:
                self._dedup_inflight.discard(rid)

    async def _send_quiet(self, msg: dict, codec: Optional[str] = None):
        """send() for replies: the peer vanishing mid-reply is routine."""
        try:
            await self.send(msg, codec)
        except Exception:
            pass

    async def send(self, msg: dict, codec: Optional[str] = None):
        if faults.ACTIVE:
            action = faults.send_action(self.name, msg.get("t"))
            if action == "drop":
                return  # black-holed link: frame vanishes, socket stays up
            if action:
                await asyncio.sleep(float(action))
        async with self._send_lock:
            if self.writer.is_closing():
                # peer went away between request and reply (e.g. a job
                # driver exiting). drain() would raise this same error
                # after the write anyway — skip the write so asyncio's
                # conn-lost warning counter never fires, but keep the
                # raise so callers still detect the dead peer.
                raise ConnectionResetError("peer connection closed")
            # vectored write of the frame parts: OOB segments (slab views)
            # go straight to the transport without being joined into one
            # contiguous bytes object first
            self.writer.writelines(_frame_parts(msg, codec or self.codec))
            await self.writer.drain()

    async def request(
        self,
        msg: dict,
        timeout: Optional[float] = None,
        warn_after_s: Optional[float] = None,
        warn_tag: Optional[str] = None,
        deadline_s: Optional[float] = None,
        retries: int = 0,
    ) -> Any:
        """Send `msg` with a fresh monotonic rid and await the correlated
        reply.

        `warn_after_s` arms a watchdog that logs LOUDLY (repeating each
        interval, naming the rid, message type, `warn_tag` and the
        retry/attempt state of this connection's other outstanding rids)
        while the reply is missing.

        `deadline_s` arms retransmit: if no reply lands within the
        (per-attempt, capped-exponential) deadline, the SAME rid is re-sent
        with a bumped `attempt` counter, up to `retries` times, then
        PlaneRequestTimeout surfaces. The rid stays stable across attempts
        so whichever execution answers first completes the one future;
        later replies are dropped as duplicates. Handlers in
        IDEMPOTENT_TYPES re-execute freely (that re-execution IS the
        recovery when the original parked on a lost wakeup); others are
        rid-deduplicated on the receiving side. The watchdog and the
        deadline share this one coroutine's timer — a retransmit never
        spawns a second warn loop."""
        rid = next(self._rid_counter)
        base = dict(msg)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        mtype = base.get("t")
        meta = {
            "t": mtype,
            "tag": warn_tag or "",
            "attempt": 0,
            "retries": int(retries or 0),
            "deadline_s": deadline_s,
            "t0": time.monotonic(),
            "recovered": False,
        }
        with self._corr_lock:
            self._pending[rid] = fut
            self._pending_meta[rid] = meta
        watchdog = None
        # sends sit inside the cleanup scope: a failed/cancelled send must
        # not leak the pending entry or an immortal watchdog
        try:
            if warn_after_s and warn_after_s > 0:
                watchdog = loop.create_task(
                    self._warn_watch(rid, fut, meta, warn_after_s)
                )
            if not deadline_s or deadline_s <= 0:
                # legacy wait-forever path (plus optional caller timeout)
                await self.send(dict(base, rid=rid))
                return await asyncio.wait_for(fut, timeout)
            max_attempts = 1 + max(0, int(retries or 0))
            start = time.monotonic()
            while True:
                attempt = meta["attempt"]
                await self.send(dict(base, rid=rid, attempt=attempt))
                wait_s = min(deadline_s * (2 ** attempt),
                             deadline_s * _BACKOFF_CAP)
                if timeout is not None:
                    wait_s = min(
                        wait_s, max(0.0, start + timeout - time.monotonic())
                    )
                try:
                    # shield: a per-attempt timeout must not cancel the
                    # shared future — a later attempt still awaits it
                    value = await asyncio.wait_for(
                        asyncio.shield(fut), wait_s
                    )
                except asyncio.TimeoutError:
                    if fut.done():
                        value = fut.result()  # reply raced the timer
                    elif (
                        timeout is not None
                        and time.monotonic() - start >= timeout
                    ):
                        raise  # caller's overall timeout: legacy contract
                    elif attempt + 1 >= max_attempts:
                        _stat("deadline_timeouts")
                        from ray_tpu.exceptions import PlaneRequestTimeout

                        raise PlaneRequestTimeout(
                            str(mtype), rid, max_attempts,
                            time.monotonic() - start, warn_tag or "",
                        )
                    else:
                        meta["attempt"] = attempt + 1
                        _stat("retries")
                        _metric(
                            "data_plane_retries_counter",
                            tags={"kind": str(mtype)},
                        )
                        logger.warning(
                            "request t=%r rid=%d%s: no reply in %.1fs, "
                            "retransmitting (attempt %d/%d) on %s",
                            mtype, rid,
                            f" [{warn_tag}]" if warn_tag else "",
                            wait_s, attempt + 1, max_attempts - 1,
                            self.name or "conn",
                        )
                        continue
                if meta["attempt"] > 0:
                    self._record_recovered(mtype, rid, meta)
                return value
        finally:
            if watchdog is not None:
                watchdog.cancel()
            with self._corr_lock:
                self._pending.pop(rid, None)
                self._pending_meta.pop(rid, None)

    def _record_recovered(self, mtype, rid: int, meta: dict) -> None:
        """A retransmitted request got its answer: recovery is as visible
        as loss was (counter + flight-recorder event, mirroring the
        orphaned-request telemetry)."""
        _stat("recovered")
        logger.warning(
            "request t=%r rid=%d recovered after %d retransmit(s) "
            "(%.1fs total) on %s",
            mtype, rid, meta["attempt"],
            time.monotonic() - meta["t0"], self.name or "conn",
        )
        try:
            import sys as _sys

            tmod = _sys.modules.get("ray_tpu.serve.telemetry")
            if tmod is not None and hasattr(tmod, "record_request_recovered"):
                tmod.record_request_recovered(mtype, rid, meta["attempt"])
            else:
                _metric(
                    "data_plane_recovered_counter", tags={"kind": str(mtype)}
                )
        except Exception:
            pass

    async def _warn_watch(self, rid, fut, meta, warn_after_s):
        """One watchdog per request, shared by every retransmit attempt:
        logs loudly while the reply is missing, lands the first fire in the
        telemetry plane (data_plane_orphaned_requests_total + a
        flight-recorder instant). The serve stack is only used when ALREADY
        imported (serving processes): a training/data worker's watchdog
        must not pull the whole serve package onto its event loop
        mid-wedge — it still gets the counter via util/metrics."""
        recorded = False
        while not fut.done():
            await asyncio.sleep(warn_after_s)
            if fut.done():
                return
            if not recorded:
                recorded = True
                try:
                    import sys as _sys

                    tmod = _sys.modules.get("ray_tpu.serve.telemetry")
                    if tmod is not None:
                        tmod.record_orphaned_request(
                            meta["t"], rid, meta["tag"])
                    else:
                        from ray_tpu.util import metrics as _m

                        _m.data_plane_orphaned_counter().inc(
                            tags={"kind": meta["tag"] or str(meta["t"])})
                        _m.flush()
                except Exception:
                    pass
            others = [
                s for s in self.pending_summary() if s["rid"] != rid
            ]
            logger.error(
                "request t=%r rid=%d%s has no reply after %.0fs "
                "(attempt %d/%d, connection %s; %d other outstanding: %s)",
                meta["t"], rid,
                f" [{meta['tag']}]" if meta["tag"] else "",
                time.monotonic() - meta["t0"],
                meta["attempt"], meta["retries"],
                "closed" if self._closed else (self.name or "open"),
                len(others), others[:8],
            )

    def pending_summary(self):
        """Retry/attempt state of every outstanding rid — thread-safe, so
        the test hang guard can dump it from a signal handler."""
        now = time.monotonic()
        with self._corr_lock:
            items = [
                (r, dict(self._pending_meta.get(r) or {}))
                for r in self._pending
            ]
        return [
            {
                "rid": r,
                "t": m.get("t"),
                "attempt": m.get("attempt", 0),
                "retries": m.get("retries", 0),
                "age_s": round(now - m.get("t0", now), 1),
                "tag": m.get("tag", ""),
            }
            for r, m in sorted(items)
        ]

    async def _close(self):
        if self._closed:
            return
        self._closed = True
        current = asyncio.current_task()
        for t in list(self._dispatch_tasks):
            if t is not current:  # _close may run inside a dispatch task
                t.cancel()
        with self._corr_lock:
            futs = list(self._pending.values())
            self._pending.clear()
            self._pending_meta.clear()
        for fut in futs:
            if not fut.done():
                fut.set_exception(ConnectionError("connection closed"))
        self._dedup_inflight.clear()
        self._reply_cache.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close is not None:
            await self.on_close()

    @property
    def closed(self) -> bool:
        return self._closed

    async def close(self):
        if self._reader_task is not None:
            self._reader_task.cancel()
        await self._close()
