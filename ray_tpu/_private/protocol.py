"""Control-plane wire protocol: length-prefixed pickled dicts.

Reference parity: src/ray/rpc (GrpcServer/GrpcClient) + src/ray/protobuf.
The reference uses gRPC because its control plane spans hosts and languages;
here the same framing rides two transports: unix domain sockets intra-host
(drivers/workers on the head machine) and TCP inter-host (per-host agents,
remote workers, remote drivers). Bulk data prefers the shared-memory object
plane; cross-node buffers are pulled through the head (see serialization).

Message = dict with "t" (type). Requests carry "rid"; replies are
{"t": "reply", "rid", "ok", "value"|"error"}.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import struct
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<Q")
MAX_MSG = 1 << 40

# Wire-format version, carried in every registration message and checked by
# the head (reference: the protobuf schema + gRPC service versioning of
# src/ray/protobuf). Bump whenever message shapes change incompatibly —
# cross-version control planes must fail fast with a clear error, not
# corrupt state mid-protocol (mixed versions happen when a multi-host
# deployment upgrades hosts one at a time).
PROTOCOL_VERSION = 2

# Handler types that may PARK indefinitely waiting for cluster events and
# only read state — safe (and necessary) to cancel when their connection
# dies. Everything else runs to completion even if the peer is gone.
# reconstruct_objects is deliberately NOT here: it pins deps and mutates
# task records across awaits, so cancelling it mid-flight would leak pins.
PARKABLE_TYPES = frozenset(
    {"poll_channel", "get_objects", "wait_objects", "pg_ready", "xget_objects"}
)


def check_protocol_version(msg: dict, peer: str) -> None:
    got = msg.get("proto", 1)
    if got != PROTOCOL_VERSION:
        raise ConnectionError(
            f"{peer} speaks control-plane protocol v{got}, this head speaks "
            f"v{PROTOCOL_VERSION}; upgrade all hosts to the same ray_tpu "
            f"version before joining them to one cluster"
        )


def is_tcp_address(address: str) -> bool:
    """'host:port' (TCP) vs a filesystem path (unix socket)."""
    if address.startswith(("/", ".")):
        return False
    host, sep, port = address.rpartition(":")
    return bool(sep) and port.isdigit() and bool(host)


def parse_tcp_address(address: str) -> Tuple[str, int]:
    host, _, port = address.rpartition(":")
    return host, int(port)


async def open_stream(address: str):
    """Open (reader, writer) to a head/agent at a unix path or host:port."""
    if is_tcp_address(address):
        host, port = parse_tcp_address(address)
        return await asyncio.open_connection(host, port)
    return await asyncio.open_unix_connection(address)


CODEC_PICKLE = "pickle"
CODEC_JSON = "json"


async def read_msg(reader: asyncio.StreamReader) -> Tuple[dict, str]:
    """Returns (msg, codec). Frames are pickle by default; a body whose
    first byte is '{' is a JSON frame from a cross-language client (the
    C++ API, cpp/client/) — unambiguous because pickle protocol >= 2
    always starts with 0x80. Replies go back in the codec of the request
    (reference: the protobuf wire format serves every worker language)."""
    hdr = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(hdr)
    if n > MAX_MSG:
        raise ConnectionError(f"oversized frame: {n}")
    body = await reader.readexactly(n)
    if body[:1] == b"{":
        import json

        return json.loads(body), CODEC_JSON
    return pickle.loads(body), CODEC_PICKLE


def _json_safe(value):
    """Best-effort JSON view of a reply value for cross-language clients
    (bytes -> base64 under a tag; unknown objects -> repr)."""
    import base64

    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, bytes):
        return {"__b64__": base64.b64encode(value).decode()}
    if isinstance(value, dict):
        return {str(k): _json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    return repr(value)


def _frame(msg: dict, codec: str = CODEC_PICKLE) -> bytes:
    if codec == CODEC_JSON:
        import json

        body = json.dumps(_json_safe(msg)).encode()
    else:
        body = pickle.dumps(msg, protocol=5)
    return _LEN.pack(len(body)) + body


async def send_msg(writer: asyncio.StreamWriter, msg: dict) -> None:
    writer.write(_frame(msg))
    await writer.drain()


class Connection:
    """A bidirectional message channel with request/response correlation.

    Both sides can issue requests and receive pushes. `handler(msg)` is called
    for every inbound non-reply message; if the message has a "rid", the
    handler's return value (or raised exception) is sent back as the reply.
    """

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        handler: Callable[[dict], Awaitable[Any]],
        on_close: Optional[Callable[[], Awaitable[None]]] = None,
    ):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.on_close = on_close
        self._rid_counter = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._send_lock = asyncio.Lock()
        self._closed = False
        self._reader_task: Optional[asyncio.Task] = None
        # sticky peer codec: once a JSON frame arrives, pushes (pubsub,
        # kill notices) go back as JSON too — a cross-language subscriber
        # must never receive a pickle frame it can't parse
        self.codec = CODEC_PICKLE
        # in-flight PARKABLE handler tasks, cancelled at close — otherwise
        # a blocked handler (e.g. a parked long-poll) outlives its
        # connection and is "destroyed but pending" at loop teardown.
        # Non-parkable (state-mutating) dispatches are left to run to
        # completion: cancelling e.g. kill_actor mid-flight would strand
        # half-applied state transitions.
        self._dispatch_tasks: set = set()

    def start(self):
        self._reader_task = asyncio.get_running_loop().create_task(self._read_loop())
        return self

    async def _read_loop(self):
        try:
            while True:
                msg, codec = await read_msg(self.reader)
                if codec == CODEC_JSON:
                    self.codec = CODEC_JSON
                if msg.get("t") == "reply":
                    fut = self._pending.pop(msg["rid"], None)
                    if fut is not None and not fut.done():
                        if msg["ok"]:
                            fut.set_result(msg.get("value"))
                        else:
                            fut.set_exception(msg["error"])
                else:
                    task = asyncio.get_running_loop().create_task(
                        self._dispatch(msg, codec)
                    )
                    if msg.get("t") in PARKABLE_TYPES:
                        self._dispatch_tasks.add(task)
                        task.add_done_callback(self._dispatch_tasks.discard)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            await self._close()

    async def _dispatch(self, msg: dict, codec: str = CODEC_PICKLE):
        rid = msg.get("rid")
        try:
            result = await self.handler(msg)
            if rid is not None:
                await self.send(
                    {"t": "reply", "rid": rid, "ok": True, "value": result}, codec
                )
        except Exception as e:  # noqa: BLE001 - errors propagate to the peer
            if rid is not None:
                try:
                    err = repr(e) if codec == CODEC_JSON else e
                    await self.send(
                        {"t": "reply", "rid": rid, "ok": False, "error": err}, codec
                    )
                except Exception:
                    pass

    async def send(self, msg: dict, codec: Optional[str] = None):
        async with self._send_lock:
            if self.writer.is_closing():
                # peer went away between request and reply (e.g. a job
                # driver exiting). drain() would raise this same error
                # after the write anyway — skip the write so asyncio's
                # conn-lost warning counter never fires, but keep the
                # raise so callers still detect the dead peer.
                raise ConnectionResetError("peer connection closed")
            self.writer.write(_frame(msg, codec or self.codec))
            await self.writer.drain()

    async def request(
        self,
        msg: dict,
        timeout: Optional[float] = None,
        warn_after_s: Optional[float] = None,
        warn_tag: Optional[str] = None,
    ) -> Any:
        """Send `msg` with a fresh monotonic rid and await the correlated
        reply. `warn_after_s` arms a watchdog that logs LOUDLY (repeating
        each interval, naming the rid, message type, `warn_tag` and this
        connection's other outstanding rids) while the reply is missing —
        semantics are unchanged, but a lost request/reply pair becomes a
        diagnosable log line next to a hang-guard stack dump instead of a
        silent wedge."""
        rid = next(self._rid_counter)
        msg = dict(msg, rid=rid)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending[rid] = fut
        watchdog = None
        # the send itself sits inside the cleanup scope: a failed/cancelled
        # send must not leak the pending entry or an immortal watchdog
        try:
            if warn_after_s and warn_after_s > 0:
                t0 = loop.time()
                mtype = msg.get("t")

                async def _watch():
                    recorded = False
                    while not fut.done():
                        await asyncio.sleep(warn_after_s)
                        if fut.done():
                            return
                        if not recorded:
                            # once per orphaned request: the wedge lands in
                            # the telemetry plane too — a
                            # data_plane_orphaned_requests_total increment
                            # (visible at /metrics) and a flight-recorder
                            # instant, force-flushed so the head holds the
                            # evidence even if this process hangs next.
                            # The serve stack is only used when ALREADY
                            # imported (serving processes): a training/data
                            # worker's watchdog must not pull the whole
                            # serve package onto its event loop mid-wedge —
                            # it still gets the counter via util/metrics.
                            recorded = True
                            try:
                                import sys as _sys

                                tmod = _sys.modules.get(
                                    "ray_tpu.serve.telemetry")
                                if tmod is not None:
                                    tmod.record_orphaned_request(
                                        mtype, rid, warn_tag or "")
                                else:
                                    from ray_tpu.util import metrics as _m

                                    _m.data_plane_orphaned_counter().inc(
                                        tags={
                                            "kind": warn_tag or str(mtype)})
                                    _m.flush()
                            except Exception:
                                pass
                        outstanding = sorted(
                            r for r in self._pending if r != rid
                        )
                        logger.error(
                            "request t=%r rid=%d%s has no reply after %.0fs "
                            "(connection %s; %d other outstanding rids: %s)",
                            mtype, rid,
                            f" [{warn_tag}]" if warn_tag else "",
                            loop.time() - t0,
                            "closed" if self._closed else "open",
                            len(outstanding), outstanding[:8],
                        )

                watchdog = loop.create_task(_watch())
            await self.send(msg)
            return await asyncio.wait_for(fut, timeout)
        finally:
            if watchdog is not None:
                watchdog.cancel()
            self._pending.pop(rid, None)

    async def _close(self):
        if self._closed:
            return
        self._closed = True
        current = asyncio.current_task()
        for t in list(self._dispatch_tasks):
            if t is not current:  # _close may run inside a dispatch task
                t.cancel()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("connection closed"))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close is not None:
            await self.on_close()

    @property
    def closed(self) -> bool:
        return self._closed

    async def close(self):
        if self._reader_task is not None:
            self._reader_task.cancel()
        await self._close()
