"""Local usage recording (opt-out), no network egress.

Reference parity: python/ray/_private/usage/usage_lib.py — the reference
collects feature-usage tags and reports them (opt-out via env,
usage_lib.py:292-297). ray_tpu keeps the same tag surface but records to a
LOCAL file only (<session_dir>/usage.json): the data answers "which
subsystems did this session touch" for operators and tests without any
phone-home.

Opt out with RAY_TPU_USAGE_STATS_ENABLED=0.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

_lock = threading.Lock()
_tags: Dict[str, str] = {}
_session_dir: Optional[str] = None


def enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") not in ("0", "false")


def set_session_dir(path: Optional[str]) -> None:
    global _session_dir
    _session_dir = path
    if path is not None:
        _flush()  # tags recorded before init (library imports) land now


def record_library_usage(name: str) -> None:
    """Tag a subsystem as used this session (train/tune/serve/data/...)."""
    record_extra_usage_tag(f"library_{name}", "1")


def record_extra_usage_tag(key: str, value: str) -> None:
    if not enabled():
        return
    with _lock:
        _tags[key] = str(value)
    _flush()


def usage_stats() -> Dict[str, str]:
    with _lock:
        return dict(_tags)


def _flush() -> None:
    sd = _session_dir
    if sd is None or not os.path.isdir(sd):
        return
    try:
        with _lock:
            payload = {"time": time.time(), "tags": dict(_tags)}
        tmp = os.path.join(sd, ".usage.json.tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, os.path.join(sd, "usage.json"))
    except OSError:
        pass


def reset_for_tests() -> None:
    with _lock:
        _tags.clear()
