"""Deterministic fault injection for the control/data plane.

Reference parity: Ray's RAY_testing_* fault-injection hooks
(src/ray/common/test_utils + the chaos-test NodeKillerActor): faults are
armed by configuration, deterministic under a seed, and exercised by the
chaos suite instead of waiting for a flaky standalone repro.

Arming
------
Set ``RAY_TPU_FAULTS`` to a comma-separated directive list before the
cluster starts (spawned workers inherit the environment), or call
``faults.arm(spec, seed=..., state_dir=...)`` programmatically (covers the
head + driver, which share the test process). ``RAY_TPU_TEST_FAULT_SEED``
seeds the controller's RNG for the probabilistic ``rand:<p>`` selector.

Directives
----------
  drop_reply:<type>:<sel>    swallow the selected replies to requests of
                             <type> (the request EXECUTED; only the reply
                             frame is lost — the lost-get_objects wedge)
  dup_reply:<type>:<sel>     deliver the selected replies twice
  delay_send:<type|any>:<s>  delay every matching outbound frame by <s> sec
  delay_handler:<type>:<s>   delay the head-side handler for <type> by <s>
  blackhole:<conn|any>       silently drop ALL frames on connections whose
                             name matches (socket stays open: the peer sees
                             a hang, not a reset)
  kill_task:<fn|any>:<sel|once>  SIGKILL this worker process right before
                             the selected matching task executes; ``once``
                             fires exactly once across ALL processes via an
                             O_EXCL marker file (a per-process counter
                             would also kill the task's retry)
  bulk_close:<sel>           close the bulk-plane socket mid-stream while
                             serving the selected request (peer-death
                             analogue: the consumer sees a short read)
  bulk_blackhole:<sel>       swallow the selected bulk-plane request — no
                             reply, socket stays open (the consumer's read
                             timeout fires)
  kv_transfer_drop:<sel>     corrupt the selected cross-replica KV
                             transfer mid-flight (serve/kv_transfer.py
                             truncates the packed payload before it
                             ships): the importer's verification fails
                             and the request falls back to local
                             recompute — never wrong tokens
  weight_swap_drop:<sel>     truncate the selected live weight pull
                             (serve/weight_swap.py): leaf verification
                             fails, the swap aborts whole, and the
                             replica keeps serving its previous version
                             intact — never a half-swapped tree

``<sel>`` is a 1-based occurrence number (``1`` = first match) or
``rand:<p>`` (fire with probability p, seeded). Counters are per-directive
and process-local.

Zero cost when off: plane hot paths guard every hook behind
``if faults.ACTIVE:`` — one module-attribute load on the fast path.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import threading
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# Fast-path flag: hot code does `if faults.ACTIVE:` and never touches the
# controller when no faults are armed.
ACTIVE = False
_CTL: Optional["FaultController"] = None


class _Directive:
    __slots__ = ("kind", "match", "arg", "count")

    def __init__(self, kind: str, match: str, arg: str = ""):
        self.kind = kind
        self.match = match
        self.arg = arg
        self.count = 0  # matches seen so far (process-local)

    def __repr__(self):
        return f"<{self.kind}:{self.match}:{self.arg} count={self.count}>"


class FaultController:
    """Parsed fault directives + per-directive match counters."""

    def __init__(self, spec: str, seed: int = 0, state_dir: str = ""):
        self.spec = spec
        self.rng = random.Random(seed)
        # cluster-wide exactly-once markers (kill_task ...:once) live here;
        # every process of one test run must see the same directory
        self.state_dir = state_dir or os.environ.get(
            "RAY_TPU_FAULTS_STATE", "/tmp/ray_tpu_faults"
        )
        self._lock = threading.Lock()
        self.fired: Dict[str, int] = {}
        self.directives: List[_Directive] = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            kind = fields[0]
            if kind in ("drop_reply", "dup_reply", "delay_send",
                        "delay_handler", "kill_task"):
                if len(fields) < 3:
                    raise ValueError(f"fault directive needs 3 fields: {part!r}")
                # selector may itself contain ':' (rand:<p>)
                self.directives.append(
                    _Directive(kind, fields[1], ":".join(fields[2:]))
                )
            elif kind == "blackhole":
                if len(fields) != 2:
                    raise ValueError(f"fault directive needs 2 fields: {part!r}")
                self.directives.append(_Directive(kind, fields[1]))
            elif kind in ("bulk_close", "bulk_blackhole"):
                if len(fields) < 2:
                    raise ValueError(f"fault directive needs 2 fields: {part!r}")
                # the second field IS the selector (may contain ':' — rand:<p>)
                self.directives.append(
                    _Directive(kind, "bulk", ":".join(fields[1:]))
                )
            elif kind == "kv_transfer_drop":
                if len(fields) < 2:
                    raise ValueError(f"fault directive needs 2 fields: {part!r}")
                self.directives.append(
                    _Directive(kind, "kv", ":".join(fields[1:]))
                )
            elif kind == "weight_swap_drop":
                if len(fields) < 2:
                    raise ValueError(f"fault directive needs 2 fields: {part!r}")
                self.directives.append(
                    _Directive(kind, "weight", ":".join(fields[1:]))
                )
            else:
                raise ValueError(f"unknown fault directive kind: {part!r}")

    # -- selection -------------------------------------------------------

    def _selected(self, d: _Directive) -> bool:
        """Advance the directive's match counter; True if this occurrence
        is the one the selector names. Caller holds the lock."""
        d.count += 1
        sel = d.arg
        if sel.startswith("rand:"):
            return self.rng.random() < float(sel[5:])
        return d.count == int(sel)

    def _record(self, d: _Directive):
        key = f"{d.kind}:{d.match}"
        self.fired[key] = self.fired.get(key, 0) + 1
        logger.warning("fault injected: %s (occurrence %d)", key, d.count)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.fired)

    # -- hooks (called from protocol.py / head.py / worker_main.py) ------

    def reply_action(self, msg_type) -> Optional[str]:
        """'drop' / 'dup' / None for a reply to a request of msg_type.
        EVERY matching directive's occurrence counter advances on every
        reply (no early return), so `drop_reply:t:1,drop_reply:t:2` means
        occurrences 1 AND 2 as a human would read it."""
        action = None
        with self._lock:
            for d in self.directives:
                if d.kind in ("drop_reply", "dup_reply") and d.match == msg_type:
                    if self._selected(d):
                        self._record(d)
                        if action is None:
                            action = (
                                "drop" if d.kind == "drop_reply" else "dup"
                            )
        return action

    def send_action(self, conn_name: str, msg_type):
        """'drop' (black-holed), a float delay in seconds, or None."""
        with self._lock:
            for d in self.directives:
                if d.kind == "blackhole" and d.match in ("any", conn_name):
                    self._record(d)
                    return "drop"
            delay = 0.0
            for d in self.directives:
                if d.kind == "delay_send" and d.match in ("any", msg_type):
                    self._record(d)
                    delay += float(d.arg)
        return delay or None

    def handler_delay(self, msg_type) -> float:
        delay = 0.0
        with self._lock:
            for d in self.directives:
                if d.kind == "delay_handler" and d.match == msg_type:
                    self._record(d)
                    delay += float(d.arg)
        return delay

    def bulk_action(self) -> Optional[str]:
        """'close' (drop the socket mid-stream) / 'blackhole' (no reply) /
        None, for one bulk-plane request being served."""
        action = None
        with self._lock:
            for d in self.directives:
                if d.kind in ("bulk_close", "bulk_blackhole"):
                    if self._selected(d):
                        self._record(d)
                        if action is None:
                            action = (
                                "close" if d.kind == "bulk_close" else "blackhole"
                            )
        return action

    def kv_transfer_action(self) -> Optional[str]:
        """'drop' (corrupt this cross-replica KV transfer mid-flight) or
        None, for one export being packed for the wire."""
        action = None
        with self._lock:
            for d in self.directives:
                if d.kind == "kv_transfer_drop":
                    if self._selected(d):
                        self._record(d)
                        action = "drop"
        return action

    def weight_swap_action(self) -> Optional[str]:
        """'drop' (truncate this live weight pull so verification fails
        and the swap aborts whole) or None, for one version being pulled."""
        action = None
        with self._lock:
            for d in self.directives:
                if d.kind == "weight_swap_drop":
                    if self._selected(d):
                        self._record(d)
                        action = "drop"
        return action

    def before_task(self, fn_name: str) -> None:
        """SIGKILL this process if a kill_task directive selects this
        execution. Never returns if it fires."""
        for d in self.directives:
            if d.kind != "kill_task" or d.match not in ("any", fn_name):
                continue
            if d.arg == "once":
                # cluster-wide exactly-once: first process to create the
                # marker wins; the task's RETRY (fresh worker, fresh
                # counters) must survive
                try:
                    os.makedirs(self.state_dir, exist_ok=True)
                    marker = os.path.join(
                        self.state_dir, f"killed_{d.kind}_{d.match}"
                    )
                    fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    os.close(fd)
                except FileExistsError:
                    continue
                except OSError:
                    continue
            else:
                with self._lock:
                    if not self._selected(d):
                        continue
            with self._lock:
                self._record(d)
            logger.error(
                "fault: SIGKILL pid %d before task %r", os.getpid(), fn_name
            )
            os.kill(os.getpid(), signal.SIGKILL)


def arm(spec: Optional[str] = None, seed: Optional[int] = None,
        state_dir: str = "") -> Optional[FaultController]:
    """Arm fault injection. With no args, reads RAY_TPU_FAULTS (no-op when
    unset). Returns the controller (None if nothing armed)."""
    global ACTIVE, _CTL
    if spec is None:
        spec = os.environ.get("RAY_TPU_FAULTS", "")
    if not spec.strip():
        return None
    if seed is None:
        seed = int(os.environ.get("RAY_TPU_TEST_FAULT_SEED", "0"))
    _CTL = FaultController(spec, seed=seed, state_dir=state_dir)
    ACTIVE = True
    logger.warning(
        "fault injection ARMED (pid %d): %s", os.getpid(), _CTL.directives
    )
    return _CTL


def disarm() -> None:
    global ACTIVE, _CTL
    ACTIVE = False
    _CTL = None


def controller() -> Optional[FaultController]:
    return _CTL


# -- thin hook wrappers: safe to call only when ACTIVE is true ------------


def reply_action(msg_type) -> Optional[str]:
    c = _CTL
    return c.reply_action(msg_type) if c is not None else None


def send_action(conn_name: str, msg_type):
    c = _CTL
    return c.send_action(conn_name, msg_type) if c is not None else None


def handler_delay(msg_type) -> float:
    c = _CTL
    return c.handler_delay(msg_type) if c is not None else 0.0


def before_task(fn_name: str) -> None:
    c = _CTL
    if c is not None:
        c.before_task(fn_name)


def bulk_action() -> Optional[str]:
    c = _CTL
    return c.bulk_action() if c is not None else None


def kv_transfer_action() -> Optional[str]:
    c = _CTL
    return c.kv_transfer_action() if c is not None else None


def weight_swap_action() -> Optional[str]:
    c = _CTL
    return c.weight_swap_action() if c is not None else None


# Env arming at import: worker processes import this via protocol.py at
# startup, so RAY_TPU_FAULTS set before cluster start arms every process.
arm()
