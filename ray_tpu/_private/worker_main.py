"""Worker process entrypoint.

Reference parity: python/ray/_private/workers/default_worker.py + the
execution upcall path _raylet.pyx:1791 (task_execution_handler). Spawned by
the head's worker pool; connects back over the session unix socket, registers,
then serves run_task/start_actor requests. Task bodies run on executor
threads so the protocol loop stays responsive.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import os
import sys
import threading

import cloudpickle

from . import faults, protocol
from .worker import (
    EventLoopThread,
    Worker,
    execute_and_package,
    global_worker,
)


class WorkerServer:
    def __init__(self, socket_path: str, worker_id: str, node_id: str):
        self.socket_path = socket_path
        self.worker_id = worker_id
        self.node_id = node_id
        self.conn: protocol.Connection = None  # type: ignore
        self._fn_cache = {}
        self._cls_cache = {}
        self.actor_instance = None
        self.actor_id = None
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="task-exec"
        )
        self._loop: asyncio.AbstractEventLoop = None  # type: ignore
        # task_id -> executing thread ident, for async cancellation; and
        # cancels that arrived before their task started executing (the
        # task may be queued behind another on the executor). _cancel_lock
        # serializes the async raise against task start/end on the executor
        # thread: without it, a cancel aimed at a task that just finished
        # could land in the NEXT task on the same thread
        self._task_threads: dict = {}
        self._pending_cancels: "collections.OrderedDict" = collections.OrderedDict()
        self._cancel_lock = threading.Lock()
        # task_ids dispatched to this worker but not yet (or currently)
        # executing — lets _cancel answer True for a task queued behind
        # another on the executor (it WILL be dropped) while still
        # answering False for a task this worker has never heard of.
        # Loop-thread only; no lock.
        self._inflight: set = set()

    async def _start_direct_server(self) -> str:
        """Listen for direct caller->worker task pushes (reference:
        CoreWorker's gRPC server receiving PushTask,
        direct_actor_task_submitter.h:67). Local workers use a unix socket
        in the session dir; agent-spawned workers (remote nodes) listen on
        TCP so cross-host callers can reach them."""

        async def on_peer(reader, writer):
            conn = protocol.Connection(reader, writer, self.handle)
            conn.start()

        if protocol.is_tcp_address(self.socket_path):
            from .config import GLOBAL_CONFIG as cfg
            from .head import _advertise_host

            # same bind policy as the control plane (see config.py
            # head_tcp_host): loopback-configured clusters must not expose
            # the unauthenticated task-push endpoint on all interfaces
            bind = cfg.head_tcp_host or "0.0.0.0"
            server = await asyncio.start_server(on_peer, host=bind, port=0)
            port = server.sockets[0].getsockname()[1]
            return f"{_advertise_host(bind)}:{port}"
        base = os.path.dirname(self.socket_path)
        sock_dir = os.path.join(base, "workers")
        os.makedirs(sock_dir, exist_ok=True)
        path = os.path.join(sock_dir, f"{self.worker_id}.sock")
        await asyncio.start_unix_server(on_peer, path=path)
        return path

    async def run(self):
        self._loop = asyncio.get_running_loop()
        reader, writer = await protocol.open_stream(self.socket_path)
        self.conn = protocol.Connection(reader, writer, self.handle, name="head")
        self.conn.start()

        # Wire the in-process global worker so user task code can call
        # ray_tpu.get/put/remote from inside tasks.
        io = EventLoopThread.__new__(EventLoopThread)
        io.loop = self._loop
        io.thread = threading.current_thread()
        global_worker.session_dir = os.environ.get("RAY_TPU_SESSION_DIR")
        global_worker.connect_worker(
            self.socket_path, self.worker_id, io, self.conn, node_id=self.node_id
        )

        try:
            direct_address = await self._start_direct_server()
        except Exception:
            direct_address = None
        self._direct_address = direct_address
        await self.conn.request(
            {
                "t": "register_worker",
                "proto": protocol.PROTOCOL_VERSION,
                "worker_id": self.worker_id,
                "pid": os.getpid(),
                "node_id": self.node_id,
                "direct_address": direct_address,
            }
        )
        # serve until the connection dies; on head death try to RECONNECT —
        # this process (and any actor state in it) survives a head restart
        # (reference: workers re-register via the raylet against a
        # restarted GCS, gcs_server.cc:130-178)
        while True:
            while not self.conn.closed:
                await asyncio.sleep(0.2)
            if not await self._reconnect():
                return

    async def _reconnect(self) -> bool:
        from .config import GLOBAL_CONFIG as cfg

        loop = asyncio.get_running_loop()
        deadline = loop.time() + cfg.head_reconnect_timeout_s
        while loop.time() < deadline:
            await asyncio.sleep(0.5)
            try:
                reader, writer = await protocol.open_stream(self.socket_path)
                conn = protocol.Connection(
                    reader, writer, self.handle, name="head"
                )
                conn.start()
                await conn.request(
                    {
                        "t": "register_worker",
                        "proto": protocol.PROTOCOL_VERSION,
                        "worker_id": self.worker_id,
                        "pid": os.getpid(),
                        "node_id": self.node_id,
                        "direct_address": self._direct_address,
                        "actor_id": self.actor_id,
                        "adopt": True,
                    },
                    timeout=10,
                )
            except Exception:
                continue
            self.conn = conn
            global_worker.conn = conn
            return True
        return False

    async def handle(self, msg):
        t = msg["t"]
        if t == "run_task":
            return await self._run_task(msg)
        if t == "start_actor":
            return await self._start_actor(msg)
        if t == "pub":
            global_worker.dispatch_pub(msg)
            return None
        if t == "ping":
            return "pong"
        if t == "profile":
            return await self._profile(msg)
        if t == "cancel_task":
            return self._cancel(msg["task_id"])
        if t == "shutdown":
            self._loop.call_soon(sys.exit, 0)
            return True
        raise ValueError(f"worker got unknown message {t!r}")

    async def _profile(self, msg):
        """Self-profile on demand (reference:
        dashboard/modules/reporter/profile_manager.py — py-spy/memray
        against a pid; here the worker samples itself, see
        util/profiling.py). Sampling runs on a FRESH thread so both the
        protocol loop and the task executor stay observable."""
        from ..util import profiling

        kind = msg.get("kind", "cpu")
        duration = float(msg.get("duration_s", 2.0))
        if kind == "dump":
            return profiling.stack_dump()
        if kind == "mem":
            return await asyncio.get_running_loop().run_in_executor(
                None, profiling.memory_profile, duration
            )
        interval = float(msg.get("interval_s", 0.01))
        return await asyncio.get_running_loop().run_in_executor(
            None, profiling.cpu_profile, duration, interval
        )

    def _cancel(self, task_id: str) -> bool:
        """Cancel a task on THIS worker (reference: _raylet.pyx
        execute_task_with_cancellation_handler + CoreWorker::HandleCancelTask
        — the cancellation is raised asynchronously in the thread executing
        the task). Running: raise TaskCancelledError in its thread via the
        C API. Not started yet (queued behind another task on the
        executor): remember the id so _execute drops it before user code
        runs."""
        import ctypes

        from ..exceptions import TaskCancelledError

        from .worker import _flag_bounded

        with self._cancel_lock:
            ident = self._task_threads.get(task_id)
            if ident is None:
                _flag_bounded(self._pending_cancels, task_id)
                # dispatched-but-not-started: the flag guarantees _execute
                # drops it before user code runs — a successful cancel, but
                # NOT an executing task: report "queued" so the head's
                # force path counts it as done WITHOUT killing the worker
                # (a kill would take down the unrelated task currently on
                # the executor thread). Unknown tasks report False so the
                # caller can chase elsewhere.
                return "queued" if task_id in self._inflight else False
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(ident), ctypes.py_object(TaskCancelledError)
            )
            return "executing"

    @staticmethod
    def _cancelled_reply(task_id: str, return_ids):
        from . import serialization
        from ..exceptions import TaskCancelledError

        env = serialization.serialize(
            TaskCancelledError(f"task {task_id} was cancelled")
        )
        env.is_error = True
        return {"results": [env for _ in return_ids] or [env]}

    def _execute(self, task_id: str, return_ids, body):
        """Run a task body on the executor thread with cancellation
        bookkeeping: short-circuit tasks cancelled before they started,
        register the executing thread for the async raise, and CLEAR any
        still-pending async exception afterwards so a cancel that lands
        between task end and deregistration cannot escape into the
        executor pool and kill its thread."""
        import ctypes

        from ..exceptions import TaskCancelledError

        ident = threading.get_ident()
        with self._cancel_lock:
            # a cancel that fired in the narrow window after its task's
            # body returned can escape past the finally below (the work
            # item catches it): purge any stale registration left on THIS
            # thread and clear a still-pending stray exc before running
            # new user code
            for stale_tid, stale_ident in list(self._task_threads.items()):
                if stale_ident == ident:
                    self._task_threads.pop(stale_tid, None)
            ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(ident), None)
            if task_id in self._pending_cancels:
                self._pending_cancels.pop(task_id, None)
                return self._cancelled_reply(task_id, return_ids)
            self._task_threads[task_id] = ident
        try:
            return body()
        except TaskCancelledError:
            # the async raise usually lands inside the user function and is
            # packaged by execute_and_package; this catches the rare landing
            # in the result-packaging window
            return self._cancelled_reply(task_id, return_ids)
        finally:
            with self._cancel_lock:
                self._task_threads.pop(task_id, None)
                self._pending_cancels.pop(task_id, None)
                # clear a set-but-unfired async exc so it cannot escape
                # into the pool and kill the thread between tasks
                ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(ident), None)

    async def _fetch_blob(self, ns: str, key: str, cache: dict):
        if key in cache:
            return cache[key]
        blob = await self.conn.request({"t": "kv_get", "ns": ns, "key": key})
        if blob is None:
            raise RuntimeError(f"function/class {key} not found in KV")
        # unpickle OFF the protocol loop: loads() may import heavy modules
        # (jax etc.), and a blocked loop can't answer health-check pings
        obj = await self._loop.run_in_executor(self._executor, cloudpickle.loads, blob)
        cache[key] = obj
        return obj

    async def _start_actor(self, msg):
        cls = await self._fetch_blob("cls", msg["cls_key"], self._cls_cache)
        self.actor_id = msg["actor_id"]
        max_concurrency = msg.get("max_concurrency", 1)
        if max_concurrency != 1:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=max_concurrency, thread_name_prefix="actor-exec"
            )

        def _init():
            from .worker import resolve_task_args

            args, kwargs = resolve_task_args(msg["args"])
            self.actor_instance = cls(*args, **kwargs)
            global_worker.current_actor = self.actor_instance
            global_worker.current_actor_id = self.actor_id

        await self._loop.run_in_executor(self._executor, _init)
        return True

    async def _run_task(self, msg):
        self._inflight.add(msg["task_id"])
        try:
            return await self._run_task_inner(msg)
        finally:
            self._inflight.discard(msg["task_id"])

    async def _run_task_inner(self, msg):
        from ..util import tracing

        if "actor_id" in msg and msg.get("actor_id"):
            method_name = msg["method"]
            if faults.ACTIVE:
                # chaos hook: SIGKILL at the task boundary — after dispatch
                # (the head believes the task is running) but before user
                # code, the exact window task retry must cover
                faults.before_task(method_name)

            def _call():
                global_worker.current_task_id = msg["task_id"]
                inst = self.actor_instance
                if inst is None:
                    raise RuntimeError("actor not initialized")
                if method_name == "__ray_terminate__":
                    self._loop.call_soon_threadsafe(self._loop.call_later, 0.05, sys.exit, 0)
                    return {"results": []}
                fn = getattr(inst, method_name)
                with tracing.span_for_execution(
                    f"actor_method.{method_name}", msg.get("trace_ctx"),
                    task_id=msg["task_id"], actor_id=msg["actor_id"],
                ):
                    return execute_and_package(
                        fn, method_name, msg["args"], msg["return_ids"], pin_results=True
                    )

            return await self._loop.run_in_executor(
                self._executor,
                lambda: self._execute(msg["task_id"], msg["return_ids"], _call),
            )
        fn = await self._fetch_blob("fn", msg["fn_key"], self._fn_cache)
        if faults.ACTIVE:
            faults.before_task(getattr(fn, "__name__", "task"))

        def _run():
            global_worker.current_task_id = msg["task_id"]
            name = getattr(fn, "__name__", "task")
            with tracing.span_for_execution(
                f"task.{name}", msg.get("trace_ctx"), task_id=msg["task_id"]
            ):
                return execute_and_package(
                    fn, name, msg["args"], msg["return_ids"],
                    streaming=msg.get("streaming", False),
                )

        return await self._loop.run_in_executor(
            self._executor,
            lambda: self._execute(msg["task_id"], msg["return_ids"], _run),
        )


def main():
    # local workers get the session unix socket; agent-spawned workers on
    # remote nodes dial the head's TCP address directly
    address = os.environ.get("RAY_TPU_SOCKET") or os.environ["RAY_TPU_ADDRESS"]
    worker_id = os.environ["RAY_TPU_WORKER_ID"]
    node_id = os.environ["RAY_TPU_NODE_ID"]
    server = WorkerServer(address, worker_id, node_id)
    try:
        asyncio.run(server.run())
    except (KeyboardInterrupt, ConnectionError):
        pass


if __name__ == "__main__":
    main()
