"""Option validation and resource resolution.

Reference parity: python/ray/_private/ray_option_utils.py.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

_COMMON_OPTIONS = {
    "num_cpus",
    "num_tpus",
    "num_gpus",
    "resources",
    "name",
    "num_returns",
    "max_retries",
    "max_restarts",
    "max_task_retries",
    "max_concurrency",
    "scheduling_strategy",
    "namespace",
    "lifetime",
    "runtime_env",
    "memory",
}


def validate_options(opts: Dict[str, Any]):
    unknown = set(opts) - _COMMON_OPTIONS
    if unknown:
        raise ValueError(f"Unknown options: {sorted(unknown)}")
    if "resources" in opts and opts["resources"] is not None:
        res = opts["resources"]
        if not isinstance(res, dict):
            raise TypeError("resources must be a dict")
        for k in ("CPU", "TPU", "GPU"):
            if k in res:
                raise ValueError(
                    f"Use num_{k.lower()}s instead of resources={{'{k}': ...}}"
                )
    return opts


def resolve_task_resources(opts: Dict[str, Any], is_actor: bool) -> Dict[str, float]:
    res: Dict[str, float] = {}
    num_cpus = opts.get("num_cpus")
    if num_cpus is None:
        # tasks default to 1 CPU; actors to 0 (they mostly wait on I/O or own
        # the TPU explicitly) — matches the reference's defaults.
        num_cpus = 0 if is_actor else 1
    if num_cpus:
        res["CPU"] = float(num_cpus)
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus"):
        res["GPU"] = float(opts["num_gpus"])
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    for k, v in (opts.get("resources") or {}).items():
        res[k] = float(v)
    return res
