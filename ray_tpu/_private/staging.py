"""Runtime-env staging: copy working_dir / py_modules into a session-owned
directory, keyed by a cheap content signature so identical envs share one
copy. Used by the head (local worker spawns, job submission) and by node
agents (remote worker spawns). Reference parity:
_private/runtime_env/working_dir.py + the per-node runtime-env agent
(runtime_env_agent.py:161), collapsed to a copy-on-spawn helper.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import threading


def stage_package(base_dir: str, name: str) -> str:
    """Extract an uploaded zip package (REST `PUT /api/packages/pkg/<name>`,
    stored at base_dir/packages/<name>) into runtime_resources, keyed by the
    zip's content hash, and return the extracted directory. Reference parity:
    _private/runtime_env/packaging.py download_and_unpack_package — ours
    reads the head-local package store instead of GCS object storage."""
    import zipfile

    pkg_path = os.path.join(base_dir, "packages", name)
    if not os.path.isfile(pkg_path):
        raise ValueError(f"no such uploaded package {name!r}")
    h = hashlib.sha1()
    with open(pkg_path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    dest = os.path.join(base_dir, "runtime_resources", "pkg-" + h.hexdigest()[:16])
    if not os.path.exists(dest):
        tmp = f"{dest}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            with zipfile.ZipFile(pkg_path) as zf:
                for info in zf.infolist():
                    # refuse path traversal (absolute paths / ..)
                    p = os.path.normpath(info.filename)
                    if p.startswith("..") or os.path.isabs(p):
                        raise ValueError(f"unsafe path in package: {info.filename!r}")
                zf.extractall(tmp)
            os.rename(tmp, dest)
        except OSError:
            if not os.path.exists(dest):
                raise
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return dest


def stage_into(base_dir: str, src: str) -> str:
    """Copy `src` (dir or file) under base_dir/runtime_resources/<sig>/ and
    return the staged path. Concurrent stages of the same content are safe:
    copy to a temp path, then atomically rename.

    `pkg://<name>` sources resolve against the session's uploaded-package
    store (Job REST API working-dir upload)."""
    if src.startswith("pkg://"):
        return stage_package(base_dir, src[len("pkg://"):])
    h = hashlib.sha1(src.encode())
    for root, _dirs, files in os.walk(src):
        for f in sorted(files):
            p = os.path.join(root, f)
            try:
                st = os.stat(p)
                h.update(f"{os.path.relpath(p, src)}:{st.st_size}:{st.st_mtime_ns}".encode())
            except OSError:
                continue
    if os.path.isfile(src):
        st = os.stat(src)
        h.update(f"{st.st_size}:{st.st_mtime_ns}".encode())
    dest = os.path.join(
        base_dir, "runtime_resources", h.hexdigest()[:16], os.path.basename(src)
    )
    if not os.path.exists(dest):
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        tmp = f"{dest}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            if os.path.isdir(src):
                shutil.copytree(src, tmp)
            else:
                shutil.copy2(src, tmp)
            os.rename(tmp, dest)
        except OSError:
            if not os.path.exists(dest):
                raise
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return dest
