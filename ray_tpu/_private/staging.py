"""Runtime-env staging: copy working_dir / py_modules into a session-owned
directory, keyed by a cheap content signature so identical envs share one
copy. Used by the head (local worker spawns, job submission) and by node
agents (remote worker spawns). Reference parity:
_private/runtime_env/working_dir.py + the per-node runtime-env agent
(runtime_env_agent.py:161), collapsed to a copy-on-spawn helper.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import threading


def stage_into(base_dir: str, src: str) -> str:
    """Copy `src` (dir or file) under base_dir/runtime_resources/<sig>/ and
    return the staged path. Concurrent stages of the same content are safe:
    copy to a temp path, then atomically rename."""
    h = hashlib.sha1(src.encode())
    for root, _dirs, files in os.walk(src):
        for f in sorted(files):
            p = os.path.join(root, f)
            try:
                st = os.stat(p)
                h.update(f"{os.path.relpath(p, src)}:{st.st_size}:{st.st_mtime_ns}".encode())
            except OSError:
                continue
    if os.path.isfile(src):
        st = os.stat(src)
        h.update(f"{st.st_size}:{st.st_mtime_ns}".encode())
    dest = os.path.join(
        base_dir, "runtime_resources", h.hexdigest()[:16], os.path.basename(src)
    )
    if not os.path.exists(dest):
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        tmp = f"{dest}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            if os.path.isdir(src):
                shutil.copytree(src, tmp)
            else:
                shutil.copy2(src, tmp)
            os.rename(tmp, dest)
        except OSError:
            if not os.path.exists(dest):
                raise
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return dest
