"""Binary IDs for jobs/tasks/actors/objects.

Reference parity: src/ray/common/id.h / id_def.h define JobID(4B), ActorID(16B),
TaskID(24B), ObjectID(28B) with embedded parent structure. We keep the same
byte-size scheme so IDs sort/compose the same way, but generation is pure
Python (the hot path here is orchestration, not per-op compute, which on TPU
lives inside a single compiled XLA program).
"""

from __future__ import annotations

import os
import threading

_JOB_ID_SIZE = 4
_ACTOR_ID_UNIQUE_BYTES = 12
_TASK_ID_UNIQUE_BYTES = 8
_OBJECT_ID_INDEX_BYTES = 4

_rng_lock = threading.Lock()


def _random_bytes(n: int) -> bytes:
    with _rng_lock:
        return os.urandom(n)


class BaseID:
    __slots__ = ("_binary",)
    SIZE = 0

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} must be {self.SIZE} bytes, got {len(binary)}"
            )
        self._binary = binary

    @classmethod
    def from_random(cls):
        return cls(_random_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __hash__(self):
        return hash(self._binary)

    def __eq__(self, other):
        return type(self) is type(other) and self._binary == other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(_JOB_ID_SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._binary, "little")


class ActorID(BaseID):
    # unique bytes + job id, mirroring id.h's ActorID layout.
    SIZE = _ACTOR_ID_UNIQUE_BYTES + _JOB_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_random_bytes(_ACTOR_ID_UNIQUE_BYTES) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[_ACTOR_ID_UNIQUE_BYTES:])


class TaskID(BaseID):
    SIZE = _TASK_ID_UNIQUE_BYTES + ActorID.SIZE

    @classmethod
    def for_task(cls, job_id: JobID) -> "TaskID":
        return cls(_random_bytes(_TASK_ID_UNIQUE_BYTES) + ActorID.nil().binary()[:_ACTOR_ID_UNIQUE_BYTES] + job_id.binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_random_bytes(_TASK_ID_UNIQUE_BYTES) + actor_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._binary[_TASK_ID_UNIQUE_BYTES:])


class ObjectID(BaseID):
    """ObjectID = TaskID + little-endian return index (object_id.h scheme)."""

    SIZE = TaskID.SIZE + _OBJECT_ID_INDEX_BYTES

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(_OBJECT_ID_INDEX_BYTES, "little"))

    @classmethod
    def from_put(cls, job_id: JobID) -> "ObjectID":
        return cls.for_return(TaskID.for_task(job_id), 0)

    def task_id(self) -> TaskID:
        return TaskID(self._binary[: TaskID.SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._binary[TaskID.SIZE:], "little")


class NodeID(BaseID):
    SIZE = 28


class WorkerID(BaseID):
    SIZE = 28


class PlacementGroupID(BaseID):
    SIZE = 14 + _JOB_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(_random_bytes(14) + job_id.binary())
