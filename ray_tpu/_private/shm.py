"""Python client for the C++ shared-memory object store (cpp/shm_store.cc).

Builds the .so on first use (g++ is a baked dependency), loads it via
ctypes, and exposes zero-copy create/get as memoryviews that numpy/jax wrap
without copies. Reference parity: CoreWorkerPlasmaStoreProvider
(plasma_store_provider.h:88) on the client side.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass
from typing import Optional

_CPP_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "cpp")
_LIB_PATH = os.path.abspath(os.path.join(_CPP_DIR, "libshm_store.so"))
_build_lock = threading.Lock()
_lib = None


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < os.path.getmtime(
            os.path.join(_CPP_DIR, "shm_store.cc")
        ):
            subprocess.run(
                ["make", "-s", "-C", os.path.abspath(_CPP_DIR)],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.shm_store_connect.restype = ctypes.c_void_p
        lib.shm_store_connect.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.shm_store_create.restype = ctypes.c_void_p
        lib.shm_store_create.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
        ]
        lib.shm_store_get.restype = ctypes.c_void_p
        lib.shm_store_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.shm_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p]
        lib.shm_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_store_evict.restype = ctypes.c_int64
        lib.shm_store_evict.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.shm_store_used.restype = ctypes.c_int64
        lib.shm_store_used.argtypes = [ctypes.c_void_p]
        lib.shm_store_capacity.restype = ctypes.c_int64
        lib.shm_store_capacity.argtypes = [ctypes.c_void_p]
        lib.shm_store_disconnect.argtypes = [ctypes.c_void_p]
        lib.shm_store_destroy.argtypes = [ctypes.c_char_p]
        lib.shm_store_pretouch.restype = ctypes.c_int64
        lib.shm_store_pretouch.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.shm_store_spill_pinned.restype = ctypes.c_int64
        lib.shm_store_spill_pinned.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
        ]
        _lib = lib
    return _lib


@dataclass
class ShmBufferRef:
    """Picklable handle to a shared-memory buffer (travels in envelopes).

    `node` is the cluster node whose local shm plane holds the primary copy
    ("" = head node); consumers on other nodes pull through the head
    (serialization.materialize)."""

    name: str
    size: int
    node: str = ""


_COPY_POOL = None
_COPY_POOL_LOCK = threading.Lock()
_PARALLEL_COPY_MIN = 32 << 20  # below this, thread fan-out costs more than it saves


def _reset_copy_pool_after_fork():
    """A forked child inherits the pool object but NOT its threads;
    submitting to it would queue work nobody drains (silent hang). The
    lock is replaced too — a fork while another thread held it would
    leave the child's copy permanently locked."""
    global _COPY_POOL, _COPY_POOL_LOCK
    _COPY_POOL = None
    _COPY_POOL_LOCK = threading.Lock()


os.register_at_fork(after_in_child=_reset_copy_pool_after_fork)


def _copy_chunk(ptr: int, data: memoryview, off: int, n: int) -> None:
    chunk = data[off : off + n]
    try:
        # zero-copy source view when the buffer is writable & contiguous
        src: object = (ctypes.c_char * n).from_buffer(chunk)
        ctypes.memmove(ptr + off, src, n)
        del src
    except (TypeError, BufferError):
        # read-only source (e.g. np.frombuffer views): numpy copies
        # straight into the mapping — no intermediate bytes object
        import numpy as np

        dst = np.ctypeslib.as_array((ctypes.c_ubyte * n).from_address(ptr + off))
        np.copyto(dst, np.frombuffer(chunk, dtype=np.uint8))


def _copy_into(ptr: int, data: memoryview, size: int) -> None:
    """Copy into the shm mapping, fanning large copies across threads —
    memmove/numpy copies release the GIL, so on multicore hosts the put
    path runs at aggregate memory bandwidth instead of one core's
    (reference: plasma clients get the same effect from parallel client
    processes writing disjoint objects)."""
    if data.itemsize != 1 or data.ndim != 1:
        # chunk offsets are BYTE offsets: flatten to a byte view first or
        # element-indexed slicing would copy the wrong regions
        data = data.cast("B")
    workers = min(8, os.cpu_count() or 1)
    if size < _PARALLEL_COPY_MIN or workers < 2:
        _copy_chunk(ptr, data, 0, size)
        return
    global _COPY_POOL
    with _COPY_POOL_LOCK:
        if _COPY_POOL is None:
            from concurrent.futures import ThreadPoolExecutor

            _COPY_POOL = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="shm-copy"
            )
    per = -(-size // workers)
    per += (-per) % (1 << 20)  # 1MB-align chunk boundaries
    futures = [
        _COPY_POOL.submit(_copy_chunk, ptr, data, off, min(per, size - off))
        for off in range(0, size, per)
    ]
    try:
        for f in futures:
            f.result()
    except BaseException:
        # one chunk failed: the caller will abandon the mapping, so NO
        # thread may still be writing into it (use-after-free) — cancel
        # what hasn't started and wait out what has
        from concurrent.futures import wait as _fwait

        for f in futures:
            f.cancel()
        _fwait(futures)
        raise


def _release_mapping(lib, handle, name_bytes, ptr):
    try:
        lib.shm_store_release(handle, name_bytes, ptr)
    except Exception:
        pass


def connect_for_session(session_dir: str):
    """Shared lazy-connect helper (head + workers): returns a ShmClient for
    the session, or None if disabled/unavailable. RAY_TPU_SHM_SESSION
    overrides the session name — agents give each node its own namespace so
    the per-node planes stay distinct even when tests colocate nodes on one
    machine."""
    from .config import GLOBAL_CONFIG as cfg

    session = os.environ.get("RAY_TPU_SHM_SESSION") or (
        os.path.basename(session_dir) if session_dir else ""
    )
    if not cfg.shm_store_enabled or not session:
        return None
    try:
        return ShmClient(session, cfg.shm_store_bytes)
    except Exception:
        return None


def attach_peer_plane(session: str) -> Optional["ShmClient"]:
    """Attach to ANOTHER node's shm plane when it lives on this machine
    (colocated test clusters, multi-agent hosts). shm_store_connect creates
    the store if missing, so probe the control segment first — blindly
    attaching to a dead peer would materialize a fresh empty store and mask
    the miss. Returns None when the peer plane is not on this host."""
    from .config import GLOBAL_CONFIG as cfg

    if not cfg.shm_store_enabled or not session:
        return None
    if not os.path.exists(f"/dev/shm/rtpu_{session}_ctrl"):
        return None
    try:
        return ShmClient(session, cfg.shm_store_bytes)
    except Exception:
        return None


class PendingBuffer:
    """An unsealed shm allocation exposing a writable view, so consumers can
    recv_into the destination slab directly (zero intermediate copy). Must
    end in commit() or abort(): unsealed objects are never LRU-evictable, so
    an abandoned mapping would leak capacity forever — a weakref finalizer
    aborts as a safety net if the owner drops the object without deciding."""

    __slots__ = (
        "_client", "name", "size", "_ptr", "view", "_done", "_finalizer",
        "__weakref__",
    )

    def __init__(self, client: "ShmClient", name: str, size: int, ptr: int):
        import weakref

        self._client = client
        self.name = name
        self.size = size
        self._ptr = ptr
        self.view = (
            memoryview((ctypes.c_char * size).from_address(ptr)).cast("B")
            if size
            else memoryview(bytearray(0))
        )
        self._done = False
        self._finalizer = weakref.finalize(
            self, _abort_pending, client.lib, client.handle, name.encode(), ptr
        )

    def commit(self) -> ShmBufferRef:
        if self._done:
            raise RuntimeError(f"pending buffer {self.name} already finished")
        self._done = True
        self._finalizer.detach()  # the sealed object must survive our GC
        self.view = memoryview(b"")  # drop the writable alias before sealing
        self._client.lib.shm_store_seal(self._client.handle, self.name.encode())
        self._client.lib.shm_store_release(
            self._client.handle, self.name.encode(), self._ptr
        )
        return ShmBufferRef(name=self.name, size=self.size)

    def abort(self) -> None:
        if self._done:
            return
        self._done = True
        self._finalizer.detach()
        self.view = memoryview(b"")
        _abort_pending(
            self._client.lib, self._client.handle, self.name.encode(), self._ptr
        )


def _abort_pending(lib, handle, name_bytes, ptr):
    """Release + delete an unsealed allocation (idempotent: delete of a
    missing/other-generation name is a no-op in the store)."""
    try:
        lib.shm_store_release(handle, name_bytes, ptr)
        lib.shm_store_delete(handle, name_bytes)
    except Exception:
        pass


class ShmClient:
    def __init__(self, session: str, capacity_bytes: int):
        self.session = session
        self.lib = _load_lib()
        self.handle = self.lib.shm_store_connect(session.encode(), capacity_bytes)
        if not self.handle:
            raise OSError("failed to connect to shm store")
        # node-local spill directory for pinned (lineage-free) objects under
        # memory pressure (reference: local_object_manager.h:110 spilling)
        from .config import GLOBAL_CONFIG as cfg

        self.spill_dir = os.path.join(cfg.session_dir_root, "spill", session)

    def _spill_file(self, name: str) -> str:
        return os.path.join(self.spill_dir, f"{name}.bin")

    def get_or_spilled(self, name: str) -> Optional[memoryview]:
        """Resolve a buffer from shm, falling back to its spill file — THE
        read path for every consumer (materialize, head fetch, agent fetch)
        so spill semantics can't diverge between them."""
        mv = self.get(ShmBufferRef(name=name, size=0))
        return mv if mv is not None else self.read_spilled(name)

    def read_spilled(self, name: str) -> Optional[memoryview]:
        """Zero-copy mmap of a spilled object's file (None if not spilled)."""
        import mmap as _mmap

        try:
            with open(self._spill_file(name), "rb") as f:
                size = os.fstat(f.fileno()).st_size
                if size == 0:
                    return memoryview(b"")
                mapped = _mmap.mmap(f.fileno(), size, access=_mmap.ACCESS_READ)
                return memoryview(mapped)
        except OSError:
            return None

    def create(
        self, name: str, data: memoryview | bytes, pin: bool = False
    ) -> Optional[ShmBufferRef]:
        """Copy `data` into a new sealed shm object. Returns None when the
        store is full even after LRU eviction of unpinned sealed objects —
        evicted ids are reconstructible from lineage (head.py), which is
        what makes producer-side eviction safe; `pin=True` marks data with
        NO lineage (ray.put) as never-evictable."""
        if self.handle is None:
            return None  # disconnected (shutdown): treat as store-full
        data = memoryview(data)
        size = data.nbytes
        ptr = self._alloc(name, size, pin)
        if not ptr:
            return None
        try:
            _copy_into(ptr, data, size)
        except BaseException:
            # an unsealed object is never LRU-evictable: without cleanup a
            # failed copy would leak its capacity forever
            self.lib.shm_store_release(self.handle, name.encode(), ptr)
            self.delete(name)
            raise
        self.lib.shm_store_seal(self.handle, name.encode())
        self.lib.shm_store_release(self.handle, name.encode(), ptr)
        return ShmBufferRef(name=name, size=size)

    def _alloc(self, name: str, size: int, pin: bool) -> Optional[int]:
        """Allocate an unsealed mapping, retrying through the LRU-evict /
        spill-pinned chain (plasma eviction contract: the head reconstructs
        evicted ids on demand; pinned lineage-free data spills to disk)."""
        ptr = self.lib.shm_store_create(self.handle, name.encode(), size, int(pin))
        if not ptr:
            want = max(size * 2, 1 << 20)
            if self.lib.shm_store_evict(self.handle, want) > 0:
                ptr = self.lib.shm_store_create(
                    self.handle, name.encode(), size, int(pin)
                )
            if not ptr:
                os.makedirs(self.spill_dir, exist_ok=True)
                if self.lib.shm_store_spill_pinned(
                    self.handle, want, self.spill_dir.encode()
                ) > 0:
                    ptr = self.lib.shm_store_create(
                        self.handle, name.encode(), size, int(pin)
                    )
        return ptr or None

    def create_uninitialized(
        self, name: str, size: int, pin: bool = False
    ) -> Optional[PendingBuffer]:
        """Allocate an UNSEALED buffer and hand back a writable view, so the
        bulk plane can recv_into the destination slab directly (the ≤1-copy
        pull path). The caller must commit() (seal, making it readable) or
        abort() (free the capacity). Returns None when the store is full
        even after eviction/spill, like create()."""
        if self.handle is None:
            return None
        ptr = self._alloc(name, size, pin)
        if not ptr:
            return None
        return PendingBuffer(self, name, size, ptr)

    def get(self, ref: ShmBufferRef) -> Optional[memoryview]:
        """Map a sealed object read-only, zero-copy. The mapping is unmapped
        and its pin dropped automatically when the last view dies (weakref
        finalizer on the backing ctypes buffer)."""
        if self.handle is None:
            return None  # disconnected (shutdown)
        import weakref

        size_out = ctypes.c_int64(0)
        ptr = self.lib.shm_store_get(self.handle, ref.name.encode(), ctypes.byref(size_out))
        if not ptr:
            return None
        buf = (ctypes.c_char * size_out.value).from_address(ptr)
        weakref.finalize(
            buf, _release_mapping, self.lib, self.handle, ref.name.encode(), ptr
        )
        # read-only: the page is PROT_READ; a writable view would SIGSEGV on
        # write instead of raising (numpy arrays unpickled from this buffer
        # correctly come out non-writeable, like the reference's plasma gets)
        return memoryview(buf).toreadonly()

    def delete(self, name: str):
        if self.handle is None:
            return  # disconnected (shutdown): late frees are no-ops
        self.lib.shm_store_delete(self.handle, name.encode())
        try:
            os.unlink(self._spill_file(name))
        except OSError:
            pass

    def used(self) -> int:
        if self.handle is None:
            return 0
        return self.lib.shm_store_used(self.handle)

    def capacity(self) -> int:
        if self.handle is None:
            return 0
        return self.lib.shm_store_capacity(self.handle)

    def evict(self, nbytes: int) -> int:
        if self.handle is None:
            return 0
        return self.lib.shm_store_evict(self.handle, nbytes)

    def pretouch_async(self):
        """Fault in the whole slab from a daemon thread (one caller per
        machine — the head does this at startup) so producers never pay
        first-touch zero-fill during puts. Skipped on single/dual-core
        hosts where the background faulting would contend with foreground
        work; there the allocator's warm-page reuse carries the load."""
        if (os.cpu_count() or 1) < 4:
            return
        handle = self.handle

        def _touch():
            try:
                if self.handle is not None:
                    # commit at most a 256MB prefix: enough for steady-state
                    # puts to stay warm without eagerly pinning the whole
                    # capacity in RAM on every node
                    self.lib.shm_store_pretouch(handle, 256 * 1024 * 1024)
            except Exception:
                pass

        threading.Thread(target=_touch, name="shm-pretouch", daemon=True).start()

    def disconnect(self):
        # The C handle is intentionally NOT freed: outstanding mapping
        # finalizers (weakref on ctypes buffers) may still call
        # shm_store_release with it after disconnect. One control-block mmap
        # per process leaks until exit — bounded and harmless.
        self.handle = None

    @staticmethod
    def destroy(session: str):
        """Remove the control segment AND sweep any leftover data segments
        (objects still referenced by crashed/leaked handles) + spill files."""
        _load_lib().shm_store_destroy(session.encode())
        import glob
        import shutil

        for path in glob.glob(f"/dev/shm/rtpu_{session}_*"):
            try:
                os.unlink(path)
            except OSError:
                pass
        from .config import GLOBAL_CONFIG as cfg

        shutil.rmtree(
            os.path.join(cfg.session_dir_root, "spill", session), ignore_errors=True
        )
