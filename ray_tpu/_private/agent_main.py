"""Node-agent process entrypoint: `python -m ray_tpu._private.agent_main`.

Reference parity: the raylet main (src/ray/raylet/main.cc) — joins an
existing cluster at --address and serves until the head connection drops.
"""

from __future__ import annotations

import argparse
import asyncio
import json

from .agent import Agent


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument("--node-id", required=True)
    p.add_argument("--resources", default="{}", help="JSON resource map")
    p.add_argument("--labels", default="{}", help="JSON label map")
    args = p.parse_args()
    agent = Agent(
        args.address,
        args.node_id,
        {k: float(v) for k, v in json.loads(args.resources).items()},
        json.loads(args.labels),
    )
    try:
        asyncio.run(agent.run())
    except (KeyboardInterrupt, ConnectionError):
        pass


if __name__ == "__main__":
    main()
