"""Helpers for spawning `-S` child interpreters.

Children skip `site` (hooks can be arbitrarily slow, pin the wrong jax
backend, or hang outright on a dead TPU tunnel), so the parent's sys.path
must ride down via PYTHONPATH. One implementation — the merge rules used
to be hand-rolled at every spawn site and drifted.
"""

from __future__ import annotations

import os
import sys
from typing import Optional, Sequence


def child_pythonpath(
    prefix_paths: Sequence[str] = (),
    inherited: Optional[str] = None,
    inherited_last: bool = False,
) -> str:
    """PYTHONPATH for a `-S` child: explicit prefixes first (staged dirs,
    repo roots), then any inherited/user PYTHONPATH, then this process's
    full sys.path (site-packages included — the child skips `site`).

    inherited_last=True puts the user's PYTHONPATH AFTER sys.path instead:
    used where the cluster's own packages must win over user paths (job
    drivers must never import a stale vendored ray_tpu over the cluster's).
    """
    parts = [p for p in prefix_paths if p]
    if inherited and not inherited_last:
        parts.append(inherited)
    parts.extend(p for p in sys.path if p)
    if inherited and inherited_last:
        parts.append(inherited)
    return os.pathsep.join(parts)
