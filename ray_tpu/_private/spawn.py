"""Helpers for spawning `-S` child interpreters.

Children skip `site` (hooks can be arbitrarily slow, pin the wrong jax
backend, or hang outright on a dead TPU tunnel), so the parent's sys.path
must ride down via PYTHONPATH. One implementation — the merge rules used
to be hand-rolled at every spawn site and drifted.
"""

from __future__ import annotations

import os
import sys
from typing import Optional, Sequence


def child_pythonpath(
    prefix_paths: Sequence[str] = (), inherited: Optional[str] = None
) -> str:
    """PYTHONPATH for a `-S` child: explicit prefixes first (staged dirs,
    the framework root), then any inherited/user PYTHONPATH (keeping its
    normal precedence over site-packages), then this process's full
    sys.path (site-packages included — the child skips `site`)."""
    parts = [p for p in prefix_paths if p]
    if inherited:
        parts.append(inherited)
    parts.extend(p for p in sys.path if p)
    return os.pathsep.join(parts)


def framework_root() -> str:
    """The directory containing the ray_tpu package — prefixed where the
    cluster's OWN code must win over user paths (job drivers)."""
    import ray_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
