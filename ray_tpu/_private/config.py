"""Flag/config system.

Reference parity: src/ray/common/ray_config_def.h — a table of typed,
env-overridable flags (RAY_<name>). Here: one dataclass-like registry,
overridable via RAY_TPU_<NAME> env vars and `init(_system_config=...)`.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict


class _Flag:
    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name: str, type_: Callable, default: Any, doc: str = ""):
        self.name = name
        self.type = type_
        self.default = default
        self.doc = doc


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("1", "true", "yes", "on")


class Config:
    """Global config registry. Values resolve in order:
    programmatic override > RAY_TPU_<NAME> env var > default."""

    _FLAGS: Dict[str, _Flag] = {}

    def __init__(self):
        self._overrides: Dict[str, Any] = {}

    @classmethod
    def define(cls, name: str, type_: Callable, default: Any, doc: str = ""):
        cls._FLAGS[name] = _Flag(name, type_, default, doc)

    def get(self, name: str):
        flag = self._FLAGS[name]
        if name in self._overrides:
            return self._overrides[name]
        env_name = "RAY_TPU_" + name.upper()
        if env_name in os.environ:
            raw = os.environ[env_name]
            if flag.type is bool:
                return _parse_bool(raw)
            return flag.type(raw)
        return flag.default

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self.get(name)
        except KeyError:
            raise AttributeError(name) from None

    def apply(self, overrides: Dict[str, Any]):
        for k, v in overrides.items():
            if k not in self._FLAGS:
                raise ValueError(f"Unknown config flag: {k}")
            self._overrides[k] = self._FLAGS[k].type(v) if not isinstance(v, bool) else v

    def snapshot(self) -> Dict[str, Any]:
        return {k: self.get(k) for k in self._FLAGS}

    def to_json(self) -> str:
        return json.dumps(self.snapshot())


D = Config.define
# --- core runtime ---
D("raylet_heartbeat_period_ms", int, 1000, "worker->head heartbeat period")
D("health_check_period_ms", int, 5000, "head-side liveness probe period")
D("health_check_failure_threshold", int, 24,
  "consecutive failed probes before a worker/node is declared dead (~2min "
  "with the default period: long GIL-holding stretches — jax traces and "
  "XLA compiles on loaded hosts — must not look like hangs)")
D("worker_register_timeout_s", float, 30.0, "max wait for a spawned worker to register")
D("task_retry_delay_ms", int, 100, "delay before retrying a failed task")
D("max_pending_lease_requests", int, 1024)
D("object_inline_limit_bytes", int, 128 * 1024, "objects <= this ride the control socket; larger go to shm")
D("fetch_chunk_bytes", int, 16 * 1024 * 1024,
  "chunk size for node-to-node buffer pulls (object_manager.h chunked "
  "transfer analogue); bounds per-message memory on the bulk plane")
D("bulk_stripe_sockets", int, 4,
  "parallel sockets a large bulk pull stripes across (READ_RANGE fan-out); "
  "1 disables striping")
D("bulk_stripe_min_bytes", int, 64 * 1024 * 1024,
  "buffers at or above this size stripe across bulk_stripe_sockets; "
  "smaller buffers ride one socket (pipelined for multi-buffer pulls)")
D("bulk_same_host", bool, True,
  "when a peer node's shm plane lives on THIS machine (colocated test "
  "clusters, multi-agent hosts), attach it directly and copy slab-to-slab "
  "instead of going through TCP")
D("bulk_read_timeout_s", float, 120.0,
  "blocking-socket timeout for bulk-plane pulls; a blackholed/dead peer "
  "surfaces as a timeout and the pull falls back to the head relay")
D("shm_store_bytes", int, 2 * 1024**3, "capacity of the C++ shared-memory object store")
D("shm_store_enabled", bool, True)
D("get_poll_timeout_s", float, 0.2)
D("actor_restart_delay_ms", int, 100)
D("worker_pool_prestart", int, 0, "workers to prestart per node at init")
D("direct_actor_calls", bool, True,
  "push actor calls straight to the actor's worker (head only resolves the "
  "route); falls back to head-mediated dispatch per actor on failure")
D("direct_task_calls", bool, True,
  "push normal tasks straight to head-granted leased workers with lease "
  "reuse (direct_task_transport.cc:588,:191); head path for placement "
  "strategies / runtime envs / TPU tasks and as fallback")
D("direct_task_max_leases", int, 8,
  "max concurrently held worker leases per (caller, resource shape)")
D("task_lease_idle_ms", int, 200,
  "idle time before a held task lease is released back to the cluster")
D("data_plane_request_warn_s", float, 60.0,
  "a driver->head data-plane request (get_objects dep resolution on the "
  "direct task channels) still unanswered after this long logs a loud "
  "repeating error naming its rid and the connection's other outstanding "
  "rids — turns a lost request/reply pair (the standalone "
  "test_repartition_exchange_exact wedge) into a diagnosable log line "
  "next to the test hang-guard's stack dump; 0 disables")
D("data_plane_request_deadline_s", float, 30.0,
  "per-attempt reply deadline for retransmit-armed data-plane requests "
  "(dep-resolution get_objects on the direct task channels): a request "
  "with no reply after this long is RE-SENT with the same rid and a "
  "bumped attempt counter (idempotent handlers re-execute; mutating ones "
  "dedup by rid head-side). Per-attempt waits back off exponentially, "
  "capped at 8x. 0 disables retransmit (legacy wait-forever behaviour)")
D("data_plane_request_retries", int, 4,
  "retransmits allowed per deadline-armed plane request before it "
  "surfaces PlaneRequestTimeout to the caller (total attempts = 1 + "
  "retries); dep pulls that exhaust this fall back to head-side task "
  "routing, which resolves deps on the head instead")
D("scheduler_spread_threshold", float, 0.5, "hybrid policy: prefer local until this utilization")
D("log_to_driver", bool, True)
D("session_dir_root", str, "/tmp/ray_tpu")
D("head_snapshot_period_ms", int, 15000,
  "period for head-state snapshots (KV, actors, jobs, PGs) to disk; 0 disables")
D("head_snapshot_path", str, "",
  "snapshot file (default <session_dir>/head_state.pkl); set a stable path "
  "to survive session-dir cleanup")
D("head_restore_path", str, "",
  "restore head state from this snapshot at startup (reference: GCS "
  "restart reload, gcs_init_data.h)")
D("head_storage_dir", str, "/tmp/ray_tpu/storage",
  "head-hosted object storage root for head:// URIs (checkpoints, "
  "experiment state); stable across sessions so a restarted cluster on "
  "the same head host can restore by URI")
D("head_reconnect_timeout_s", float, 60.0,
  "how long agents/workers/drivers keep retrying the head address after "
  "their connection drops (head crash + restart-from-snapshot window)")
D("head_tcp_host", str, "127.0.0.1",
  "bind host for the multi-host TCP control plane; the wire protocol is "
  "unauthenticated pickle, so bind non-loopback (0.0.0.0) only on trusted "
  "networks (real multi-host deployments)")
D("head_tcp_port", int, 0, "bind port for the TCP control plane (0 = ephemeral)")
D("dashboard_enabled", bool, True, "serve the dashboard-lite HTTP endpoint")
D("dashboard_host", str, "127.0.0.1")
D("dashboard_port", int, 0, "dashboard port (0 = ephemeral)")
D("memory_monitor_refresh_ms", int, 1000,
  "period for node memory-pressure sampling (reference: memory_monitor.h); "
  "0 disables the OOM killer")
D("memory_usage_threshold", float, 0.95,
  "node memory fraction above which the OOM killing policy fires "
  "(reference: ray_config_def.h memory_usage_threshold)")
D("memory_monitor_test_path", str, "",
  "test hook: file holding '<used> <total>' bytes used as the memory sample")
D("resource_report_period_ms", int, 2000,
  "agent->head node load report period (ray_syncer gossip analogue)")
# --- serve ingress hardening ---
# The HTTP proxy reads these at construction in ITS worker process, so set
# them via RAY_TPU_* env vars (inherited by spawned workers) or per-proxy
# through HTTPProxyActor kwargs / set_limits(); handle/breaker knobs are
# read in the calling process, so `init(_system_config=...)` works too.
D("serve_http_keep_alive_timeout_s", float, 30.0,
  "deadline for a complete request head to arrive on a connection — covers "
  "both idle keep-alive waits and slow-loris header trickle; expiry sends "
  "408 and closes")
D("serve_http_read_timeout_s", float, 30.0,
  "deadline for the request BODY (content-length or chunked) to arrive "
  "after the head; expiry sends 408 and closes")
D("serve_http_max_header_bytes", int, 64 * 1024,
  "request head larger than this is rejected with 431")
D("serve_http_max_body_bytes", int, 32 * 1024 * 1024,
  "request body larger than this is rejected with 413")
D("serve_http_max_connections", int, 1024,
  "open connections per proxy; excess connections get 503 + Retry-After")
D("serve_http_max_queued_calls", int, 128,
  "in-flight replica calls per proxy before new requests get 503 + "
  "Retry-After (backpressure ahead of the bounded call pool)")
D("serve_http_retry_after_s", float, 1.0,
  "Retry-After header value on 503 backpressure responses")
D("serve_handle_retry_attempts", int, 3,
  "re-route attempts after a replica died/was draining mid-call")
D("serve_handle_backoff_base_s", float, 0.05,
  "initial backoff before a replica-death re-route; doubles per attempt")
D("serve_handle_backoff_max_s", float, 1.0,
  "cap on the per-attempt re-route backoff (jitter rides below the cap)")
D("serve_breaker_failure_threshold", int, 5,
  "consecutive handle-level failures before a deployment's circuit breaker "
  "opens and calls fail fast with DeploymentUnavailableError")
D("serve_breaker_reset_s", float, 1.0,
  "how long an open circuit breaker waits before letting one probe through")
# --- serve continuous batching / token streaming ---
# Generation knobs are read in the REPLICA process at ContinuousBatcher
# construction (env vars or explicit constructor args); stream-pull knobs
# are read in the proxy process per pull.
D("serve_generation_max_batch_size", int, 8,
  "decode slots per ContinuousBatcher: the running batch admits new "
  "requests and retires finished ones at token granularity up to this size")
D("serve_generation_batch_wait_timeout_s", float, 0.01,
  "coalescing window when the running batch is EMPTY: wait this long for "
  "more requests before the first decode step (an active batch admits "
  "queued requests between steps without waiting)")
D("serve_stream_pull_max_chunks", int, 64,
  "max chunks the proxy pulls from a replica stream per stream_next call")
D("serve_stream_pull_wait_s", float, 0.25,
  "long-poll wait inside stream_next: block up to this long for the first "
  "chunk before returning an empty pull (bounds pull-call latency)")
D("serve_stream_idle_reap_s", float, 120.0,
  "a registered replica stream nobody has pulled for this long is "
  "cancelled and dropped — an abandoned consumer must not inflate "
  "num_ongoing (wedging drain) or hold a decode slot forever")
# --- paged KV cache (models/kv_paging.py) ---
# Read in the replica process at PagedDecodeEngine construction (env vars
# or explicit constructor args).
D("serve_kv_block_tokens", int, 64,
  "tokens per physical KV-cache block: the paging granularity — smaller "
  "blocks waste less tail memory and share finer prefixes but grow the "
  "block tables; 64 keeps the minor gather dim MXU/lane aligned")
D("serve_kv_cache_blocks", int, 0,
  "total physical blocks in a PagedDecodeEngine's pool (0 = dense "
  "equivalent: max_batch_size * ceil(max_seq_len/block_tokens), + the "
  "reserved null block); set below dense to oversubscribe HBM — prefix "
  "reuse and preemption keep oversubscription safe")
D("serve_kv_cache_dtype", str, "fp",
  "paged KV-pool storage: 'fp' stores model dtype (the exact reference "
  "path, bit-identical to dense decode); 'int8' stores int8 blocks with "
  "per-block per-kv-head f32 scales — half the HBM per resident token, "
  "~2x concurrent sequences per chip, quantize at cache write / dequant "
  "at the attention read (greedy decode stays token-identical on the "
  "parity suite; logits drift within the quantization tolerance)")
D("serve_paged_attention", str, "auto",
  "paged decode-step attention: 'gather' materializes each slot's "
  "[Nmax*block] window through its block table (exact reference); "
  "'fused' walks the table block-in-place (Pallas kernel on TPU, chunked "
  "online softmax elsewhere — ops/paged_attention.py), so the gather "
  "never exists; 'auto' = fused on TPU, gather on CPU; "
  "'fused:kernel'/'fused:xla' force one fused backend (tests)")
D("serve_paged_attention_chunk_blocks", int, 8,
  "fused-XLA paged attention only: physical blocks folded per "
  "online-softmax chunk in the block-table walk — larger chunks amortize "
  "gather dispatch, smaller ones cap the transient [B, chunk*block_tokens] "
  "window; the Pallas kernel walks block-by-block and ignores this")
D("serve_kv_pool_mb", int, 0,
  "size the paged KV pool by HBM budget instead of block count: "
  "num_blocks = budget // block_bytes, so int8 pools hold ~2x the blocks "
  "of bf16 for the same bytes; 0 = use serve_kv_cache_blocks / the "
  "dense-equivalent default (explicit constructor args win over both)")
D("serve_prefill_chunk_tokens", int, 0,
  "chunked prefill: admit long prompts into the RUNNING batch in chunks "
  "of this many tokens — each engine step advances one chunk while every "
  "other slot decodes, so a 4k-token prompt never stalls in-flight "
  "streams for its whole prefill (the head-of-line tail-latency fix for "
  "mixed traffic). 0 = whole-prompt prefill at admission (the "
  "lowest-latency path for a lone request); prompts at or under the "
  "chunk size admit whole either way")
D("serve_speculative_k", int, 0,
  "speculative decoding on the paged engine: a drafter proposes up to k "
  "tokens per slot per step and the target model verifies all k+1 "
  "positions in ONE batched decode step — accepted tokens commit through "
  "the block-table append, the rejected tail rolls back (table truncated, "
  "blocks freed). Greedy output stays token-for-token identical to "
  "non-speculative decode; greedy/temperature-0 only. 0 = off; the "
  "single-stream latency win scales with the drafter's accept rate")
D("serve_speculative_drafter", str, "ngram",
  "drafter when serve_speculative_k > 0: 'ngram' (self-drafting suffix "
  "lookup over the slot's own history — no extra model) or "
  "'ngram:<max_n>'; PagedDecodeEngine(drafter=...) also accepts any "
  "object with propose(tokens, k) -> tokens, the small-draft-model hook")
D("serve_model_path", str, "",
  "default checkpoint DIRECTORY for serve.openai_api.OpenAICompletions "
  "(model.safetensors + config.json + vocab.json + merges.txt — the "
  "model-hub layout, models/hub); explicit constructor args win")
D("serve_model_id", str, "",
  "model id the OpenAI-compatible endpoint advertises in /v1/models and "
  "completion responses; empty = the checkpoint directory's name")
D("serve_telemetry", bool, True,
  "serving telemetry plane (serve/telemetry.py): request-lifecycle "
  "histograms/counters/gauges (TTFT, inter-token latency, queue wait, "
  "request/error/preemption counters, KV-pool utilization, batch "
  "occupancy, spec accept rate — tagged by deployment/replica) plus the "
  "engine flight recorder. Read at engine/batcher construction in the "
  "replica process; off = zero per-token/per-step telemetry work")
D("serve_telemetry_recorder_events", int, 4096,
  "flight-recorder ring capacity: step-level engine events (admit, "
  "prefill_chunk, decode, verify, rollback, preempt, readmit, retire, "
  "eos) kept per process, oldest dropped first — the post-mortem window "
  "behind serve.telemetry.dump_timeline() / `ray_tpu timeline`; 0 "
  "disables the recorder while keeping the metrics")
D("serve_telemetry_push_s", float, 5.0,
  "min interval between a process's flight-recorder pushes to the head "
  "(piggybacked on replica stats/health polls; drain, engine faults and "
  "dump_timeline() force an immediate push)")
D("serve_kv_prefix_cache", bool, True,
  "keep full prompt blocks in a hash-trie after release so identical "
  "prompt prefixes (system prompts, few-shot headers) share physical "
  "blocks and skip prefill for the shared span; cache-held blocks are "
  "evicted LRU under pool pressure")
D("serve_kv_transfer", bool, True,
  "cluster-wide KV plane (serve/kv_transfer.py): replicas export cached "
  "prefix blocks on request and import peers' blocks before prefill, so "
  "a prefix computed anywhere in the deployment is a hit everywhere; "
  "any transfer failure falls back to local recompute — never wrong "
  "tokens. Off = every replica's PrefixCache stays private")
D("serve_kv_transfer_min_blocks", int, 1,
  "minimum full prompt blocks below which a replica does not attempt a "
  "remote prefix pull (the transfer round-trip must be worth more than "
  "the prefill it saves)")
D("serve_prefix_affinity", bool, False,
  "prefix-affinity routing: the controller aggregates a bounded LRU "
  "prefix->replica digest from replica stats and publishes it over "
  "long-poll; handles break power-of-two-choices ties toward the "
  "replica advertising the longest cached chain for the request's "
  "prefix hint. Plain load wins when queue depth diverges (see "
  "serve_prefix_affinity_max_skew) so affinity cannot create hotspots")
D("serve_prefix_affinity_max_skew", int, 2,
  "max in-flight-request excess the affinity replica may carry over the "
  "two-choices winner and still take the request; beyond it the load "
  "pick wins — the hotspot cap")
D("serve_prefix_hint_tokens", int, 64,
  "leading prompt tokens hashed into the prefix hint used by affinity "
  "routing and the replica-side digest; proxy, handle and replicas must "
  "agree, so this is config (not engine geometry)")
D("serve_prefix_digest_size", int, 512,
  "per-deployment cap on the controller's prefix->replica digest "
  "(bounded LRU: oldest hint evicted first)")
D("serve_weight_swap", bool, True,
  "live weight plane (serve/weight_swap.py): learners publish versioned "
  "param trees as bulk-plane objects, replicas subscribe over long-poll, "
  "pull + device_put by their own partition rules and hot-swap between "
  "engine steps — in-flight streams survive (recompute-on-readmit), the "
  "prefix cache flushes, and the transfer-sig version bumps so stale "
  "chain keys can never serve new-weight traffic. Off = subscribers "
  "never attach; publish() still works for manual pulls")
D("serve_weight_chunk_mb", int, 64,
  "per-leaf chunk size for published weights: leaves larger than this "
  "ship as multiple bulk-plane objects so pulls stripe across senders "
  "and a single giant leaf cannot serialize the swap; 0 = never chunk")
D("serve_weight_poll_timeout_s", float, 10.0,
  "long-poll timeout of the replica-side weight watcher (how long one "
  "poll parks on the weights channel before re-arming)")
D("serve_disaggregate", bool, False,
  "disaggregated prefill/decode default for kv_transfer.deploy_"
  "disaggregated(): prefill-tagged replicas run chunked prefill to "
  "completion and hand committed blocks to a decode replica over the "
  "transfer path; decode resumes token-for-token identically (greedy). "
  "The two pools scale on the existing autoscaling signals — block "
  "saturation (prefill) and batch occupancy (decode)")
D("train_dist_heartbeat_timeout_s", int, 30,
  "upper bound on detecting a dead jax.distributed gang peer: the "
  "coordination-service heartbeat interval/missing-count are derived "
  "from this, so a hard-killed rank parks the surviving ranks' shutdown "
  "barrier ~this long instead of jax's ~100s default — the gang-restart "
  "latency floor (train/trainer.py). 0 = keep jax's defaults")
D("train_dcn_grad_compression", str, "off",
  "gradient compression over the slow `dcn` axis of a multi-slice mesh "
  "(train/step.py): 'off' = fp32 all-reduce spanning (dcn, dp) as today; "
  "'int8' = full-precision reduce INSIDE the slice (ICI), then an int8 "
  "block-quantized exchange with error feedback across slices — ~4x "
  "fewer DCN bytes per step (util/collective/compress.py). Adds an "
  "error-feedback residual buffer to the optimizer state (checkpointed; "
  "restoring a pre-compression checkpoint zero-initializes it)")
D("train_dcn_grad_compression_block", int, 256,
  "quantization block size for train_dcn_grad_compression=int8: one "
  "shared fp32 scale per block crosses DCN alongside the int8 payload")
# --- TPU ---
D("tpu_chips_per_host", int, 4, "default TPU chips advertised per host when detected")
D("mesh_dryrun_platform", str, "cpu")

GLOBAL_CONFIG = Config()
