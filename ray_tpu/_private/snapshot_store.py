"""Pluggable head-snapshot stores.

Reference parity: src/ray/gcs/store_client/ — the GCS server persists its
tables through a StoreClient interface with in-memory, redis, and
observable backends (redis_store_client.h), so losing the head process
doesn't lose cluster metadata, and losing the head HOST doesn't either if
the store is external. ray_tpu's equivalent: the head's periodic state
snapshot writes through a SnapshotStore chosen by the
head_snapshot_path/head_restore_path config value:

- plain path            -> FileSnapshotStore (atomic tmp+rename, default)
- sqlite:///path/to.db  -> SqliteSnapshotStore: versioned rows in a SQLite
  database (WAL), keeping a bounded history — point the path at a mounted
  remote volume or replicate the db file and head-host disk loss stops
  being metadata loss. This is the redis-parity external store: a real
  database with history, not a single overwritten file.
- gs://bucket/key.pkl   -> GcsSnapshotStore via the gsutil CLI (TPU hosts
  ship it; RAY_TPU_GSUTIL overrides for tests/airgap), errors clearly
  when unavailable.

register_snapshot_store() adds custom schemes (e.g. a real redis client).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional


class SnapshotStore:
    def save(self, data: bytes) -> None:
        raise NotImplementedError

    def load(self) -> Optional[bytes]:
        """Latest snapshot bytes, or None when the store is empty."""
        raise NotImplementedError


class FileSnapshotStore(SnapshotStore):
    def __init__(self, path: str):
        self.path = path

    def save(self, data: bytes) -> None:
        import uuid

        tmp = f"{self.path}.tmp-{uuid.uuid4().hex[:8]}"
        os.makedirs(os.path.dirname(self.path) or "/", exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self.path)

    def load(self) -> Optional[bytes]:
        try:
            with open(self.path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None


class SqliteSnapshotStore(SnapshotStore):
    """Versioned snapshot rows; keeps the newest `keep` versions."""

    def __init__(self, path: str, keep: int = 8):
        self.path = path
        self.keep = keep
        self._schema_ready = False

    def _conn(self):
        import sqlite3

        os.makedirs(os.path.dirname(self.path) or "/", exist_ok=True)
        conn = sqlite3.connect(self.path, timeout=30)
        if not self._schema_ready:
            # once per store instance: WAL is persistent in the db file and
            # the table is stable, so steady-state saves (every few hundred
            # ms on the head) skip the pragma lock + schema check
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute(
                "CREATE TABLE IF NOT EXISTS head_snapshots ("
                " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                " created_at REAL NOT NULL,"
                " state BLOB NOT NULL)"
            )
            self._schema_ready = True
        return conn

    def save(self, data: bytes) -> None:
        import time

        conn = self._conn()
        try:
            with conn:
                conn.execute(
                    "INSERT INTO head_snapshots (created_at, state) VALUES (?, ?)",
                    (time.time(), data),
                )
                conn.execute(
                    "DELETE FROM head_snapshots WHERE id NOT IN "
                    "(SELECT id FROM head_snapshots ORDER BY id DESC LIMIT ?)",
                    (self.keep,),
                )
        finally:
            conn.close()

    def load(self) -> Optional[bytes]:
        conn = self._conn()
        try:
            row = conn.execute(
                "SELECT state FROM head_snapshots ORDER BY id DESC LIMIT 1"
            ).fetchone()
            return bytes(row[0]) if row else None
        finally:
            conn.close()

    def history(self) -> list:
        """(id, created_at) of stored versions, newest first."""
        conn = self._conn()
        try:
            return conn.execute(
                "SELECT id, created_at FROM head_snapshots ORDER BY id DESC"
            ).fetchall()
        finally:
            conn.close()


class GcsSnapshotStore(SnapshotStore):
    def __init__(self, uri: str):
        self.uri = uri

    def _tool(self) -> str:
        import shutil as _shutil

        tool = os.environ.get("RAY_TPU_GSUTIL") or _shutil.which("gsutil")
        if not tool:
            raise RuntimeError(
                "gs:// snapshot store needs the gsutil CLI (not found; set "
                "RAY_TPU_GSUTIL to override)"
            )
        return tool

    def save(self, data: bytes) -> None:
        import subprocess
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".pkl") as tf:
            tf.write(data)
            tf.flush()
            proc = subprocess.run(
                [self._tool(), "cp", tf.name, self.uri],
                capture_output=True, text=True,
            )
            if proc.returncode != 0:
                raise RuntimeError(f"gsutil cp failed: {proc.stderr.strip()}")

    def load(self) -> Optional[bytes]:
        import subprocess
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".pkl") as tf:
            proc = subprocess.run(
                [self._tool(), "cp", self.uri, tf.name],
                capture_output=True, text=True,
            )
            if proc.returncode != 0:
                err = proc.stderr.lower()
                # only a MISSING object means "empty store"; auth/network
                # failures must raise, not silently mint a fresh cluster
                if "no urls matched" in err or "does not exist" in err or (
                    "not found" in err
                ):
                    return None
                raise RuntimeError(f"gsutil cp failed: {proc.stderr.strip()}")
            with open(tf.name, "rb") as f:
                return f.read()


_FACTORIES: Dict[str, Callable[[str], SnapshotStore]] = {
    "sqlite": lambda target: SqliteSnapshotStore(target[len("sqlite://"):]),
    "gs": GcsSnapshotStore,
}


def register_snapshot_store(scheme: str, factory: Callable[[str], SnapshotStore]):
    _FACTORIES[scheme] = factory


def store_for(target: str) -> SnapshotStore:
    """Resolve a snapshot target string to its store. Plain paths (no
    scheme) stay on the original single-file layout."""
    if "://" not in target:
        return FileSnapshotStore(target)
    scheme = target.split("://", 1)[0]
    factory = _FACTORIES.get(scheme)
    if factory is None:
        raise ValueError(
            f"no snapshot store for scheme {scheme!r} "
            f"(known: file-path, {sorted(_FACTORIES)}); "
            "register_snapshot_store() to add one"
        )
    return factory(target)
