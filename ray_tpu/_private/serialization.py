"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Reference parity: python/ray/_private/serialization.py (SerializationContext,
serialize/deserialize_objects) — large binary buffers (numpy, jax host arrays)
are extracted out-of-band so they can ride the shared-memory object store with
zero copies instead of the control socket.

ObjectRefs contained in a value are collected during pickling (thread-local
collector wired into ObjectRef.__reduce__) so the runtime can track ownership
and resolve dependencies — the analogue of Ray's contained-object-ID scan.
"""

from __future__ import annotations

import pickle
import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional

import cloudpickle

_INLINE_BUFFER_LIMIT = 8 * 1024  # buffers below this are folded in-band


class _RefCollector(threading.local):
    def __init__(self):
        self.active: Optional[list] = None


_ref_collector = _RefCollector()


def record_contained_ref(ref) -> None:
    """Called from ObjectRef.__reduce__ during pickling."""
    if _ref_collector.active is not None:
        _ref_collector.active.append(ref)


@dataclass
class SerializedObject:
    """A picklable envelope: payload + out-of-band buffers + contained refs.

    Buffers may be zero-copy memoryviews (fresh from serialize) — pickling
    the envelope (socket path) converts them to bytes; the shm path consumes
    the views directly without ever materializing bytes."""

    payload: bytes
    buffers: List[Any] = field(default_factory=list)
    contained_refs: List[Any] = field(default_factory=list)
    is_error: bool = False

    def total_bytes(self) -> int:
        return len(self.payload) + sum(
            b.size if hasattr(b, "size") and not isinstance(b, (bytes, memoryview)) else len(b)
            for b in self.buffers
        )

    def __reduce__(self):
        wire_buffers = [
            bytes(b) if isinstance(b, memoryview) else b for b in self.buffers
        ]
        return (
            _rebuild_envelope,
            (self.payload, wire_buffers, self.contained_refs, self.is_error),
        )


def _rebuild_envelope(payload, buffers, refs, is_error):
    return SerializedObject(
        payload=payload, buffers=buffers, contained_refs=refs, is_error=is_error
    )


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []
    refs: list = []
    prev = _ref_collector.active
    _ref_collector.active = refs
    try:
        def _cb(buf: pickle.PickleBuffer):
            raw = buf.raw()
            if raw.nbytes <= _INLINE_BUFFER_LIMIT:
                return True  # keep in-band
            buffers.append(buf)
            return False

        payload = cloudpickle.dumps(value, protocol=5, buffer_callback=_cb)
    finally:
        _ref_collector.active = prev
    # keep raw views (zero-copy); __reduce__ converts to bytes only if the
    # envelope actually rides the socket instead of the shm plane
    out = [b.raw() for b in buffers]
    # Dedup refs by id while preserving order.
    seen = set()
    uniq = []
    for r in refs:
        if r.id not in seen:
            seen.add(r.id)
            uniq.append(r)
    return SerializedObject(payload=payload, buffers=out, contained_refs=uniq)


def deserialize(obj: SerializedObject) -> Any:
    return pickle.loads(obj.payload, buffers=obj.buffers)


def externalize(env: SerializedObject, shm_client, threshold: int) -> SerializedObject:
    """Move large out-of-band buffers into the shared-memory store, replacing
    them with ShmBufferRef handles (zero-copy across host processes)."""
    if shm_client is None:
        return env
    import uuid

    new_buffers = []
    for buf in env.buffers:
        if isinstance(buf, (bytes, memoryview)) and len(buf) >= threshold:
            ref = shm_client.create(uuid.uuid4().hex, memoryview(buf))
            new_buffers.append(ref if ref is not None else buf)
        else:
            new_buffers.append(buf)
    env.buffers = new_buffers
    return env


def materialize(env: SerializedObject, shm_client) -> SerializedObject:
    """Resolve ShmBufferRef buffers into mapped memoryviews (no copy)."""
    from .shm import ShmBufferRef

    out = []
    for buf in env.buffers:
        if isinstance(buf, ShmBufferRef):
            if shm_client is None:
                raise RuntimeError("shm buffer present but shm store unavailable")
            mv = shm_client.get(buf)
            if mv is None:
                from ..exceptions import ObjectLostError

                raise ObjectLostError(buf.name)
            out.append(mv)
        else:
            out.append(buf)
    env.buffers = out
    return env


def shm_buffer_names(env: SerializedObject):
    from .shm import ShmBufferRef

    return [b.name for b in env.buffers if isinstance(b, ShmBufferRef)]
