"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Reference parity: python/ray/_private/serialization.py (SerializationContext,
serialize/deserialize_objects) — large binary buffers (numpy, jax host arrays)
are extracted out-of-band so they can ride the shared-memory object store with
zero copies instead of the control socket.

ObjectRefs contained in a value are collected during pickling (thread-local
collector wired into ObjectRef.__reduce__) so the runtime can track ownership
and resolve dependencies — the analogue of Ray's contained-object-ID scan.
"""

from __future__ import annotations

import pickle
import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional

import cloudpickle

_INLINE_BUFFER_LIMIT = 8 * 1024  # buffers below this are folded in-band


class _RefCollector(threading.local):
    def __init__(self):
        self.active: Optional[list] = None


_ref_collector = _RefCollector()


def record_contained_ref(ref) -> None:
    """Called from ObjectRef.__reduce__ during pickling."""
    if _ref_collector.active is not None:
        _ref_collector.active.append(ref)


@dataclass
class SerializedObject:
    """A picklable envelope: payload + out-of-band buffers + contained refs.

    Buffers may be zero-copy memoryviews (fresh from serialize) — pickling
    the envelope (socket path) converts them to bytes; the shm path consumes
    the views directly without ever materializing bytes."""

    payload: bytes
    buffers: List[Any] = field(default_factory=list)
    contained_refs: List[Any] = field(default_factory=list)
    is_error: bool = False

    def total_bytes(self) -> int:
        return len(self.payload) + sum(
            b.size if hasattr(b, "size") and not isinstance(b, (bytes, memoryview)) else len(b)
            for b in self.buffers
        )

    def __reduce_ex__(self, protocol):
        if protocol >= 5:
            # PickleBuffer wrap: on the plane framing these ride as raw
            # out-of-band segments (protocol._frame_parts) — a big task
            # arg/return crossing a socket is never copied through pickle.
            # Without a buffer_callback (plain dumps) they serialize
            # in-band and load back as bytes, so every caller still works.
            wire_buffers = [
                pickle.PickleBuffer(b) if isinstance(b, memoryview) else b
                for b in self.buffers
            ]
        else:
            wire_buffers = [
                bytes(b) if isinstance(b, memoryview) else b
                for b in self.buffers
            ]
        return (
            _rebuild_envelope,
            (self.payload, wire_buffers, self.contained_refs, self.is_error),
        )


def _rebuild_envelope(payload, buffers, refs, is_error):
    return SerializedObject(
        payload=payload, buffers=buffers, contained_refs=refs, is_error=is_error
    )


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []
    refs: list = []
    prev = _ref_collector.active
    _ref_collector.active = refs
    try:
        def _cb(buf: pickle.PickleBuffer):
            raw = buf.raw()
            if raw.nbytes <= _INLINE_BUFFER_LIMIT:
                return True  # keep in-band
            buffers.append(buf)
            return False

        payload = cloudpickle.dumps(value, protocol=5, buffer_callback=_cb)
    finally:
        _ref_collector.active = prev
    # keep raw views (zero-copy); __reduce__ converts to bytes only if the
    # envelope actually rides the socket instead of the shm plane
    out = [b.raw() for b in buffers]
    # Dedup refs by id while preserving order.
    seen = set()
    uniq = []
    for r in refs:
        if r.id not in seen:
            seen.add(r.id)
            uniq.append(r)
    return SerializedObject(payload=payload, buffers=out, contained_refs=uniq)


def deserialize(obj: SerializedObject) -> Any:
    return pickle.loads(obj.payload, buffers=obj.buffers)


def externalize(
    env: SerializedObject, shm_client, threshold: int, pin: bool = False
) -> SerializedObject:
    """Move large out-of-band buffers into the shared-memory store, replacing
    them with ShmBufferRef handles (zero-copy across host processes). Each
    handle is tagged with the producing node so cross-node consumers know
    where the primary copy lives. pin=True (ray.put data: no lineage) marks
    the buffers never-evictable."""
    if shm_client is None:
        return env
    import uuid

    from .worker import global_worker

    node = global_worker.node_id or ""
    new_buffers = []
    for buf in env.buffers:
        if isinstance(buf, (bytes, memoryview)) and len(buf) >= threshold:
            ref = shm_client.create(uuid.uuid4().hex, memoryview(buf), pin=pin)
            if ref is not None:
                ref.node = node
                new_buffers.append(ref)
            else:
                new_buffers.append(buf)
        else:
            new_buffers.append(buf)
    env.buffers = new_buffers
    return env


def materialize(env: SerializedObject, shm_client) -> SerializedObject:
    """Resolve ShmBufferRef buffers into memoryviews.

    Same-node buffers map zero-copy from the local shm plane. Cross-node
    buffers (ref.node != ours) are pulled through the head (which relays to
    the owning node's agent — reference: pull_manager.h:52) and cached into
    the local plane under the same cluster-unique name, so repeat consumers
    on this node hit shm."""
    from .shm import ShmBufferRef

    from ..exceptions import ObjectLostError

    refs = [b for b in env.buffers if isinstance(b, ShmBufferRef)]
    if not refs:
        return env
    from .worker import global_worker

    my_node = global_worker.node_id or ""
    resolved = {}
    missing = []
    for buf in refs:
        if buf.name in resolved:
            continue
        mv = shm_client.get_or_spilled(buf.name) if shm_client is not None else None
        if mv is not None:
            resolved[buf.name] = mv
        elif (buf.node or "") == my_node and shm_client is not None:
            raise ObjectLostError(buf.name)  # primary copy gone (evicted)
        else:
            missing.append(buf)
    if missing:
        by_node: dict = {}
        for buf in missing:
            by_node.setdefault(buf.node or "", []).append(buf)
        for node, bufs in by_node.items():
            # bulk plane first: zero-copy pull straight from the owning
            # node (object_manager.h:117) — the sizes in the refs let the
            # consumer recv_into preallocated slab space; the head relay
            # is the fallback (and the only path for head-owned buffers,
            # where the head IS the owner)
            direct_eligible = bool(node) and node != my_node
            if direct_eligible:
                got = global_worker.fetch_buffers_direct(node, bufs)
                if got is not None:
                    # already slab-resident (recv_into landed there) — no
                    # re-cache; a None value means the OWNER lost it
                    for name, data in got.items():
                        if data is None:
                            raise ObjectLostError(name)
                        resolved[name] = memoryview(data)
                    continue
                _count_relay_fallback()
            got = global_worker.request(
                {
                    "t": "fetch_buffers",
                    "names": [b.name for b in bufs],
                    "node": node,
                }
            )
            _account_relay(got)
            for name, data in got.items():
                if data is None:
                    raise ObjectLostError(name)
                mv = None
                if shm_client is not None:
                    # cache into the local slab, then RESOLVE AGAINST THE
                    # SLAB COPY — the transient receive buffer becomes
                    # droppable instead of living on under the envelope
                    ref2 = shm_client.create(name, data)
                    if ref2 is not None:
                        mv = shm_client.get(ref2)
                resolved[name] = mv if mv is not None else memoryview(data)
    env.buffers = [
        resolved[b.name] if isinstance(b, ShmBufferRef) else b for b in env.buffers
    ]
    return env


def _count_relay_fallback() -> None:
    """A direct node-to-node pull failed and the fetch is falling back to
    the head relay — make that visible (chaos tests assert on it)."""
    try:
        from ray_tpu.util import metrics as _m

        _m.bulk_plane_fallbacks_counter().inc()
    except Exception:
        pass


def _account_relay(got: dict) -> None:
    try:
        from .bulk import account

        for data in got.values():
            if data is not None:
                account("relay", len(data))
    except Exception:
        pass


def shm_buffer_names(env: SerializedObject):
    from .shm import ShmBufferRef

    return [b.name for b in env.buffers if isinstance(b, ShmBufferRef)]


def shm_buffer_refs(env: SerializedObject):
    from .shm import ShmBufferRef

    return [b for b in env.buffers if isinstance(b, ShmBufferRef)]
