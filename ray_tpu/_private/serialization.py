"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Reference parity: python/ray/_private/serialization.py (SerializationContext,
serialize/deserialize_objects) — large binary buffers (numpy, jax host arrays)
are extracted out-of-band so they can ride the shared-memory object store with
zero copies instead of the control socket.

ObjectRefs contained in a value are collected during pickling (thread-local
collector wired into ObjectRef.__reduce__) so the runtime can track ownership
and resolve dependencies — the analogue of Ray's contained-object-ID scan.
"""

from __future__ import annotations

import pickle
import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional

import cloudpickle

_INLINE_BUFFER_LIMIT = 8 * 1024  # buffers below this are folded in-band


class _RefCollector(threading.local):
    def __init__(self):
        self.active: Optional[list] = None


_ref_collector = _RefCollector()


def record_contained_ref(ref) -> None:
    """Called from ObjectRef.__reduce__ during pickling."""
    if _ref_collector.active is not None:
        _ref_collector.active.append(ref)


@dataclass
class SerializedObject:
    """A picklable envelope: payload + out-of-band buffers + contained refs."""

    payload: bytes
    buffers: List[bytes] = field(default_factory=list)
    contained_refs: List[Any] = field(default_factory=list)

    def total_bytes(self) -> int:
        return len(self.payload) + sum(len(b) for b in self.buffers)


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []
    refs: list = []
    prev = _ref_collector.active
    _ref_collector.active = refs
    try:
        def _cb(buf: pickle.PickleBuffer):
            raw = buf.raw()
            if raw.nbytes <= _INLINE_BUFFER_LIMIT:
                return True  # keep in-band
            buffers.append(buf)
            return False

        payload = cloudpickle.dumps(value, protocol=5, buffer_callback=_cb)
    finally:
        _ref_collector.active = prev
    out = [bytes(b.raw()) for b in buffers]
    # Dedup refs by id while preserving order.
    seen = set()
    uniq = []
    for r in refs:
        if r.id not in seen:
            seen.add(r.id)
            uniq.append(r)
    return SerializedObject(payload=payload, buffers=out, contained_refs=uniq)


def deserialize(obj: SerializedObject) -> Any:
    return pickle.loads(obj.payload, buffers=obj.buffers)
