"""@remote task functions.

Reference parity: python/ray/remote_function.py (RemoteFunction._remote :245
→ core_worker.submit_task :391).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional


def _validated_runtime_env(env: Optional[dict]) -> Optional[dict]:
    from .runtime_env import RuntimeEnv

    return RuntimeEnv.validate(env)


class RemoteFunction:
    def __init__(self, function, **default_options):
        self._function = function
        self._default_options = default_options
        functools.update_wrapper(self, function)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "Remote functions cannot be called directly. "
            f"Use {self._function.__name__}.remote() instead."
        )

    def options(self, **task_options) -> "RemoteFunction":
        opts = dict(self._default_options)
        opts.update(task_options)
        return RemoteFunction(self._function, **opts)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_options)

    def _remote(self, args, kwargs, opts: Dict[str, Any]):
        from ._private.worker import global_worker
        from ._private.options import resolve_task_resources

        num_returns = opts.get("num_returns", 1)
        # generator tasks (reference: num_returns="streaming" returns an
        # ObjectRefGenerator from .remote(); "dynamic" returns a single ref
        # whose get() resolves to the generator — _raylet.pyx
        # ObjectRefGenerator / DynamicObjectRefGenerator)
        streaming = num_returns in ("streaming", "dynamic")
        refs = global_worker.submit_task(
            self._function,
            args,
            kwargs,
            name=opts.get("name") or self._function.__name__,
            num_returns=1 if streaming else num_returns,
            resources=resolve_task_resources(opts, is_actor=False),
            # reference default: tasks retry 3x on SYSTEM failures (worker
            # crash, lease failure) — ray_config_def.h task_max_retries;
            # application exceptions never retry
            max_retries=opts.get("max_retries", 3),
            scheduling_strategy=_strategy_to_wire(opts.get("scheduling_strategy")),
            runtime_env=_validated_runtime_env(opts.get("runtime_env")),
            streaming=streaming,
        )
        if num_returns == "streaming":
            from .object_ref import ObjectRefGenerator

            return ObjectRefGenerator(refs[0])
        if num_returns in (1, "dynamic"):
            return refs[0]
        return refs

    @property
    def bind(self):
        from .dag.function_node import bind_function

        return functools.partial(bind_function, self)


def _strategy_to_wire(strategy):
    from .util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if strategy is None or isinstance(strategy, str):
        return strategy
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        return {
            "type": "placement_group",
            "pg_id": strategy.placement_group.id,
            "bundle_index": strategy.placement_group_bundle_index,
        }
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return {"type": "node_affinity", "node_id": strategy.node_id, "soft": strategy.soft}
    raise TypeError(f"Unknown scheduling strategy {strategy!r}")
