"""Checkpoints: multi-host async save/restore of sharded arrays via Orbax.

Reference parity: ray.air.checkpoint.Checkpoint (air/checkpoint.py:66 —
dict/directory/URI forms) — but where the reference's model is "rank 0
uploads a directory" (tune/syncer.py:306), sharded TPU states save in
parallel: every host writes its own shards (orbax/tensorstore), which is
the only model that scales to 7B+ param states on pod slices (SURVEY §5.4).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, Optional


class Checkpoint:
    """A directory-backed checkpoint handle (picklable; travels by path)."""

    def __init__(self, path: str, metrics: Optional[Dict[str, Any]] = None):
        self.path = path
        self.metrics = metrics or {}

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    def to_directory(self, dest: Optional[str] = None) -> str:
        if dest is None:
            return self.path
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        import pickle

        with open(os.path.join(d, "data.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> Dict[str, Any]:
        import pickle

        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    def __reduce__(self):
        return (Checkpoint, (self.path, self.metrics))

    # ---- URI persistence (reference: air/checkpoint.py:707 to_uri,
    # :735 from_uri — pyarrow-fs upload/download; ours rides the
    # train/storage.py scheme registry: file:// head:// gs://) ----

    def to_uri(self, uri: str) -> str:
        """Upload this checkpoint's directory to a storage URI."""
        from . import storage

        return storage.upload_dir(self.path, uri)

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        """Download a checkpoint from a storage URI into a local dir."""
        from . import storage

        return cls(storage.download_dir(uri))


def save_checkpoint(path: str, state: Any, *, step: Optional[int] = None) -> str:
    """Save a (sharded) pytree state with orbax; returns the checkpoint dir
    (or the URI when `path` is one — saved locally, then uploaded)."""
    import orbax.checkpoint as ocp

    from . import storage

    if storage.is_uri(path):
        uri = storage.uri_join(path, f"step_{step}") if step is not None else path
        local = save_checkpoint(tempfile.mkdtemp(prefix="ray_tpu_ckpt_"), state)
        storage.upload_dir(local, uri)
        shutil.rmtree(local, ignore_errors=True)
        return uri
    path = os.path.abspath(path)
    if step is not None:
        path = os.path.join(path, f"step_{step}")
    if os.path.exists(path):
        shutil.rmtree(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state)
    ckptr.wait_until_finished()
    ckptr.close()
    return path


def restore_checkpoint(path: str, abstract_state: Any) -> Any:
    """Restore into the sharding/layout described by abstract_state
    (jax.eval_shape output with shardings attached, or a concrete state).
    `path` may be a storage URI (downloaded first — multi-host restore
    without shared disk)."""
    import orbax.checkpoint as ocp

    from . import storage

    if storage.is_uri(path):
        path = storage.download_dir(path)
    ckptr = ocp.StandardCheckpointer()
    out = ckptr.restore(os.path.abspath(path), abstract_state)
    ckptr.close()
    return out


def restore_train_state(path: str, abstract_state: Any) -> Any:
    """restore_checkpoint for TrainStates that may carry error-feedback
    residuals (train_dcn_grad_compression='int8' wraps the optimizer state
    as (inner_state, EFState) — train/step.py make_sharded_init).

    A checkpoint written BEFORE compression was enabled has no EFState
    entry; restoring it into a compression-enabled abstract state would be
    a tree-structure mismatch. This helper retries with the EF half
    stripped from the abstract tree and zero-fills the residuals with the
    requested sharding — mathematically exact: EF residuals are carried
    rounding error, and zero is the state of a run that has not rounded
    anything yet."""
    try:
        return restore_checkpoint(path, abstract_state)
    except Exception:
        from ..util.collective.compress import EFState

        opt = getattr(abstract_state, "opt_state", None)
        if not (
            isinstance(opt, tuple)
            and len(opt) == 2
            and isinstance(opt[1], EFState)
        ):
            raise
        import jax
        import jax.numpy as jnp

        legacy = abstract_state._replace(opt_state=opt[0])
        restored = restore_checkpoint(path, legacy)

        def _zeros(a):
            z = jnp.zeros(a.shape, a.dtype)
            sh = getattr(a, "sharding", None)
            return jax.device_put(z, sh) if sh is not None else z

        ef = jax.tree.map(_zeros, opt[1])
        return restored._replace(opt_state=(restored.opt_state, ef))


def abstract_like(state: Any) -> Any:
    """Build the abstract (ShapeDtypeStruct+sharding) mirror of a live state."""
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "sharding")
        else x,
        state,
    )
