"""Typed training configs.

Reference parity: python/ray/air/config.py (ScalingConfig, RunConfig,
FailureConfig, CheckpointConfig).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """How many worker processes and what each owns.

    TPU-native semantics: num_workers = HOSTS (one SPMD process per host,
    owning all its chips through one mesh), not devices — the reference's
    one-worker-per-GPU model (ScalingConfig num_workers * 1 GPU) does not
    map to XLA's single-client-per-host runtime.
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpu_chips_per_worker: int = 4
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    placement_strategy: str = "SPREAD"
    env_vars: Dict[str, str] = field(default_factory=dict)
    # multi-slice DCN topology (parallel/multislice.py): the gang's hosts
    # split into this many equal slices; workers of one slice hold
    # consecutive ranks. Each worker's train loop can then build the
    # two-level (dcn x ICI) mesh with session.build_multislice_mesh.
    num_slices: int = 1
    # interleaved-1F1B depth for pp-outer loops: each pipeline device hosts
    # this many non-adjacent stage chunks (parallel/pipeline.py), shrinking
    # the bubble from (pp-1)/(n_mb+pp-1) toward (pp-1)/(v*n_mb+pp-1).
    # Surfaced to the train loop via session.get_virtual_stages_per_device()
    # and consumed as TransformerConfig.pp_interleave.
    virtual_stages_per_device: int = 1
    # cross-slice gradient compression for dp-outer loops: None inherits
    # the process-wide train_dcn_grad_compression flag; "off"/"int8" pin it
    # for this gang (exported to the workers' env so every host agrees).
    dcn_grad_compression: Optional[str] = None

    def __post_init__(self):
        if self.num_slices < 1:
            raise ValueError(f"num_slices must be >= 1, got {self.num_slices}")
        if self.num_workers % self.num_slices:
            raise ValueError(
                f"num_workers={self.num_workers} does not split into "
                f"{self.num_slices} equal slices; slices must hold the same "
                "number of hosts"
            )
        if self.virtual_stages_per_device < 1:
            raise ValueError(
                f"virtual_stages_per_device must be >= 1, got "
                f"{self.virtual_stages_per_device}"
            )
        if self.dcn_grad_compression not in (None, "off", "int8"):
            raise ValueError(
                f"dcn_grad_compression must be None, 'off' or 'int8', got "
                f"{self.dcn_grad_compression!r}"
            )

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker)
        if self.use_tpu:
            res.setdefault("TPU", float(self.tpu_chips_per_worker))
        res.setdefault("CPU", 1.0)
        return res


@dataclass
class FailureConfig:
    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
