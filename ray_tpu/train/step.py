"""Sharded train-step factory.

The TPU-native replacement for the reference's whole DDP/FSDP/DeepSpeed
engine zoo (train_loop_utils.py:75 prepare_model): one jit'ed function with
NamedSharding in/out specs; GSPMD inserts gradient all-reduces (dp), param
all-gathers + grad reduce-scatters (fsdp = ZeRO-3), activation collectives
(tp), and ring/all-to-all exchanges (sp) from the sharding table alone.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._private.config import GLOBAL_CONFIG
from ..models.transformer import TransformerConfig, init_params, make_loss_fn, param_specs
from ..parallel.sharding import ShardingRules
from ..util.collective import compress


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def resolve_dcn_compression(
    flag: Optional[str], mesh: Mesh, rules: Optional[ShardingRules] = None
) -> str:
    """Normalize the train_dcn_grad_compression knob: None reads the global
    config; 'int8' silently degrades to 'off' on meshes with no real dcn
    axis (single slice) where there is nothing to compress, and — when the
    rule table is given — on topologies whose dcn axis does not shard the
    batch (pp_outer: the slice boundary carries stage activations, not a
    gradient all-reduce, so there is no dcn gradient exchange to quantize)."""
    if flag is None:
        flag = GLOBAL_CONFIG.train_dcn_grad_compression
    if flag not in ("off", "int8"):
        raise ValueError(
            f"train_dcn_grad_compression must be 'off' or 'int8', got {flag!r}"
        )
    if flag == "int8":
        if mesh.shape.get("dcn", 1) < 2:
            return "off"
        if rules is not None:
            bax = rules.mesh_axes("batch")
            axes = bax if isinstance(bax, tuple) else (bax,)
            if "dcn" not in axes:
                return "off"
    return flag


def _param_shardings(mesh: Mesh, rules: ShardingRules, specs_tree):
    def is_spec(x):
        return isinstance(x, tuple) and all(a is None or isinstance(a, str) for a in x)

    return jax.tree.map(
        lambda axes: NamedSharding(mesh, rules.spec(*axes)), specs_tree, is_leaf=is_spec
    )


def _opt_shardings(opt_state_shapes, params_shapes, params_shardings, mesh):
    """Optimizer-state leaves mirror param leaves structurally (adam mu/nu);
    match by array shape — equal-shaped params share equal specs in our
    models, scalars replicate."""
    by_shape = {}
    flat_p, _ = jax.tree.flatten(params_shapes)
    flat_s, _ = jax.tree.flatten(params_shardings)
    for p, s in zip(flat_p, flat_s):
        by_shape[tuple(p.shape)] = s
    replicated = NamedSharding(mesh, P())

    def pick(leaf):
        return by_shape.get(tuple(leaf.shape), replicated)

    return jax.tree.map(pick, opt_state_shapes)


def make_sharded_init(
    cfg: TransformerConfig,
    mesh: Mesh,
    rules: ShardingRules,
    optimizer: optax.GradientTransformation,
    dcn_grad_compression: Optional[str] = None,
) -> Tuple[Callable[[jax.Array], TrainState], Any]:
    """Returns (init_fn, state_shardings). init_fn is jit'ed with sharded
    outputs so params are born distributed — no host-memory spike.

    With dcn_grad_compression='int8' (or the train_dcn_grad_compression
    config flag) the optimizer state becomes (inner_state, EFState): the
    error-feedback residuals ride the optimizer state so checkpoints carry
    them (train/checkpoint.py zero-fills them when restoring a
    pre-compression checkpoint)."""
    compression = resolve_dcn_compression(dcn_grad_compression, mesh, rules)
    specs = param_specs(cfg)
    p_shard = _param_shardings(mesh, rules, specs)
    p_shapes = jax.eval_shape(lambda k: init_params(k, cfg), jax.random.PRNGKey(0))
    o_shapes = jax.eval_shape(optimizer.init, p_shapes)
    o_shard = _opt_shardings(o_shapes, p_shapes, p_shard, mesh)
    if compression == "int8":
        o_shard = (o_shard, compress.ef_state_sharding(mesh))
    state_shardings = TrainState(
        step=NamedSharding(mesh, P()), params=p_shard, opt_state=o_shard
    )
    n_slices = mesh.shape.get("dcn", 1)
    block = GLOBAL_CONFIG.train_dcn_grad_compression_block

    def _init(rng) -> TrainState:
        params = init_params(rng, cfg)
        opt_state = optimizer.init(params)
        if compression == "int8":
            opt_state = (opt_state, compress.init_ef_state(params, n_slices, block))
        return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)

    init_jit = jax.jit(_init, out_shardings=state_shardings)
    return init_jit, state_shardings


def batch_sharding(mesh: Mesh, rules: ShardingRules) -> NamedSharding:
    # Raw batches arrive batch-sharded only (their seq length is often L+1,
    # not divisible by sp); activations get resharded onto `sp` by the first
    # sharding constraint inside the compiled program. Returned as a single
    # sharding used as a pytree PREFIX, so it applies to every leaf of the
    # batch dict whether or not an (optional) mask is present.
    return NamedSharding(mesh, rules.spec("batch", None))


def make_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    rules: ShardingRules,
    optimizer: optax.GradientTransformation,
    state_shardings: TrainState,
    compute_dtype_grads: bool = False,
    dcn_grad_compression: Optional[str] = None,
):
    """Returns train_step(state, batch) -> (state, metrics), jit'ed with
    donated state (in-place HBM update) and sharded in/out.

    compute_dtype_grads=True differentiates wrt the params AFTER their cast
    to cfg.dtype, so the gradient tree materializes in bf16 instead of
    fp32 — classic mixed precision (fp32 master weights, low-precision
    grads). Optimizer state stays fp32 (or mu_dtype). Note the bf16 param
    copy it introduces is live across the whole step while fp32 grad
    leaves die progressively into the update, so the PEAK-memory effect is
    config-dependent (measured ~neutral on the gpt_1b HBM-limit bench —
    the remat policy, not this, was the fitting lever there).

    dcn_grad_compression='int8' (or the train_dcn_grad_compression config
    flag; requires a multi-slice mesh and a make_sharded_init built with
    the same flag) computes PER-SLICE gradients — the batch regains an
    explicit n_slices dim that a vmap(spmd_axis_name="dcn") backward keeps
    on its slice, so the automatic all-reduce GSPMD inserts spans only the
    intra-slice ICI axes — then means them across slices through the int8
    + error-feedback path of util/collective/compress.py. DCN sees one s8
    all-reduce plus the shared-scale f32 exchange instead of the fp32
    gradient all-reduce: ~4x fewer slice-boundary bytes, bit-identical
    'off' path."""
    compression = resolve_dcn_compression(dcn_grad_compression, mesh, rules)
    loss_fn = make_loss_fn(cfg, rules, mesh)
    if compression == "int8":
        n_slices = mesh.shape["dcn"]
        block = GLOBAL_CONFIG.train_dcn_grad_compression_block
        # the per-slice view: inside the vmapped region the dcn axis is
        # consumed by the stacked dim, so the inner table must not name it
        rules_in = rules.without_axis("dcn")
        loss_fn_in = make_loss_fn(cfg, rules_in, mesh)
        inner_bax = rules_in.mesh_axes("batch")
        stacked_shard = NamedSharding(mesh, P("dcn", inner_bax))

        def _stack_batch(batch):
            def split(x):
                x = x.reshape((n_slices, x.shape[0] // n_slices) + x.shape[1:])
                return jax.lax.with_sharding_constraint(x, stacked_shard)

            return jax.tree.map(split, batch)

    def _grads(params, batch):
        if compression == "int8":
            vg = jax.vmap(
                jax.value_and_grad(loss_fn_in),
                in_axes=(None, 0),
                spmd_axis_name="dcn",
            )
            losses, g = vg(params, _stack_batch(batch))
            return jnp.mean(losses), g
        return jax.value_and_grad(loss_fn)(params, batch)

    def step_fn(state: TrainState, batch) -> Tuple[TrainState, Dict[str, jax.Array]]:
        if compression == "int8":
            opt_state, ef = state.opt_state
        else:
            opt_state, ef = state.opt_state, None
        if compute_dtype_grads:
            # the model casts fp32 leaves to cfg.dtype at use anyway; doing
            # the cast OUTSIDE the grad means d(loss)/d(bf16 leaf) = bf16
            p_lo = jax.tree.map(
                lambda p: p.astype(cfg.dtype)
                if jnp.issubdtype(p.dtype, jnp.floating)
                else p,
                state.params,
            )
            loss, grads = _grads(p_lo, batch)
        else:
            loss, grads = _grads(state.params, batch)
        if compression == "int8":
            # mean over slices rides the int8 + error-feedback DCN path
            grads, ef = compress.compressed_slice_mean(grads, ef, block=block)
        updates, new_opt = optimizer.update(grads, opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        if compression == "int8":
            new_opt = (new_opt, ef)
        new_state = TrainState(state.step + 1, new_params, new_opt)
        return new_state, {"loss": loss, "grad_norm": gnorm, "step": new_state.step}

    b_shard = batch_sharding(mesh, rules)
    return jax.jit(
        step_fn,
        in_shardings=(state_shardings, b_shard),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,),
    )


def default_optimizer(
    lr: float = 3e-4,
    weight_decay: float = 0.1,
    warmup: int = 100,
    mu_dtype: Optional[Any] = None,
):
    """AdamW with warmup-cosine. mu_dtype=jnp.bfloat16 halves the momentum
    buffer — the lever that fits a ~1B-param model (fp32 params + adam
    state) in one v5e's 16G HBM."""
    sched = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, 10000, lr * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(
            sched, b1=0.9, b2=0.95, weight_decay=weight_decay, mu_dtype=mu_dtype
        ),
    )
