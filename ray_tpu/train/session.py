"""Worker-side training session.

Reference parity: ray.air.session (air/session.py:43 report, :97
get_checkpoint, :359 get_dataset_shard) + _TrainSession
(train/_internal/session.py:76): the user's train loop calls
session.report(metrics, checkpoint=...) and the trainer streams them out.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    trial_name: str = ""
    config: Dict[str, Any] = field(default_factory=dict)
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Any] = None
    # how many DCN slices the gang's hosts form (ScalingConfig.num_slices);
    # this worker belongs to slice world_rank // (world_size // num_slices)
    num_slices: int = 1
    # interleaved-1F1B depth (ScalingConfig.virtual_stages_per_device):
    # pp-outer train loops feed this to TransformerConfig.pp_interleave
    virtual_stages_per_device: int = 1
    results: "queue.Queue" = field(default_factory=queue.Queue)
    done: threading.Event = field(default_factory=threading.Event)

    def slice_rank(self) -> int:
        return self.world_rank // max(1, self.world_size // self.num_slices)


_ctx = threading.local()


def _set_context(ctx: TrainContext):
    _ctx.value = ctx


def get_context() -> TrainContext:
    ctx = getattr(_ctx, "value", None)
    if ctx is None:
        raise RuntimeError("session API used outside a train loop")
    return ctx


def report(
    metrics: Optional[Dict[str, Any]] = None, checkpoint: Optional[Any] = None, **kwargs
) -> None:
    """Accepts both calling styles the reference has shipped:
    report({"loss": x}) (AIR session API, air/session.py:43) and
    report(loss=x) (classic tune.report kwargs)."""
    merged = {**(metrics or {}), **kwargs}
    ctx = get_context()
    ctx.results.put({"metrics": merged, "checkpoint": checkpoint})


def get_checkpoint():
    return get_context().checkpoint


def get_dataset_shard(name: str = "train"):
    return get_context().dataset_shards.get(name)


def get_world_rank() -> int:
    return get_context().world_rank


def get_world_size() -> int:
    return get_context().world_size


def get_local_rank() -> int:
    return get_context().local_rank


def get_num_slices() -> int:
    return get_context().num_slices


def get_virtual_stages_per_device() -> int:
    return get_context().virtual_stages_per_device


def build_multislice_mesh(slice_spec=None, preset: str = "dp_outer"):
    """Build the gang's two-level (dcn x ICI) mesh + slice-aware rule table
    from the trainer's host topology (ScalingConfig.num_slices).

    Returns (mesh, rules). With num_slices=1 the dcn axis has size 1, so
    the same train loop runs single-slice and multi-slice unchanged.
    slice_spec is the PER-SLICE MeshSpec (tp/sp/ep must fit one slice);
    preset is "dp_outer" or "pp_outer" (parallel/multislice.py for the
    selection guidance)."""
    from ..parallel.mesh import MeshSpec
    from ..parallel.multislice import (
        SliceTopology,
        build_multislice_mesh as _build,
        multislice_rules,
    )

    ctx = get_context()
    topo = SliceTopology(ctx.num_slices, slice_spec or MeshSpec(dp=-1))
    rules = multislice_rules(preset)
    return _build(topo), rules
