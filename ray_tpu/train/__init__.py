"""ray_tpu.train: distributed training (Ray Train parity, TPU-native).

Where the reference's DataParallelTrainer spawns N torch-DDP workers with
NCCL process groups (train/torch/config.py:113), JaxTrainer spawns ONE
worker per HOST; each worker's train_loop compiles a single SPMD program
under jit over the pod-slice mesh and GSPMD owns all collectives.
"""

from .step import TrainState, make_train_step, make_sharded_init  # noqa: F401
from .trainer import JaxTrainer  # noqa: F401
from .config import ScalingConfig, RunConfig, FailureConfig, CheckpointConfig  # noqa: F401
from .session import report, get_context  # noqa: F401
from .checkpoint import (  # noqa: F401
    Checkpoint,
    save_checkpoint,
    restore_checkpoint,
    restore_train_state,
)
from .batch_predictor import BatchPredictor, JaxPredictor, Predictor  # noqa: F401,E402

from .._private.usage import record_library_usage as _rlu  # noqa: E402

_rlu("train")
