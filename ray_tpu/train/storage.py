"""URI storage providers: move checkpoint/experiment directories between
hosts without shared disk.

Reference parity: ray.air.checkpoint.Checkpoint.to_uri/from_uri
(air/checkpoint.py:707,735) + air/_internal/remote_storage.py (the
pyarrow-fs upload/download helpers behind them). The reference leans on
fsspec/pyarrow cloud filesystems; ray_tpu ships a small scheme registry
with three providers:

- file://   — local or NFS paths (copy).
- head://   — the CLUSTER's own storage: a chunked upload/download plane on
  the head, persisted under a stable directory on the head host
  (config: head_storage_dir), independent of the session. This is what
  makes multi-host restore work with zero external infrastructure: any
  node (or a new driver after a cluster restart on the same head host)
  can fetch by URI.
- gs://     — Google Cloud Storage via the `gsutil` CLI (TPU pod hosts ship
  it); errors clearly when unavailable. The transfer tool is pluggable for
  tests (RAY_TPU_GSUTIL env var).

Register custom schemes with `register_storage("s3", provider)`.
"""

from __future__ import annotations

import os
import shutil
import tarfile
import tempfile
from typing import Dict, List, Optional
from urllib.parse import urlparse

_CHUNK = 8 * 1024 * 1024


class StorageProvider:
    """One URI scheme's transfer operations. Directories are the unit."""

    def upload_dir(self, local_dir: str, uri: str) -> str:
        raise NotImplementedError

    def download_dir(self, uri: str, local_dir: str) -> str:
        raise NotImplementedError

    def exists(self, uri: str) -> bool:
        raise NotImplementedError

    def delete(self, uri: str) -> None:
        raise NotImplementedError

    def list(self, uri: str) -> List[str]:
        """Immediate children under a URI prefix (names, not full URIs)."""
        raise NotImplementedError

    def upload_file(self, local_path: str, uri: str) -> str:
        """Single-file upload — incremental writers (workflow step sync)
        push one file per durability point instead of re-shipping dirs."""
        raise NotImplementedError

    def download_file(self, uri: str, local_path: str) -> str:
        raise NotImplementedError


# --------------------------------------------------------------------------
# file://
# --------------------------------------------------------------------------


def _file_path(uri: str) -> str:
    p = urlparse(uri)
    return os.path.abspath(os.path.join("/", p.netloc + p.path))


class FileStorage(StorageProvider):
    def upload_dir(self, local_dir: str, uri: str) -> str:
        dest = _file_path(uri)
        if os.path.abspath(local_dir) != dest:
            os.makedirs(os.path.dirname(dest) or "/", exist_ok=True)
            # REPLACE, never merge — and via rename pairs, so a concurrent
            # reader sees the old tree or the new one, never a partial copy
            tmp = f"{dest}.new-{os.getpid()}"
            old = f"{dest}.old-{os.getpid()}"
            try:
                shutil.copytree(local_dir, tmp)
                if os.path.isdir(dest):
                    os.rename(dest, old)
                os.rename(tmp, dest)
            finally:
                shutil.rmtree(old, ignore_errors=True)
                shutil.rmtree(tmp, ignore_errors=True)
        return uri

    def download_dir(self, uri: str, local_dir: str) -> str:
        src = _file_path(uri)
        if not os.path.isdir(src):
            raise FileNotFoundError(f"no directory at {uri}")
        if os.path.abspath(local_dir) != src:
            shutil.copytree(src, local_dir, dirs_exist_ok=True)
        return local_dir

    def exists(self, uri: str) -> bool:
        return os.path.exists(_file_path(uri))

    def delete(self, uri: str) -> None:
        shutil.rmtree(_file_path(uri), ignore_errors=True)

    def list(self, uri: str) -> List[str]:
        p = _file_path(uri)
        return sorted(os.listdir(p)) if os.path.isdir(p) else []

    def upload_file(self, local_path: str, uri: str) -> str:
        dest = _file_path(uri)
        os.makedirs(os.path.dirname(dest) or "/", exist_ok=True)
        shutil.copy2(local_path, dest)
        return uri

    def download_file(self, uri: str, local_path: str) -> str:
        src = _file_path(uri)
        if not os.path.isfile(src):
            raise FileNotFoundError(f"no file at {uri}")
        os.makedirs(os.path.dirname(local_path) or "/", exist_ok=True)
        shutil.copy2(src, local_path)
        return local_path


# --------------------------------------------------------------------------
# head:// — cluster-hosted storage (chunked over the head protocol)
# --------------------------------------------------------------------------


def _head_key(uri: str) -> str:
    p = urlparse(uri)
    key = (p.netloc + p.path).strip("/")
    norm = os.path.normpath(key)
    if not key or norm.startswith("..") or os.path.isabs(norm):
        raise ValueError(f"bad head:// key {key!r}")
    return norm


class HeadStorage(StorageProvider):
    """Directories travel as tar streams in chunks over the head socket;
    the head persists them under head_storage_dir (survives the session).
    Requires a live cluster connection (ray_tpu.init)."""

    def _worker(self):
        import ray_tpu
        from ray_tpu._private.worker import global_worker

        if not global_worker.connected:
            ray_tpu.init(address="auto")
        return global_worker

    def _put_path(self, local_path: str, key: str):
        w = self._worker()
        token = w.request({"t": "stor_begin", "key": key})
        with open(local_path, "rb") as f:
            while True:
                chunk = f.read(_CHUNK)
                if not chunk:
                    break
                w.request({"t": "stor_chunk", "token": token, "data": chunk})
        w.request({"t": "stor_end", "token": token})

    def _get_path(self, key: str, out, uri: str):
        """Stream key's bytes into file object `out` through a read session:
        the head pins one version behind an open fd, so a concurrent
        overwrite can't interleave two versions into the download."""
        w = self._worker()
        opened = w.request({"t": "stor_open", "key": key})
        if opened is None:
            raise FileNotFoundError(f"no object at {uri}")
        token, size = opened
        try:
            off = 0
            while off < size:
                data = w.request(
                    {"t": "stor_read", "token": token, "offset": off, "size": _CHUNK}
                )
                if not data:
                    raise RuntimeError(f"{uri} truncated during download")
                out.write(data)
                off += len(data)
        finally:
            try:
                w.request({"t": "stor_close", "token": token})
            except Exception:
                pass

    def upload_dir(self, local_dir: str, uri: str) -> str:
        with tempfile.NamedTemporaryFile(suffix=".tar") as tf:
            with tarfile.open(tf.name, "w") as tar:
                tar.add(local_dir, arcname=".")
            self._put_path(tf.name, _head_key(uri))
        return uri

    def download_dir(self, uri: str, local_dir: str) -> str:
        os.makedirs(local_dir, exist_ok=True)
        with tempfile.NamedTemporaryFile(suffix=".tar") as tf:
            self._get_path(_head_key(uri), tf, uri)
            tf.flush()
            with tarfile.open(tf.name) as tar:
                tar.extractall(local_dir, filter="data")
        return local_dir

    def upload_file(self, local_path: str, uri: str) -> str:
        self._put_path(local_path, _head_key(uri))
        return uri

    def download_file(self, uri: str, local_path: str) -> str:
        os.makedirs(os.path.dirname(local_path) or "/", exist_ok=True)
        tmp = f"{local_path}.dl-{os.getpid()}"
        try:
            with open(tmp, "wb") as out:
                self._get_path(_head_key(uri), out, uri)
            os.replace(tmp, local_path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        return local_path

    def exists(self, uri: str) -> bool:
        return self._worker().request({"t": "stor_size", "key": _head_key(uri)}) is not None

    def delete(self, uri: str) -> None:
        self._worker().request({"t": "stor_del", "key": _head_key(uri)})

    def list(self, uri: str) -> List[str]:
        return self._worker().request({"t": "stor_list", "prefix": _head_key(uri)})


# --------------------------------------------------------------------------
# gs:// — gsutil CLI (pluggable binary for tests / airgapped CI)
# --------------------------------------------------------------------------


class GcsStorage(StorageProvider):
    def _tool(self) -> List[str]:
        tool = os.environ.get("RAY_TPU_GSUTIL") or shutil.which("gsutil")
        if not tool:
            raise RuntimeError(
                "gs:// storage needs the gsutil CLI (not found on PATH; "
                "set RAY_TPU_GSUTIL to override)"
            )
        return [tool]

    def _run(self, *args: str, check: bool = True):
        import subprocess

        proc = subprocess.run(
            self._tool() + list(args), capture_output=True, text=True
        )
        if check and proc.returncode != 0:
            raise RuntimeError(
                f"gsutil {' '.join(args)} failed: {proc.stderr.strip()}"
            )
        return proc

    def upload_dir(self, local_dir: str, uri: str) -> str:
        # trailing-slash contract: copy CONTENTS of local_dir under uri
        self._run("-m", "rsync", "-r", local_dir, uri.rstrip("/"))
        return uri

    def download_dir(self, uri: str, local_dir: str) -> str:
        os.makedirs(local_dir, exist_ok=True)
        self._run("-m", "rsync", "-r", uri.rstrip("/"), local_dir)
        return local_dir

    def exists(self, uri: str) -> bool:
        return self._run("ls", uri, check=False).returncode == 0

    def delete(self, uri: str) -> None:
        self._run("-m", "rm", "-r", uri, check=False)

    def list(self, uri: str) -> List[str]:
        proc = self._run("ls", uri.rstrip("/") + "/", check=False)
        out = []
        for line in proc.stdout.splitlines():
            line = line.strip().rstrip("/")
            if line:
                out.append(line.rsplit("/", 1)[-1])
        return out

    def upload_file(self, local_path: str, uri: str) -> str:
        self._run("cp", local_path, uri)
        return uri

    def download_file(self, uri: str, local_path: str) -> str:
        os.makedirs(os.path.dirname(local_path) or "/", exist_ok=True)
        proc = self._run("cp", uri, local_path, check=False)
        if proc.returncode != 0:
            raise FileNotFoundError(f"gsutil cp failed for {uri}: {proc.stderr.strip()}")
        return local_path


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

_PROVIDERS: Dict[str, StorageProvider] = {
    "file": FileStorage(),
    "head": HeadStorage(),
    "gs": GcsStorage(),
}


def register_storage(scheme: str, provider: StorageProvider) -> None:
    _PROVIDERS[scheme] = provider


def is_uri(path: Optional[str]) -> bool:
    return bool(path) and "://" in str(path)


def get_storage(uri: str) -> StorageProvider:
    scheme = urlparse(uri).scheme
    provider = _PROVIDERS.get(scheme)
    if provider is None:
        raise ValueError(
            f"no storage provider for scheme {scheme!r} "
            f"(known: {sorted(_PROVIDERS)}); register_storage() to add one"
        )
    return provider


def upload_dir(local_dir: str, uri: str) -> str:
    return get_storage(uri).upload_dir(local_dir, uri)


_TMP_DOWNLOADS: List[str] = []


def _clean_tmp_downloads():
    for d in _TMP_DOWNLOADS:
        shutil.rmtree(d, ignore_errors=True)
    _TMP_DOWNLOADS.clear()


def download_dir(uri: str, local_dir: Optional[str] = None) -> str:
    if local_dir is None:
        # default-temp downloads are process-scoped scratch: remember them
        # and sweep at exit so repeated restores don't accumulate copies
        local_dir = tempfile.mkdtemp(prefix="ray_tpu_dl_")
        if not _TMP_DOWNLOADS:
            import atexit

            atexit.register(_clean_tmp_downloads)
        _TMP_DOWNLOADS.append(local_dir)
    return get_storage(uri).download_dir(uri, local_dir)


def uri_join(uri: str, *parts: str) -> str:
    return "/".join([uri.rstrip("/")] + [p.strip("/") for p in parts])
