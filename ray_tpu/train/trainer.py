"""JaxTrainer: the DataParallelTrainer equivalent.

Reference parity: train/data_parallel_trainer.py:58 + BackendExecutor
(train/_internal/backend_executor.py:104) + WorkerGroup (worker_group.py:193).
Differences, by TPU design:
  - one worker actor per HOST (not per device); the worker's train loop
    builds a Mesh over the host's chips (or the whole slice when
    jax.distributed is enabled) and compiles ONE SPMD program.
  - the backend seam that runs dist.init_process_group in the reference
    (train/torch/config.py:113) here passes coordinator info for
    jax.distributed.initialize — after which GSPMD owns every collective.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

import ray_tpu
from ray_tpu.util import placement_group, PlacementGroupSchedulingStrategy

from .config import RunConfig, ScalingConfig
from .session import TrainContext, _set_context


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint: Optional[Any] = None
    error: Optional[Exception] = None


class TrainWorker:
    """Actor hosting one training process (one host's SPMD shard)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.ctx: Optional[TrainContext] = None
        self._done = threading.Event()
        self._ret = None
        self._err: Optional[Exception] = None

    def ready(self):
        return True

    def get_coordinator_address(self) -> str:
        """Rank-0 upcall: a `host:port` the REST of the gang can dial for
        jax.distributed rendezvous. Resolved AFTER placement, on the worker
        itself — the reference does exactly this for the torch rendezvous
        (train/torch/config.py:113-170 master addr/port queried from worker
        0; backend_executor.py:342) — a driver-picked loopback address
        cannot form a mesh across hosts."""
        import socket

        from ray_tpu._private.head import _advertise_host

        host = _advertise_host("0.0.0.0")  # this node's outbound/routable IP
        s = socket.socket()
        s.bind(("0.0.0.0", 0))
        port = s.getsockname()[1]
        s.close()  # jax.distributed binds it next; standard rendezvous race
        return f"{host}:{port}"

    def run(
        self,
        train_fn: Callable,
        config: Dict[str, Any],
        datasets=None,
        checkpoint=None,
        coordinator: Optional[str] = None,
        num_slices: int = 1,
        virtual_stages_per_device: int = 1,
    ):
        dist_inited = False
        if self.world_size > 1 and coordinator:
            import jax

            from ray_tpu._private.config import GLOBAL_CONFIG as gcfg

            kwargs = dict(
                coordinator_address=coordinator,
                num_processes=self.world_size,
                process_id=self.rank,
            )
            hb_s = int(gcfg.train_dist_heartbeat_timeout_s)
            if hb_s > 0:
                # bound gang peer-death detection: jax's default
                # coordination-service heartbeat budget (10s x 10 missing
                # = ~100s) parks every SURVIVING rank that long at the
                # shutdown barrier when a gang member dies hard — the
                # latency floor of the whole gang-restart path. The knobs
                # are not in the public initialize() on this jax line, so
                # reach the internal state initializer (same call the
                # wrapper makes) and fall back to defaults on any other
                # jax internals. Heartbeats run on a C++ thread, so a
                # long jit compile cannot miss them.
                interval = max(1, hb_s // 6)
                missing = max(2, -(-hb_s // interval))
                try:
                    from jax._src import distributed as _dist
                    from jax._src import xla_bridge as _xb

                    if _xb.backends_are_initialized():
                        raise RuntimeError(
                            "jax.distributed must initialize before any "
                            "JAX computations"
                        )
                    _dist.global_state.initialize(
                        **kwargs,
                        service_heartbeat_interval_seconds=interval,
                        service_max_missing_heartbeats=missing,
                        client_heartbeat_interval_seconds=interval,
                        client_max_missing_heartbeats=missing,
                    )
                    dist_inited = True
                except (ImportError, AttributeError, TypeError):
                    pass  # unknown jax internals: default heartbeats
            if not dist_inited:
                jax.distributed.initialize(**kwargs)
                dist_inited = True
        self.ctx = TrainContext(
            world_rank=self.rank,
            world_size=self.world_size,
            local_rank=0,
            config=config or {},
            dataset_shards=datasets or {},
            checkpoint=checkpoint,
            num_slices=num_slices,
            virtual_stages_per_device=virtual_stages_per_device,
        )
        _set_context(self.ctx)
        try:
            import inspect

            sig = inspect.signature(train_fn)
            self._ret = train_fn(config) if len(sig.parameters) >= 1 else train_fn()
            return self._ret
        except BaseException as e:
            self._err = e
            raise
        finally:
            self.ctx.done.set()
            if dist_inited:
                import jax

                try:  # leave the process reusable for a gang-restart attempt
                    jax.distributed.shutdown()
                except Exception:
                    pass

    def next_results(self, max_items: int = 100):
        """Drain queued session.report() payloads (non-blocking)."""
        out = []
        if self.ctx is None:
            return out, False
        while len(out) < max_items:
            try:
                out.append(self.ctx.results.get_nowait())
            except Exception:
                break
        return out, self.ctx.done.is_set()


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint=None,
    ):
        self._train_fn = train_loop_per_worker
        self._config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._datasets = datasets or {}
        self._resume_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        """Run the training gang; on worker failure, restart the WHOLE gang
        from the last reported checkpoint up to
        run_config.failure_config.max_failures times (SURVEY §7.2: pjit
        programs are SPMD gangs — all-or-nothing restart from checkpoint is
        the tractable elastic-training v1; reference analogue: Tune
        restarting a trial from its checkpoint under FailureConfig)."""
        fc = self.run_config.failure_config
        resume = self._resume_checkpoint
        history: List[Dict[str, Any]] = []
        failures = 0
        while True:
            try:
                result = self._fit_attempt(resume)
            except Exception as e:  # setup-phase failure (spawn/pg/ready)
                result = Result(error=e)
            # keep the full metric history across restarts
            history.extend(result.metrics_history)
            result.metrics_history = list(history)
            if result.error is None or failures >= fc.max_failures:
                return result
            failures += 1
            resume = result.checkpoint if result.checkpoint is not None else resume
            logger.warning(
                "training gang failed (%r); restart %d/%d from %s",
                result.error, failures, fc.max_failures,
                "last checkpoint" if resume is not None else "scratch",
            )

    def _fit_attempt(self, resume_checkpoint) -> Result:
        """One gang attempt. Setup failures raise (fit() settles them into
        a Result); workers and the placement group are ALWAYS torn down —
        a leaked half-built gang would starve the restart attempt."""
        pg_box: List[Any] = []
        workers: List[Any] = []
        try:
            return self._fit_attempt_inner(resume_checkpoint, pg_box.append, workers)
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            if pg_box:
                from ray_tpu.util import remove_placement_group

                try:
                    remove_placement_group(pg_box[0])
                except Exception:
                    pass

    def _fit_attempt_inner(self, resume_checkpoint, set_pg, workers) -> Result:
        sc = self.scaling_config
        n = sc.num_workers
        res = sc.worker_resources()
        strategy = None
        if n > 1:
            pg = placement_group([dict(res) for _ in range(n)], strategy=sc.placement_strategy)
            set_pg(pg)
            if not pg.wait(120):
                raise RuntimeError(
                    f"placement group for {n} training workers not placeable "
                    f"within 120s (bundles: {res})"
                )
            strategy = PlacementGroupSchedulingStrategy(placement_group=pg)

        WorkerCls = ray_tpu.remote(TrainWorker)
        opts: Dict[str, Any] = {
            "num_cpus": res.get("CPU", 1),
            "max_concurrency": 2,  # run + next_results pump
        }
        if res.get("TPU"):
            opts["num_tpus"] = res["TPU"]
        if strategy is not None:
            opts["scheduling_strategy"] = strategy
        extra = {k: v for k, v in res.items() if k not in ("CPU", "TPU")}
        if extra:
            opts["resources"] = extra
        env_vars = dict(sc.env_vars)
        if sc.dcn_grad_compression is not None:
            # pin the gang-wide compression mode: every host must compile
            # the same step (the int8 path changes the opt_state pytree)
            env_vars.setdefault(
                "RAY_TPU_TRAIN_DCN_GRAD_COMPRESSION", sc.dcn_grad_compression
            )
        if env_vars:
            opts["runtime_env"] = {"env_vars": env_vars}

        workers.extend(
            WorkerCls.options(**opts).remote(rank, n) for rank in range(n)
        )
        # timeout: unschedulable/crashing workers must raise into the
        # restart loop, not block setup forever
        ray_tpu.get([w.ready.remote() for w in workers], timeout=180)

        # rendezvous: rank-0 worker (placed!) picks the coordinator address
        # on ITS node and the driver broadcasts it to the gang
        coordinator = None
        if n > 1:
            coordinator = ray_tpu.get(
                workers[0].get_coordinator_address.remote(), timeout=60
            )

        # shard datasets across workers (streaming split)
        def shard_for(rank):
            out = {}
            for name, ds in self._datasets.items():
                if hasattr(ds, "split_at"):
                    out[name] = ds.split_at(rank, n)
                else:
                    out[name] = ds
            return out

        run_refs = [
            w.run.remote(
                self._train_fn, self._config, shard_for(i), resume_checkpoint,
                coordinator, sc.num_slices, sc.virtual_stages_per_device,
            )
            for i, w in enumerate(workers)
        ]

        result = Result()
        done = False
        try:
            while not done:
                reports, rank0_done = ray_tpu.get(workers[0].next_results.remote())
                for rep in reports:
                    result.metrics_history.append(rep["metrics"])
                    result.metrics = rep["metrics"]
                    if rep.get("checkpoint") is not None:
                        result.checkpoint = rep["checkpoint"]
                if rank0_done:
                    done = True
                else:
                    ready, _ = ray_tpu.wait(run_refs, num_returns=len(run_refs), timeout=0.2)
                    if len(ready) == len(run_refs):
                        done = True
        except Exception as e:  # a worker died mid-run: settle the error so
            result.error = e  # fit()'s gang-restart loop can act on it
        # surface worker errors (rank 0 first)
        if result.error is None:
            try:
                ray_tpu.get(run_refs)
            except Exception as e:  # noqa: BLE001
                result.error = e
        # final drain (best-effort: the pump actor may be gone)
        try:
            reports, _ = ray_tpu.get(workers[0].next_results.remote())
            for rep in reports:
                result.metrics_history.append(rep["metrics"])
                result.metrics = rep["metrics"]
                if rep.get("checkpoint") is not None:
                    result.checkpoint = rep["checkpoint"]
        except Exception:
            pass
        # worker + placement-group teardown happens in _fit_attempt's
        # finally (covers setup failures too)
        return result
