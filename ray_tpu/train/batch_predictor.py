"""Batch inference: map a trained checkpoint over a Dataset.

Reference parity: python/ray/train/batch_predictor.py (BatchPredictor) +
the air Predictor interface (torch_predictor.py) — rebuilt on the data
layer's actor-pool map operator: each pool worker loads the checkpoint
ONCE (the expensive part), then streams batches through `predict`, with
the executor's windowed backpressure bounding memory.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Type

from .checkpoint import Checkpoint


class Predictor:
    """Interface: construct from a checkpoint, predict on host batches.

    JAX-native subclasses jit their apply function in __init__ (once per
    pool worker) so per-batch work is a single compiled call."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Any) -> Any:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Predictor over a jittable apply_fn(params, batch) -> output.

    `params_loader(checkpoint) -> params` turns the checkpoint into a
    parameter pytree (e.g. restore_checkpoint with an abstract state)."""

    def __init__(self, params: Any, apply_fn: Callable[[Any, Any], Any]):
        import jax

        self.params = params
        self.apply_fn = jax.jit(apply_fn)

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint: Checkpoint,
        *,
        apply_fn: Callable[[Any, Any], Any],
        params_loader: Callable[[Checkpoint], Any],
    ) -> "JaxPredictor":
        return cls(params_loader(checkpoint), apply_fn)

    def predict(self, batch: Any):
        import numpy as np

        out = self.apply_fn(self.params, batch)
        # back to host types so downstream data ops stay framework-free
        import jax

        return jax.tree.map(lambda x: np.asarray(x), out)


class _PredictorWorker:
    """Callable class for the actor pool: checkpoint -> predictor once."""

    def __init__(self, predictor_cls, checkpoint, kwargs):
        self.predictor = predictor_cls.from_checkpoint(checkpoint, **kwargs)

    def __call__(self, batch):
        return self.predictor.predict(batch)


class BatchPredictor:
    """Maps a checkpoint over datasets (reference: batch_predictor.py).

    predictor = BatchPredictor(ckpt, JaxPredictor, apply_fn=..., params_loader=...)
    preds = predictor.predict(ds, batch_size=512, num_actors=4)
    """

    def __init__(
        self,
        checkpoint: Checkpoint,
        predictor_cls: Type[Predictor],
        **predictor_kwargs,
    ):
        self.checkpoint = checkpoint
        self.predictor_cls = predictor_cls
        self.predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(
        cls, checkpoint: Checkpoint, predictor_cls: Type[Predictor], **kwargs
    ) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **kwargs)

    def predict(
        self,
        dataset,
        *,
        batch_size: Optional[int] = 256,
        num_actors: int = 2,
        compute: str = "actors",
    ):
        """Lazy: returns a Dataset whose blocks are prediction outputs."""
        return dataset.map_batches(
            _PredictorWorker,
            batch_size=batch_size,
            compute=compute,
            num_actors=num_actors,
            fn_constructor_args=(
                self.predictor_cls,
                self.checkpoint,
                self.predictor_kwargs,
            ),
        )
