"""ObjectRef: a handle to a (possibly pending) object in the cluster.

Reference parity: python/ray/_raylet.pyx ObjectRef + the distributed
refcounting hooks of reference_count.h:61. Each live Python ObjectRef holds
one reference registered with the owner directory; unpickling a ref in any
process registers a new one (borrower registration, simplified).
"""

from __future__ import annotations

import concurrent.futures
from typing import Optional

from ._private import serialization


class ObjectRef:
    __slots__ = ("id", "_registered", "_escaped", "_owner", "__weakref__")

    def __init__(self, id_hex: str, skip_adding_local_ref: bool = False):
        from ._private.worker import global_worker

        self.id = id_hex
        self._registered = False
        # True once this ref has been pickled (task arg, put, actor call):
        # another process may now hold the id, so its envelope MUST be
        # forwarded to the head even if this local ref dies first
        self._escaped = False
        # owner handle = the ref minted at submit/put whose +1 rides the
        # result forward; only ITS death may cancel that forward. Duplicate
        # handles (unpickled copies) registered their own +1 and must
        # always decrement instead.
        self._owner = skip_adding_local_ref
        if not skip_adding_local_ref and global_worker.connected:
            global_worker.add_object_ref(id_hex)
            self._registered = True
        elif skip_adding_local_ref:
            self._registered = True  # ref was pre-counted at creation

    def hex(self) -> str:
        return self.id

    def binary(self) -> bytes:
        return bytes.fromhex(self.id)

    def task_id(self) -> str:
        return self.id[:-8]

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and self.id == other.id

    def __repr__(self):
        return f"ObjectRef({self.id})"

    def __reduce__(self):
        serialization.record_contained_ref(self)
        self._escaped = True
        return (ObjectRef, (self.id,))

    def __del__(self):
        try:
            if self._registered:
                from ._private.worker import global_worker

                global_worker.remove_object_ref(
                    self.id, escaped=self._escaped or not self._owner
                )
        except Exception:
            pass

    def future(self) -> concurrent.futures.Future:
        """Return a concurrent.futures.Future resolving to the object value."""
        from ._private.worker import global_worker

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _poll():
            try:
                fut.set_result(global_worker.get(self))
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading

        threading.Thread(target=_poll, daemon=True).start()
        return fut

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def _make_ref(id_hex: str) -> ObjectRef:
    return ObjectRef(id_hex)


# Streamed-generator task returns index their yielded objects from this
# offset in the ObjectID index space, far above any static num_returns
# (reference: object_id.h reserves the dynamic-return index range the same
# way for streaming generators).
STREAM_INDEX_BASE = 1_000_000


def stream_object_id(task_id_hex: str, index: int) -> str:
    from ._private.ids import ObjectID, TaskID

    return ObjectID.for_return(
        TaskID.from_hex(task_id_hex), STREAM_INDEX_BASE + index
    ).hex()


class StreamDescriptor:
    """The terminal value of a streaming/dynamic generator task: how many
    objects were yielded (their ids derive from the task id). ray_tpu.get
    on the task's ref resolves this to an ObjectRefGenerator."""

    def __init__(self, task_id_hex: str, count: int):
        self.task_id = task_id_hex
        self.count = count

    def __reduce__(self):
        return (StreamDescriptor, (self.task_id, self.count))


class ObjectRefGenerator:
    """Iterator over the ObjectRefs a generator task yields (reference:
    python/ray/_raylet.pyx ObjectRefGenerator / DynamicObjectRefGenerator).
    Yields become consumable AS the remote generator produces them —
    iteration blocks on the next yield OR task completion, whichever comes
    first; a mid-stream task error surfaces after the yields that preceded
    it."""

    def __init__(self, completion_ref: "ObjectRef", count: Optional[int] = None):
        # Ownership model: every yield's baseline (+1 from the worker's
        # put) belongs to the COMPLETION object — the head releases them
        # all when it is freed. Refs handed out here are plain borrows
        # (+1/-1 of their own), so consuming the same dynamic stream twice
        # is safe and an abandoned generator leaks nothing once the
        # completion ref dies.
        self._completion_ref = completion_ref
        self._task_id = completion_ref.task_id()
        self._i = 0
        self._count: Optional[int] = count
        self._pending_ref: Optional[ObjectRef] = None

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def _take_pending(self) -> "ObjectRef":
        ref = self._pending_ref
        if ref is None:
            ref = ObjectRef(stream_object_id(self._task_id, self._i))
        self._pending_ref = None
        self._i += 1
        return ref

    def __next__(self) -> "ObjectRef":
        from ._private.worker import global_worker

        while True:
            if self._count is not None:
                if self._i >= self._count:
                    self._pending_ref = None  # borrow: safe to just drop
                    raise StopIteration
                return self._take_pending()
            if self._pending_ref is None:
                self._pending_ref = ObjectRef(stream_object_id(self._task_id, self._i))
            ready, _ = global_worker.wait(
                [self._pending_ref, self._completion_ref], num_returns=1, timeout=None
            )
            if ready and ready[0].id == self._pending_ref.id:
                return self._take_pending()
            # completion first: a yield with this index either never
            # happened (StopIteration / task error) or raced in just
            # before the terminal marker — resolve the count to decide
            desc = global_worker.get(self._completion_ref)  # raises task errors
            if not isinstance(desc, StreamDescriptor):
                raise TypeError(
                    f"expected a streaming task terminal marker, got {type(desc)}"
                )
            self._count = desc.count

    def completed(self) -> "ObjectRef":
        """The ref that settles when the generator task finishes."""
        return self._completion_ref
