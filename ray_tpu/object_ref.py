"""ObjectRef: a handle to a (possibly pending) object in the cluster.

Reference parity: python/ray/_raylet.pyx ObjectRef + the distributed
refcounting hooks of reference_count.h:61. Each live Python ObjectRef holds
one reference registered with the owner directory; unpickling a ref in any
process registers a new one (borrower registration, simplified).
"""

from __future__ import annotations

import concurrent.futures
from typing import Optional

from ._private import serialization


class ObjectRef:
    __slots__ = ("id", "_registered", "_escaped", "_owner", "__weakref__")

    def __init__(self, id_hex: str, skip_adding_local_ref: bool = False):
        from ._private.worker import global_worker

        self.id = id_hex
        self._registered = False
        # True once this ref has been pickled (task arg, put, actor call):
        # another process may now hold the id, so its envelope MUST be
        # forwarded to the head even if this local ref dies first
        self._escaped = False
        # owner handle = the ref minted at submit/put whose +1 rides the
        # result forward; only ITS death may cancel that forward. Duplicate
        # handles (unpickled copies) registered their own +1 and must
        # always decrement instead.
        self._owner = skip_adding_local_ref
        if not skip_adding_local_ref and global_worker.connected:
            global_worker.add_object_ref(id_hex)
            self._registered = True
        elif skip_adding_local_ref:
            self._registered = True  # ref was pre-counted at creation

    def hex(self) -> str:
        return self.id

    def binary(self) -> bytes:
        return bytes.fromhex(self.id)

    def task_id(self) -> str:
        return self.id[:-8]

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and self.id == other.id

    def __repr__(self):
        return f"ObjectRef({self.id})"

    def __reduce__(self):
        serialization.record_contained_ref(self)
        self._escaped = True
        return (ObjectRef, (self.id,))

    def __del__(self):
        try:
            if self._registered:
                from ._private.worker import global_worker

                global_worker.remove_object_ref(
                    self.id, escaped=self._escaped or not self._owner
                )
        except Exception:
            pass

    def future(self) -> concurrent.futures.Future:
        """Return a concurrent.futures.Future resolving to the object value."""
        from ._private.worker import global_worker

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _poll():
            try:
                fut.set_result(global_worker.get(self))
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading

        threading.Thread(target=_poll, daemon=True).start()
        return fut

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def _make_ref(id_hex: str) -> ObjectRef:
    return ObjectRef(id_hex)
