"""In-process multi-node cluster for tests.

Reference parity: python/ray/cluster_utils.py:99 (Cluster, add_node :165) —
the highest-leverage test fixture in the reference (SURVEY §4.2): N logical
nodes share one head; scheduling/PG/failover tests run single-machine.
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional

from ._private.worker import global_worker

_node_counter = itertools.count(1)


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: Optional[dict] = None):
        import ray_tpu

        self._nodes = []
        if initialize_head:
            head_node_args = head_node_args or {}
            ray_tpu.init(**head_node_args)

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> str:
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update({k: float(v) for k, v in (resources or {}).items()})
        node_id = f"node-{next(_node_counter)}"
        global_worker.request(
            {"t": "add_node", "node_id": node_id, "resources": res, "labels": labels or {}}
        )
        self._nodes.append(node_id)
        return node_id

    def remove_node(self, node_id: str) -> None:
        global_worker.request({"t": "remove_node", "node_id": node_id})
        if node_id in self._nodes:
            self._nodes.remove(node_id)

    def shutdown(self):
        import ray_tpu

        ray_tpu.shutdown()
