"""In-process multi-node cluster for tests.

Reference parity: python/ray/cluster_utils.py:99 (Cluster, add_node :165) —
the highest-leverage test fixture in the reference (SURVEY §4.2). Like the
reference (which starts real raylet processes, add_node :165), add_node
starts a REAL per-host agent process that joins the head over localhost TCP:
node death, cross-node object pulls, and failover are all exercised for
real. `add_node(logical=True)` keeps the old resource-record-only mode for
pure scheduling tests.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, Optional

from ._private.worker import global_worker

_node_counter = itertools.count(1)


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: Optional[dict] = None):
        import ray_tpu

        self._nodes = []
        self._procs: Dict[str, subprocess.Popen] = {}
        if initialize_head:
            head_node_args = head_node_args or {}
            ray_tpu.init(**head_node_args)

    @property
    def head_tcp_address(self) -> Optional[str]:
        node = global_worker.node
        return None if node is None else node.head.tcp_address

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        logical: bool = False,
        wait: bool = True,
    ) -> str:
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update({k: float(v) for k, v in (resources or {}).items()})
        node_id = f"node-{next(_node_counter)}"
        if logical:
            global_worker.request(
                {"t": "add_node", "node_id": node_id, "resources": res, "labels": labels or {}}
            )
            self._nodes.append(node_id)
            return node_id
        address = self.head_tcp_address
        if address is None:
            raise RuntimeError("head has no TCP listener; cannot start real nodes")
        argv = [
            sys.executable,
            "-m",
            "ray_tpu._private.agent_main",
            "--address",
            address,
            "--node-id",
            node_id,
            "--resources",
            json.dumps(res),
            "--labels",
            json.dumps(labels or {}),
        ]
        env = dict(os.environ)
        from ._private.spawn import child_pythonpath

        env["PYTHONPATH"] = child_pythonpath(inherited=env.get("PYTHONPATH"))
        # agents never own the chips; workers they spawn default to cpu jax
        env.setdefault("JAX_PLATFORMS", "cpu")
        # own process group: kill_node(force) can take the whole node (agent
        # + its workers) down at once, like killing a host
        proc = subprocess.Popen(
            [sys.executable, "-S"] + argv[1:], env=env, start_new_session=True
        )
        self._procs[node_id] = proc
        self._nodes.append(node_id)
        if wait:
            self.wait_for_node(node_id)
        return node_id

    def wait_for_node(self, node_id: str, timeout: float = 20.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            nodes = global_worker.request({"t": "nodes"})
            if any(n["node_id"] == node_id and n["alive"] for n in nodes):
                return
            proc = self._procs.get(node_id)
            if proc is not None and proc.poll() is not None:
                raise RuntimeError(
                    f"agent for {node_id} exited rc={proc.returncode} before registering"
                )
            time.sleep(0.05)
        raise TimeoutError(f"node {node_id} did not register within {timeout}s")

    def kill_node(self, node_id: str) -> None:
        """SIGKILL the node's whole process group (agent + workers) — the
        chaos path (reference: test_utils.py:1370 NodeKillerActor)."""
        proc = self._procs.pop(node_id, None)
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, OSError):
                proc.kill()
        if node_id in self._nodes:
            self._nodes.remove(node_id)

    def remove_node(self, node_id: str) -> None:
        global_worker.request({"t": "remove_node", "node_id": node_id})
        proc = self._procs.pop(node_id, None)
        if proc is not None:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        if node_id in self._nodes:
            self._nodes.remove(node_id)

    def shutdown(self):
        import ray_tpu

        ray_tpu.shutdown()
        # one SIGTERM pass over every agent group FIRST: agents exit on it
        # immediately (default handler), where the old per-proc wait(5)
        # expired serially and SIGKILLed the group anyway — a flat
        # multi-second tax on every cluster-using test's teardown
        for proc in self._procs.values():
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
                except (ProcessLookupError, OSError):
                    proc.terminate()
        for node_id, proc in list(self._procs.items()):
            try:
                proc.wait(timeout=2)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, OSError):
                    proc.kill()
        self._procs.clear()
