"""Offline RL algorithms: BC, MARWIL, and discrete CQL.

Reference parity: rllib/algorithms/bc/, rllib/algorithms/marwil/marwil.py
(advantage-weighted behavior cloning; BC is MARWIL with beta=0) and
rllib/algorithms/cql/ (conservative Q-learning). TPU-first redesign: each
update — every minibatch of every epoch — is ONE jitted lax.scan program
over a device-resident copy of the offline batch, instead of the
reference's Python minibatch loop.

All three train purely from an offline dataset written by
`rl.offline.JsonWriter` (no env interaction); pass `env` in the config only
if you want periodic evaluation rollouts.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm
from .config import AlgorithmConfig
from .learner import Learner, TrainState
from .models import ac_apply, init_ac_params, init_q_params, q_apply
from .offline import JsonReader
from .sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS, SampleBatch


def _device_batch(batch: SampleBatch, keys) -> Dict[str, jnp.ndarray]:
    return {k: jnp.asarray(np.asarray(batch[k])) for k in keys}


def _minibatch_scan(update_one, n_rows: int, minibatch_size: int, num_epochs: int):
    """Build the scan-of-scans driver shared by the offline learners:
    epochs x minibatches with per-epoch reshuffle, all inside jit.

    `update_one(state, mb, *extra)` receives `extra` traced as arguments of
    the compiled program — anything that changes between calls (e.g. CQL's
    target params) MUST ride through here, not a closure: jit would bake a
    closed-over array in as a constant."""
    mbs = max(1, min(minibatch_size, n_rows))
    n_mb = max(1, n_rows // mbs)

    def epoch(carry, _):
        state, data, extra = carry
        rng, sub = jax.random.split(state.rng)
        perm = jax.random.permutation(sub, n_rows)
        state = state._replace(rng=rng)

        def mb_step(st, i):
            idx = jax.lax.dynamic_slice_in_dim(perm, i * mbs, mbs)
            mb = {k: v[idx] for k, v in data.items()}
            st, metrics = update_one(st, mb, *extra)
            return st, metrics

        state, metrics = jax.lax.scan(mb_step, state, jnp.arange(n_mb))
        return (state, data, extra), metrics

    def run(state: TrainState, data: Dict[str, jnp.ndarray], *extra):
        (state, _, _), metrics = jax.lax.scan(
            epoch, (state, data, extra), None, length=num_epochs
        )
        return state, {k: v[-1, -1] for k, v in metrics.items()}

    return jax.jit(run)


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.input_path: Optional[str] = None
        self.beta = 1.0            # 0.0 => plain BC
        self.vf_coeff = 1.0
        self.lr = 1e-4
        self.train_batch_size = 2048
        self.minibatch_size = 256
        self.num_epochs = 1
        self.num_rollout_workers = 0
        self.adv_clip = 10.0       # exp-advantage clamp (marwil.py parity)


class MARWILLearner(Learner):
    """Advantage-weighted BC: loss = -E[exp(beta*A) * logp(a|s)] + vf loss.
    beta=0 reduces to behavior cloning (the BC algorithm reuses this)."""

    def __init__(self, obs_dim, num_actions, hidden=(64, 64), lr=1e-4,
                 beta=1.0, vf_coeff=1.0, adv_clip=10.0,
                 minibatch_size=256, num_epochs=1, seed=0):
        super().__init__(config=None)
        self.beta, self.vf_coeff, self.adv_clip = beta, vf_coeff, adv_clip
        self.minibatch_size, self.num_epochs = minibatch_size, num_epochs
        self.optimizer = optax.adam(lr)
        params = init_ac_params(jax.random.PRNGKey(seed), obs_dim, num_actions, hidden)
        self.state = TrainState(
            params=params, opt_state=self.optimizer.init(params),
            rng=jax.random.PRNGKey(seed + 1),
        )
        self._runs: Dict[int, Any] = {}

    def loss(self, params, mb) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        logits, value = ac_apply(params, mb[OBS])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, mb[ACTIONS][:, None].astype(jnp.int32), -1)[:, 0]
        # one-step-return advantage vs the learned value baseline
        # (monte-carlo returns are not in the offline schema; rewards are)
        adv = jax.lax.stop_gradient(mb[REWARDS] - value)
        if self.beta > 0.0:
            w = jnp.exp(jnp.clip(self.beta * adv, -self.adv_clip, self.adv_clip))
        else:
            w = jnp.ones_like(adv)
        bc_loss = -jnp.mean(w * logp)
        vf_loss = jnp.mean((value - mb[REWARDS]) ** 2)
        total = bc_loss + self.vf_coeff * vf_loss * (1.0 if self.beta > 0 else 0.0)
        return total, {
            "loss": total, "bc_loss": bc_loss, "vf_loss": vf_loss,
            "mean_logp": jnp.mean(logp),
        }

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        data = _device_batch(batch, (OBS, ACTIONS, REWARDS))
        n = data[OBS].shape[0]

        def update_one(st, mb):
            (_, metrics), grads = jax.value_and_grad(self.loss, has_aux=True)(
                st.params, mb
            )
            upd, opt_state = self.optimizer.update(grads, st.opt_state, st.params)
            return st._replace(
                params=optax.apply_updates(st.params, upd), opt_state=opt_state
            ), metrics

        run = self._runs.get(n)
        if run is None:
            run = self._runs[n] = _minibatch_scan(
                update_one, n, self.minibatch_size, self.num_epochs
            )
        self.state, metrics = run(self.state, data)
        return {k: float(v) for k, v in metrics.items()}

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        logits, _ = ac_apply(self.state.params, jnp.asarray(obs))
        return np.asarray(jnp.argmax(logits, -1))


class MARWIL(Algorithm):
    _config_class = MARWILConfig

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = self.algo_config
        if not cfg.input_path:
            raise ValueError("MARWIL/BC needs config.input_path (offline shards)")
        self.reader = JsonReader(cfg.input_path, shuffle=True, seed=cfg.seed)
        all_data = self.reader.read_all()
        self._data = all_data
        obs_dim = int(np.asarray(all_data[OBS]).shape[-1])
        num_actions = int(np.asarray(all_data[ACTIONS]).max()) + 1
        self.learner_group = MARWILLearner(
            obs_dim, num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
            lr=cfg.lr, beta=cfg.beta, vf_coeff=cfg.vf_coeff,
            adv_clip=cfg.adv_clip, minibatch_size=cfg.minibatch_size,
            num_epochs=cfg.num_epochs, seed=cfg.seed,
        )
        self.workers = None
        self._rng = np.random.default_rng(cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        n = len(self._data)
        take = min(cfg.train_batch_size, n)
        idx = self._rng.choice(n, size=take, replace=False)
        batch = SampleBatch({k: np.asarray(v)[idx] for k, v in self._data.items()})
        metrics = self.learner_group.update(batch)
        self._timesteps_total += take
        metrics["timesteps_total"] = self._timesteps_total
        return metrics

    # offline: no env workers to report or stop
    def step(self) -> Dict[str, Any]:
        import time as _t

        t0 = _t.perf_counter()
        result = self.training_step()
        result["time_this_iter_s"] = _t.perf_counter() - t0
        return result

    def cleanup(self) -> None:
        pass

    stop = cleanup

    def save_checkpoint(self) -> Any:
        return {"weights": self.learner_group.get_weights(),
                "opt_state": jax.device_get(self.learner_group.state.opt_state),
                "rng": jax.device_get(self.learner_group.state.rng),
                # the driver-side batch sampler is training state too: a
                # resumed run must draw the same sample sequence
                "np_rng": self._rng.bit_generator.state,
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, checkpoint: Any) -> None:
        lg = self.learner_group
        lg.set_weights(checkpoint["weights"])
        if checkpoint.get("opt_state") is not None:
            lg.state = lg.state._replace(
                opt_state=jax.device_put(checkpoint["opt_state"])
            )
        if checkpoint.get("rng") is not None:
            lg.state = lg.state._replace(rng=jax.device_put(checkpoint["rng"]))
        if checkpoint.get("np_rng") is not None:
            self._rng.bit_generator.state = checkpoint["np_rng"]
        self._timesteps_total = checkpoint.get("timesteps_total", 0)


class BCConfig(MARWILConfig):
    def __init__(self):
        super().__init__()
        self.beta = 0.0


class BC(MARWIL):
    """Behavior cloning = MARWIL with beta=0 (reference: rllib/algorithms/bc)."""

    _config_class = BCConfig


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.input_path: Optional[str] = None
        self.lr = 3e-4
        self.gamma = 0.99
        self.cql_alpha = 1.0       # conservative penalty weight
        self.target_update_freq = 8
        self.train_batch_size = 2048
        self.minibatch_size = 256
        self.num_epochs = 1
        self.num_rollout_workers = 0


class CQLLearner(Learner):
    """Discrete CQL: double-Q TD loss + alpha * E[logsumexp Q - Q(a_data)].

    The conservative term pushes down Q on unseen actions, bounding the
    usual offline-RL overestimation (reference: rllib/algorithms/cql —
    continuous SAC-based there; the discrete form keeps the same penalty)."""

    def __init__(self, obs_dim, num_actions, hidden=(64, 64), lr=3e-4,
                 gamma=0.99, cql_alpha=1.0, target_update_freq=8,
                 minibatch_size=256, num_epochs=1, seed=0):
        super().__init__(config=None)
        self.gamma, self.cql_alpha = gamma, cql_alpha
        self.target_update_freq = target_update_freq
        self.minibatch_size, self.num_epochs = minibatch_size, num_epochs
        self.optimizer = optax.adam(lr)
        params = init_q_params(jax.random.PRNGKey(seed), obs_dim, num_actions, hidden)
        self.state = TrainState(
            params=params, opt_state=self.optimizer.init(params),
            rng=jax.random.PRNGKey(seed + 1),
        )
        self.target_params = jax.tree_util.tree_map(jnp.copy, params)
        self._updates = 0
        self._runs: Dict[int, Any] = {}

    def loss(self, params, target_params, mb):
        q = q_apply(params, mb[OBS])
        q_data = jnp.take_along_axis(q, mb[ACTIONS][:, None].astype(jnp.int32), -1)[:, 0]
        # double-Q target: online argmax, target evaluation
        next_q_online = q_apply(params, mb[NEXT_OBS])
        next_a = jnp.argmax(next_q_online, -1)
        next_q_t = q_apply(target_params, mb[NEXT_OBS])
        next_q = jnp.take_along_axis(next_q_t, next_a[:, None], -1)[:, 0]
        target = mb[REWARDS] + self.gamma * (1.0 - mb[DONES]) * jax.lax.stop_gradient(next_q)
        td_loss = jnp.mean((q_data - target) ** 2)
        cql_term = jnp.mean(jax.scipy.special.logsumexp(q, axis=-1) - q_data)
        total = td_loss + self.cql_alpha * cql_term
        return total, {
            "loss": total, "td_loss": td_loss, "cql_term": cql_term,
            "q_data_mean": jnp.mean(q_data),
        }

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        data = _device_batch(batch, (OBS, ACTIONS, REWARDS, NEXT_OBS, DONES))
        n = data[OBS].shape[0]

        # target params ride as a traced ARGUMENT: a closure would be baked
        # into the compiled program as a constant and target syncs below
        # would silently never reach it
        def update_one(st, mb, target_params):
            (_, metrics), grads = jax.value_and_grad(self.loss, has_aux=True)(
                st.params, target_params, mb
            )
            upd, opt_state = self.optimizer.update(grads, st.opt_state, st.params)
            return st._replace(
                params=optax.apply_updates(st.params, upd), opt_state=opt_state
            ), metrics

        run = self._runs.get(n)
        if run is None:
            run = self._runs[n] = _minibatch_scan(
                update_one, n, self.minibatch_size, self.num_epochs
            )
        self.state, metrics = run(self.state, data, self.target_params)
        self._updates += 1
        if self._updates % self.target_update_freq == 0:
            self.target_params = jax.tree_util.tree_map(jnp.copy, self.state.params)
        return {k: float(v) for k, v in metrics.items()}

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(jnp.argmax(q_apply(self.state.params, jnp.asarray(obs)), -1))


class CQL(MARWIL):
    """Shares MARWIL's offline driver; swaps in the conservative Q learner."""

    _config_class = CQLConfig

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = self.algo_config
        if not cfg.input_path:
            raise ValueError("CQL needs config.input_path (offline shards)")
        self.reader = JsonReader(cfg.input_path, shuffle=True, seed=cfg.seed)
        self._data = self.reader.read_all()
        obs_dim = int(np.asarray(self._data[OBS]).shape[-1])
        num_actions = int(np.asarray(self._data[ACTIONS]).max()) + 1
        self.learner_group = CQLLearner(
            obs_dim, num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
            lr=cfg.lr, gamma=cfg.gamma, cql_alpha=cfg.cql_alpha,
            target_update_freq=cfg.target_update_freq,
            minibatch_size=cfg.minibatch_size, num_epochs=cfg.num_epochs,
            seed=cfg.seed,
        )
        self.workers = None
        self._rng = np.random.default_rng(cfg.seed)

    def save_checkpoint(self) -> Any:
        # MARWIL's checkpoint (weights/opt_state/rng/np_rng/timesteps)
        # plus CQL's extra training state: the target network and the
        # target-sync counter — a resume that reinitializes either
        # diverges (random TD targets / off-schedule syncs)
        lg = self.learner_group
        return {
            **super().save_checkpoint(),
            "target_weights": jax.device_get(lg.target_params),
            "updates": lg._updates,
        }

    def load_checkpoint(self, checkpoint: Any) -> None:
        super().load_checkpoint(checkpoint)
        lg = self.learner_group
        tw = checkpoint.get("target_weights")
        if tw is not None:
            lg.target_params = jax.device_put(tw)
        lg._updates = checkpoint.get("updates", 0)
