"""A2C: synchronous advantage actor-critic.

Reference parity: rllib/algorithms/a2c/a2c.py (A2C = synchronous sampling +
one plain policy-gradient pass per batch — PPO's pipeline minus the clipped
surrogate and the epoch loop). Reuses PPO's sampling/GAE machinery; the
learner runs exactly one epoch of unclipped pg updates per train batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .learner import PPOLearner
from .models import ac_apply
from .ppo import PPO, PPOConfig
from .sample_batch import ACTIONS, ADVANTAGES, OBS, TARGETS


class A2CConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = A2C
        # A2C is strictly on-policy single-pass: more epochs would reuse
        # the batch with stale advantages and no trust region to guard it
        self.num_epochs = 1
        self.lr = 7e-4
        self.entropy_coeff = 0.01


class A2CLearner(PPOLearner):
    """PPO's compiled update skeleton with the vanilla pg loss."""

    def loss(self, params, mb):
        logits, value = ac_apply(params, mb[OBS])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, mb[ACTIONS][:, None], axis=-1)[:, 0]
        adv = mb[ADVANTAGES]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg_loss = -jnp.mean(logp * adv)
        vf_loss = 0.5 * jnp.mean((value - mb[TARGETS]) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = pg_loss + self.vf_coeff * vf_loss - self.entropy_coeff * entropy
        return total, {
            "total_loss": total,
            "policy_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }


class A2C(PPO):
    _config_class = A2CConfig
    _learner_cls = A2CLearner
