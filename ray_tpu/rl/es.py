"""Evolution Strategies: derivative-free policy search over actor fleets.

Reference parity: rllib/algorithms/es/ (Salimans et al. OpenAI-ES) — the
population's perturbations are evaluated by PARALLEL rollout actors that
share nothing but the current parameter vector and per-perturbation noise
SEEDS (workers regenerate noise locally, so only scalars cross the wire),
with antithetic pairs and centered-rank fitness shaping.

TPU-first note: ES's per-perturbation work is tiny MLP rollouts — a CPU
actor-fleet workload by design; the framework contribution here is the
seed-based scatter/gather over the actor fleet, mirroring the reference's
shared-noise-table architecture without the 250MB table (seeds regenerate
slices on demand)."""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .config import AlgorithmConfig
from .rollout_worker import _make_env
from ..tune.trainable import Trainable


def _flat_mlp_dims(obs_dim: int, hidden, n_actions: int) -> List[tuple]:
    dims = []
    prev = obs_dim
    for h in tuple(hidden) + (n_actions,):
        dims.append((prev, h))
        prev = h
    return dims


def _n_params(dims) -> int:
    return sum(i * o + o for i, o in dims)


def _act(flat: np.ndarray, dims, obs: np.ndarray) -> int:
    """Deterministic argmax policy over a flat parameter vector."""
    x = obs
    off = 0
    for li, (i, o) in enumerate(dims):
        w = flat[off:off + i * o].reshape(i, o)
        off += i * o
        b = flat[off:off + o]
        off += o
        x = x @ w + b
        if li < len(dims) - 1:
            x = np.tanh(x)
    return int(np.argmax(x))


class ESEvalWorker:
    """Evaluates antithetic perturbation pairs: receives (weights, seeds,
    sigma), regenerates each seed's noise locally, returns one scalar
    return per direction (reference: es/es.py Worker.do_rollouts)."""

    def __init__(self, env_spec, hidden=(32, 32), seed: int = 0,
                 episode_limit: int = 500):
        self.env = _make_env(env_spec)
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.dims = _flat_mlp_dims(
            self.obs_dim, hidden, int(self.env.action_space.n)
        )
        self.episode_limit = episode_limit
        self._reset_seed = seed

    def ready(self) -> bool:
        return True

    def _episode(self, flat: np.ndarray):
        obs, _ = self.env.reset(seed=self._reset_seed)
        self._reset_seed += 1
        ret, steps = 0.0, 0
        for _ in range(self.episode_limit):
            obs = np.asarray(obs, np.float32).reshape(-1)
            obs2, r, term, trunc, _ = self.env.step(_act(flat, self.dims, obs))
            ret += float(r)
            steps += 1
            obs = obs2
            if term or trunc:
                break
        return ret, steps

    def evaluate(self, weights: np.ndarray, seeds: List[int], sigma: float):
        """([(ret_plus, ret_minus)] per seed, total env steps) —
        antithetic pairs."""
        out, total_steps = [], 0
        for s in seeds:
            noise = np.random.default_rng(s).standard_normal(
                weights.shape[0]
            ).astype(np.float32)
            rp, sp = self._episode(weights + sigma * noise)
            rm, sm = self._episode(weights - sigma * noise)
            out.append((rp, rm))
            total_steps += sp + sm
        return out, total_steps


def _centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping (reference es_utils.compute_centered_ranks)."""
    ranks = np.empty(x.size, dtype=np.float32)
    ranks[x.ravel().argsort()] = np.arange(x.size, dtype=np.float32)
    return (ranks / (x.size - 1) - 0.5).reshape(x.shape)


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=ES)
        self.pop_size: int = 32          # antithetic PAIRS per iteration
        self.sigma: float = 0.05
        self.lr = 0.03
        self.num_rollout_workers = 2
        self.l2_coeff: float = 0.005
        self.episode_limit: int = 500


class ES(Trainable):
    _config_class = ESConfig

    def __init__(self, config=None, **kwargs):
        import ray_tpu

        config = self._config_class.coerce(config)
        self.algo_config = config
        cfg = config
        env = _make_env(cfg.env)
        obs_dim = int(np.prod(env.observation_space.shape))
        n_actions = int(env.action_space.n)
        env.close()
        hidden = tuple(cfg.model.get("hidden", (32, 32)))
        self.dims = _flat_mlp_dims(obs_dim, hidden, n_actions)
        rng = np.random.default_rng(cfg.seed)
        self.weights = (0.1 * rng.standard_normal(_n_params(self.dims))).astype(
            np.float32
        )
        self._mom = np.zeros_like(self.weights)
        self._seed_counter = cfg.seed * 1_000_003
        Worker = ray_tpu.remote(ESEvalWorker)
        self.workers = [
            Worker.remote(cfg.env, hidden=hidden, seed=cfg.seed + 17 * i,
                          episode_limit=cfg.episode_limit)
            for i in range(max(1, cfg.num_rollout_workers))
        ]
        ray_tpu.get([w.ready.remote() for w in self.workers])
        self._timesteps_total = 0
        self.iteration = 0
        self._recent: List[float] = []

    def _evaluate_population(self):
        """Mint seeds, scatter shards over the fleet, gather antithetic
        return pairs — the machinery ES and ARS share. Returns
        (returns [pop, 2], seeds)."""
        import ray_tpu

        cfg = self.algo_config
        seeds = [self._seed_counter + i for i in range(cfg.pop_size)]
        self._seed_counter += cfg.pop_size
        # scatter seed shards over the fleet; only scalars return
        shards = np.array_split(np.asarray(seeds), len(self.workers))
        refs = [
            w.evaluate.remote(self.weights, [int(s) for s in shard], cfg.sigma)
            for w, shard in zip(self.workers, shards) if len(shard)
        ]
        parts = ray_tpu.get(refs)
        pairs = [p for part, _steps in parts for p in part]
        self._timesteps_total += sum(steps for _part, steps in parts)
        return np.asarray(pairs, np.float32), seeds

    def _noise(self, seed: int) -> np.ndarray:
        return np.random.default_rng(seed).standard_normal(
            self.weights.shape[0]
        ).astype(np.float32)

    def _gradient(self, returns: np.ndarray, seeds) -> np.ndarray:
        """Centered-rank antithetic gradient (the OpenAI-ES estimator;
        ARS overrides with top-direction selection)."""
        cfg = self.algo_config
        ranks = _centered_ranks(returns)
        deltas = ranks[:, 0] - ranks[:, 1]             # antithetic difference
        grad = np.zeros_like(self.weights)
        for s, d in zip(seeds, deltas):
            grad += d * self._noise(s)
        return grad / (2 * len(seeds) * cfg.sigma)

    def _apply_update(self, grad: np.ndarray, returns: np.ndarray) -> Dict[str, Any]:
        cfg = self.algo_config
        grad -= cfg.l2_coeff * self.weights
        self._mom = 0.9 * self._mom + cfg.lr * grad
        self.weights = self.weights + self._mom
        mean_ret = float(returns.mean())
        self._recent.append(mean_ret)
        self._recent = self._recent[-20:]
        return {
            "episode_reward_mean": float(np.mean(self._recent)),
            "population_reward_mean": mean_ret,
            "population_reward_max": float(returns.max()),
            "grad_norm": float(np.linalg.norm(grad)),
            "timesteps_total": self._timesteps_total,
        }

    def training_step(self) -> Dict[str, Any]:
        returns, seeds = self._evaluate_population()
        return self._apply_update(self._gradient(returns, seeds), returns)

    # tune's TrialRunner drives class trainables via step(); standalone
    # callers use the base Trainable.train() wrapper
    step = training_step

    def compute_action(self, obs) -> int:
        return _act(self.weights, self.dims, np.asarray(obs, np.float32).reshape(-1))

    def save_checkpoint(self) -> Any:
        # seed counter travels: a restore must CONTINUE the perturbation
        # sequence, not replay already-consumed noise directions
        return {"weights": self.weights.copy(), "mom": self._mom.copy(),
                "seed_counter": self._seed_counter,
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, checkpoint: Any) -> None:
        self.weights = np.asarray(checkpoint["weights"], np.float32)
        self._mom = np.asarray(checkpoint["mom"], np.float32)
        self._seed_counter = checkpoint.get("seed_counter", self._seed_counter)
        self._timesteps_total = checkpoint.get("timesteps_total", 0)

    def stop(self) -> None:
        import ray_tpu

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    cleanup = stop


class ARSConfig(ESConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = ARS
        self.top_directions: int = 8  # b in Mania et al. (<= pop_size)


class ARS(ES):
    """Augmented Random Search (reference: rllib/algorithms/ars/ — Mania et
    al. 2018): ES's antithetic machinery, but the update (a) keeps only the
    top-b directions by max(ret+, ret-) and (b) scales by the std of the
    SELECTED returns instead of centered-rank shaping — the paper's V2
    normalization. Shares ES's seed-scatter evaluation fleet wholesale."""

    _config_class = ARSConfig

    def _gradient(self, returns: np.ndarray, seeds) -> np.ndarray:
        cfg = self.algo_config
        b = min(cfg.top_directions, len(seeds))
        order = np.argsort(-returns.max(axis=1))[:b]         # best directions
        sigma_r = float(returns[order].std()) + 1e-8
        grad = np.zeros_like(self.weights)
        for i in order:
            grad += (returns[i, 0] - returns[i, 1]) * self._noise(seeds[int(i)])
        return grad / (b * sigma_r)
