"""PPO: synchronous sample → compiled minibatch-SGD update → weight sync.

Reference parity: rllib/algorithms/ppo/ppo.py (PPO.training_step :440 —
synchronous_parallel_sample, LearnerGroup.update, weight broadcast) with the
learner math in rllib/algorithms/ppo/torch/ppo_torch_learner.py, redesigned
as a single jitted update (see learner.py).
"""

from __future__ import annotations

from typing import Any, Dict

from .algorithm import Algorithm
from .config import AlgorithmConfig
from .learner import LearnerGroup, PPOLearner
from .sample_batch import concat_samples


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=PPO)
        # PPO-specific training knobs
        self.clip_eps: float = 0.2
        self.vf_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.max_grad_norm: float = 0.5


class PPO(Algorithm):
    _config_class = PPOConfig
    _learner_cls = PPOLearner  # A2C swaps in its unclipped learner

    def _build_learner(self) -> LearnerGroup:
        cfg = self.algo_config
        # probe the env once for spaces (reference: Algorithm.setup builds
        # the learner from the local worker's policy spaces)
        from .rollout_worker import _make_env

        env = _make_env(cfg.env)
        import numpy as np

        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close()

        learner_cls = self._learner_cls

        def factory():
            return learner_cls(
                obs_dim=obs_dim,
                num_actions=num_actions,
                hidden=tuple(cfg.model.get("hidden", (64, 64))),
                lr=cfg.lr,
                clip_eps=getattr(cfg, "clip_eps", 0.2),
                vf_coeff=getattr(cfg, "vf_coeff", 0.5),
                entropy_coeff=getattr(cfg, "entropy_coeff", 0.01),
                num_epochs=cfg.num_epochs,
                minibatch_size=cfg.minibatch_size,
                max_grad_norm=getattr(cfg, "max_grad_norm", 0.5),
                seed=cfg.seed,
                mesh=cfg.mesh,
            )

        return LearnerGroup(
            factory, remote=cfg.remote_learner, num_tpus=cfg.num_tpus_for_learner
        )

    def training_step(self) -> Dict[str, Any]:
        batches = [self.workers.sample()]
        collected = len(batches[0])
        # keep sampling until train_batch_size is met (rollout_ops semantics)
        while collected < self.algo_config.train_batch_size:
            b = self.workers.sample()
            collected += len(b)
            batches.append(b)
        batch = concat_samples(batches)
        self._timesteps_total += len(batch)
        metrics = self.learner_group.update(batch)
        self.workers.set_weights(self.learner_group.get_weights())
        metrics["num_env_steps_sampled_this_iter"] = len(batch)
        return metrics
