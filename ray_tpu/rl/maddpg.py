"""MADDPG: multi-agent DDPG with centralized critics, decentralized actors.

Reference parity: rllib/algorithms/maddpg/ (Lowe et al., "Multi-Agent
Actor-Critic for Mixed Cooperative-Competitive Environments") — each agent
owns a deterministic actor over its OWN observation, while its critic sees
ALL agents' observations and actions (centralized training, decentralized
execution). This is the continuous-action MARL family the discrete
MAPPO/QMIX stack doesn't cover.

TPU-first: all agents' critic and actor updates for a minibatch compile
into ONE jitted function (a static python loop over agents inside the jit
— per-agent shapes may differ, the compiler sees each as its own fused
subgraph), with Polyak target updates folded in. One dispatch per gradient
step for the whole population.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .config import AlgorithmConfig
from .models import _tower_init, _mlp
from .multi_agent import MultiAgentEnv
from .replay_buffer import ReplayBuffer
from .sample_batch import SampleBatch
from ..tune.trainable import Trainable


def _actor_apply(params, obs):
    return jnp.tanh(_mlp(params, obs))


def _critic_apply(params, joint):
    return _mlp(params, joint)[..., 0]


class MADDPGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=MADDPG)
        self.actor_lr: float = 1e-3
        self.critic_lr: float = 1e-3
        self.tau: float = 0.01
        self.buffer_size: int = 100_000
        self.learning_starts: int = 1_000
        self.minibatch_size: int = 256
        self.num_sgd_iter: int = 8
        self.exploration_noise: float = 0.2
        self.train_batch_size = 256  # env steps collected per iteration
        self.model = {"hidden": (64, 64)}

    def environment(self, env: Callable[[], MultiAgentEnv], **kwargs):
        self.env = env
        return self


class MADDPGLearner:
    """All-agent update as one compiled step (critics + actors + Polyak)."""

    def __init__(self, agent_specs: Dict[str, tuple], hidden, actor_lr,
                 critic_lr, gamma, tau, seed: int = 0):
        # agent_specs: {agent_id: (obs_dim, act_dim)}; insertion order fixes
        # the joint concat layout everywhere
        self.agent_ids = list(agent_specs)
        self.specs = agent_specs
        self.gamma, self.tau = gamma, tau
        joint_dim = sum(o + a for o, a in agent_specs.values())
        rng = jax.random.PRNGKey(seed)
        params = {}
        for aid, (obs_dim, act_dim) in agent_specs.items():
            rng, k1, k2 = jax.random.split(rng, 3)
            params[aid] = {
                "actor": _tower_init(k1, (obs_dim, *hidden, act_dim), 0.01),
                "critic": _tower_init(k2, (joint_dim, *hidden, 1), 1.0),
            }
        self.params = params
        self.target = jax.tree_util.tree_map(jnp.copy, params)
        self.actor_opt = optax.adam(actor_lr)
        self.critic_opt = optax.adam(critic_lr)
        self.opt_state = {
            aid: {
                "actor": self.actor_opt.init(params[aid]["actor"]),
                "critic": self.critic_opt.init(params[aid]["critic"]),
            }
            for aid in self.agent_ids
        }
        self._update_fn = None
        # jitted joint act: one compiled dispatch per env step for the
        # whole population (eager per-agent forwards dominate rollout
        # wall-clock otherwise)
        self._act_fn = jax.jit(
            lambda params, obs: {
                a: _actor_apply(params[a]["actor"], obs[a]) for a in obs
            }
        )

    def _build_update(self):
        agent_ids, gamma, tau = self.agent_ids, self.gamma, self.tau
        actor_opt, critic_opt = self.actor_opt, self.critic_opt

        def update(params, target, opt_state, mb):
            obs = {a: mb[f"obs_{a}"] for a in agent_ids}
            acts = {a: mb[f"act_{a}"] for a in agent_ids}
            metrics = {}
            # target joint next action (all target actors, computed once)
            next_acts = [
                _actor_apply(target[a]["actor"], mb[f"next_obs_{a}"])
                for a in agent_ids
            ]
            next_joint = jnp.concatenate(
                [mb[f"next_obs_{a}"] for a in agent_ids] + next_acts, axis=-1
            )
            joint = jnp.concatenate(
                [obs[a] for a in agent_ids] + [acts[a] for a in agent_ids], axis=-1
            )
            for a in agent_ids:
                # ---- centralized critic: TD target from target nets
                q_next = _critic_apply(target[a]["critic"], next_joint)
                y = mb[f"rew_{a}"] + gamma * (1.0 - mb["done"]) * (
                    jax.lax.stop_gradient(q_next)
                )

                def critic_loss(cp):
                    q = _critic_apply(cp, joint)
                    return jnp.mean((q - y) ** 2)

                cl, cgrads = jax.value_and_grad(critic_loss)(params[a]["critic"])
                cup, opt_state[a]["critic"] = critic_opt.update(
                    cgrads, opt_state[a]["critic"], params[a]["critic"]
                )
                params[a]["critic"] = optax.apply_updates(params[a]["critic"], cup)

                # ---- decentralized actor: ascend own critic with own
                # action swapped for the policy's output
                def actor_loss(ap):
                    my_act = _actor_apply(ap, obs[a])
                    cols = [obs[x] for x in agent_ids] + [
                        my_act if x == a else acts[x] for x in agent_ids
                    ]
                    q = _critic_apply(
                        params[a]["critic"], jnp.concatenate(cols, axis=-1)
                    )
                    return -jnp.mean(q)

                al, agrads = jax.value_and_grad(actor_loss)(params[a]["actor"])
                aup, opt_state[a]["actor"] = actor_opt.update(
                    agrads, opt_state[a]["actor"], params[a]["actor"]
                )
                params[a]["actor"] = optax.apply_updates(params[a]["actor"], aup)
                metrics[f"critic_loss_{a}"] = cl
                metrics[f"actor_loss_{a}"] = al
            target = jax.tree_util.tree_map(
                lambda t, p: (1.0 - tau) * t + tau * p, target, params
            )
            return params, target, opt_state, metrics

        return jax.jit(update, donate_argnums=(0, 1, 2))

    def update(self, mb: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._update_fn is None:
            self._update_fn = self._build_update()
        mb = {k: jnp.asarray(v) for k, v in mb.items()}
        self.params, self.target, self.opt_state, metrics = self._update_fn(
            self.params, self.target, self.opt_state, mb
        )
        return {k: float(v) for k, v in metrics.items()}

    def act(self, obs: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = self._act_fn(self.params, {a: jnp.asarray(v) for a, v in obs.items()})
        return {a: np.asarray(v) for a, v in out.items()}

    def get_state(self):
        """Full training state: online + target params and optimizer state
        (resuming from online-only would TD-bootstrap off random targets)."""
        return jax.device_get(
            {"params": self.params, "target": self.target,
             "opt_state": self.opt_state}
        )

    def set_state(self, state):
        self.params = jax.device_put(state["params"])
        self.target = jax.device_put(state["target"])
        self.opt_state = jax.device_put(state["opt_state"])

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        self.params = jax.device_put(weights)


class MADDPG(Trainable):
    """Driver-local env loop + joint replay + all-agent jitted updates
    (the reference's MADDPG also trains through one local worker)."""

    _config_class = MADDPGConfig

    def __init__(self, config: Optional[MADDPGConfig] = None, **kwargs):
        config = self._config_class.coerce(config)
        self.algo_config = config
        cfg = config
        self.env: MultiAgentEnv = cfg.env()
        obs, _ = self.env.reset(seed=cfg.seed)
        self.agent_ids = sorted(obs)
        specs = {}
        for a in self.agent_ids:
            # per-agent spaces when the env provides them, else the uniform
            # MultiAgentEnv.action_space
            spaces = getattr(self.env, "action_spaces", None) or {}
            act_space = spaces.get(a) or self.env.action_space
            specs[a] = (int(np.prod(np.shape(obs[a]))), int(np.prod(act_space.shape)))
        self.specs = specs
        hidden = tuple(cfg.model.get("hidden", (64, 64)))
        self.learner = MADDPGLearner(
            specs, hidden, cfg.actor_lr, cfg.critic_lr, cfg.gamma, cfg.tau,
            seed=cfg.seed,
        )
        self.replay = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._obs = {a: np.asarray(obs[a], np.float32) for a in self.agent_ids}
        self._rng = np.random.default_rng(cfg.seed)
        self._ep_return = 0.0
        self._ep_returns: List[float] = []
        self._timesteps_total = 0
        self.iteration = 0

    # ------------------------------------------------------------- rollout

    def _collect(self, n_steps: int):
        cfg = self.algo_config
        for _ in range(n_steps):
            stacked = {a: self._obs[a][None] for a in self.agent_ids}
            acts = self.learner.act(stacked)
            actions = {}
            for a in self.agent_ids:
                noise = cfg.exploration_noise * self._rng.standard_normal(
                    self.specs[a][1]
                ).astype(np.float32)
                actions[a] = np.clip(acts[a][0] + noise, -1.0, 1.0)
            nobs, rews, terms, truncs, _ = self.env.step(actions)
            done = bool(terms.get("__all__", False))
            trunc = bool(truncs.get("__all__", False))
            row = {"done": np.array([np.float32(done)])}
            for a in self.agent_ids:
                row[f"obs_{a}"] = self._obs[a][None]
                row[f"act_{a}"] = np.asarray(actions[a], np.float32)[None]
                row[f"rew_{a}"] = np.array([np.float32(rews[a])])
                row[f"next_obs_{a}"] = np.asarray(nobs[a], np.float32)[None] \
                    if a in nobs else self._obs[a][None]
            self.replay.add(SampleBatch(row))
            self._ep_return += float(np.mean([rews[a] for a in self.agent_ids]))
            self._timesteps_total += 1
            if done or trunc:
                self._ep_returns.append(self._ep_return)
                self._ep_return = 0.0
                obs, _ = self.env.reset()
                nobs = obs
            self._obs = {a: np.asarray(nobs[a], np.float32) for a in self.agent_ids}

    # ------------------------------------------------------------- training

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        self._collect(cfg.train_batch_size)
        metrics: Dict[str, Any] = {}
        if len(self.replay) >= cfg.learning_starts:
            for _ in range(cfg.num_sgd_iter):
                mb = self.replay.sample(cfg.minibatch_size)
                metrics.update(self.learner.update(dict(mb)))
        window = self._ep_returns[-100:]
        if window:
            metrics["episode_reward_mean"] = float(np.mean(window))
        metrics["timesteps_total"] = self._timesteps_total
        return metrics

    # tune's TrialRunner drives class trainables via step(); standalone
    # callers use the base Trainable.train() wrapper
    step = training_step

    def save_checkpoint(self) -> Any:
        return {"state": self.learner.get_state(),
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, checkpoint: Any) -> None:
        if "state" in checkpoint:
            self.learner.set_state(checkpoint["state"])
        else:  # older online-only checkpoints
            self.learner.set_weights(checkpoint["weights"])
        self._timesteps_total = checkpoint.get("timesteps_total", 0)

    def compute_actions(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Deterministic (no-noise) joint action for evaluation."""
        stacked = {a: np.asarray(obs[a], np.float32)[None] for a in obs}
        return {a: v[0] for a, v in self.learner.act(stacked).items()}

    def stop(self) -> None:
        try:
            self.env.close()
        except Exception:
            pass

    cleanup = stop
