"""TD3: twin-delayed deterministic policy gradients for continuous control.

Reference parity: rllib/algorithms/td3/td3.py (TD3 = DDPG + twin critics +
target-policy smoothing + delayed actor updates; rllib implements it as a
DDPG config preset). Shares SAC's networks (the pi mean head acts as the
deterministic policy; the log_std head is simply unused), replay buffer,
and continuous rollout worker; the num_sgd_iter gradient steps run as one
jitted lax.scan with the delayed-actor mask computed inside the scan.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm
from .config import AlgorithmConfig
from .learner import Learner, LearnerGroup, TrainState
from .models import init_sac_params, sac_pi_apply, sac_q_apply
from .replay_buffer import ReplayBuffer
from .rollout_worker import _make_env
from .sac import _ContinuousWorker
from .sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=TD3)
        self.buffer_size: int = 100_000
        self.learning_starts: int = 1_000
        self.tau: float = 0.005
        self.num_sgd_iter: int = 32
        self.policy_delay: int = 2  # actor/target update every N critic steps
        self.target_noise: float = 0.2  # smoothing noise std on target actions
        self.target_noise_clip: float = 0.5
        self.exploration_noise: float = 0.1  # behavior-policy Gaussian std
        self.lr = 1e-3
        self.minibatch_size = 256
        self.train_batch_size = 256
        self.model = {"hidden": (256, 256)}


class _TD3Worker(_ContinuousWorker):
    """Deterministic actor + fixed exploration noise (vs SAC's learned-std
    sampling); actions live squashed in [-1, 1] like SAC's."""

    def __init__(self, *args, exploration_noise: float = 0.1, **kwargs):
        super().__init__(*args, **kwargs)
        self.exploration_noise = exploration_noise

    def _action(self, mean: np.ndarray, log_std: np.ndarray) -> np.ndarray:
        noise = self._rng.standard_normal(mean.shape).astype(np.float32)
        return np.clip(np.tanh(mean) + self.exploration_noise * noise, -1.0, 1.0)


class TD3Learner(Learner):
    def __init__(
        self,
        obs_dim: int,
        act_dim: int,
        hidden=(256, 256),
        lr: float = 1e-3,
        gamma: float = 0.99,
        tau: float = 0.005,
        policy_delay: int = 2,
        target_noise: float = 0.2,
        target_noise_clip: float = 0.5,
        num_sgd_iter: int = 32,
        minibatch_size: int = 256,
        seed: int = 0,
    ):
        super().__init__(config=None)
        self.gamma = gamma
        self.tau = tau
        self.policy_delay = policy_delay
        self.target_noise = target_noise
        self.target_noise_clip = target_noise_clip
        self.num_sgd_iter = num_sgd_iter
        self.minibatch_size = minibatch_size
        self.optimizer = optax.adam(lr)
        nets = init_sac_params(jax.random.PRNGKey(seed), obs_dim, act_dim, hidden)
        params = {
            "nets": nets,
            "target": jax.tree_util.tree_map(jnp.copy, nets),
            "it": jnp.zeros((), jnp.int32),
        }
        self.state = TrainState(
            params=params,
            opt_state=self.optimizer.init(nets),
            rng=jax.random.PRNGKey(seed + 1),
        )
        self._update_fn = None

    def _losses(self, nets, target, mb, rng, actor_mask):
        # -- critic: target-policy smoothing --
        mean_t, _ = sac_pi_apply(target, mb[NEXT_OBS])
        noise = jnp.clip(
            self.target_noise * jax.random.normal(rng, mean_t.shape),
            -self.target_noise_clip,
            self.target_noise_clip,
        )
        a_next = jnp.clip(jnp.tanh(mean_t) + noise, -1.0, 1.0)
        q1t, q2t = sac_q_apply(target, mb[NEXT_OBS], a_next)
        y = mb[REWARDS] + self.gamma * (1.0 - mb[DONES]) * jax.lax.stop_gradient(
            jnp.minimum(q1t, q2t)
        )
        q1, q2 = sac_q_apply(nets, mb[OBS], mb[ACTIONS])
        critic_loss = 0.5 * (jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2))

        # -- delayed deterministic actor: maximize Q1(s, pi(s)) --
        mean, _ = sac_pi_apply(nets, mb[OBS])
        a_pi = jnp.tanh(mean)
        q1p, _ = sac_q_apply(jax.lax.stop_gradient(nets), mb[OBS], a_pi)
        actor_loss = -jnp.mean(q1p)

        total = critic_loss + actor_mask * actor_loss
        return total, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "mean_q": jnp.mean(q1),
        }

    def _build_update(self):
        optimizer = self.optimizer
        tau = self.tau
        delay = self.policy_delay
        losses = self._losses

        def step(carry, inp):
            nets, target, opt_state, it = carry
            mb, rng = inp
            actor_mask = (it % delay == 0).astype(jnp.float32)
            (_, metrics), grads = jax.value_and_grad(losses, has_aux=True)(
                nets, target, mb, rng, actor_mask
            )
            updates, opt_state = optimizer.update(grads, opt_state, nets)
            nets = optax.apply_updates(nets, updates)
            # polyak targets on the same delayed schedule as the actor
            # (Fujimoto et al. 2018, alg. 1)
            step_tau = tau * actor_mask
            target = jax.tree_util.tree_map(
                lambda t, o: (1.0 - step_tau) * t + step_tau * o, target, nets
            )
            return (nets, target, opt_state, it + 1), metrics

        def update(state: TrainState, minibatches):
            p = state.params
            rng, sub = jax.random.split(state.rng)
            n = jax.tree_util.tree_leaves(minibatches)[0].shape[0]
            rngs = jax.random.split(sub, n)
            (nets, target, opt_state, it), metrics = jax.lax.scan(
                step, (p["nets"], p["target"], state.opt_state, p["it"]), (minibatches, rngs)
            )
            metrics = jax.tree_util.tree_map(lambda m: m.mean(), metrics)
            params = {"nets": nets, "target": target, "it": it}
            return TrainState(params, opt_state, rng), metrics

        # NOT donated: on this rig's jax build (0.4.37 CPU), THIS executable
        # comes back from the persistent compilation cache (tests/conftest.py)
        # with its donated-input aliasing broken — nets/target outputs return
        # the unmodified inputs (targets never move) while `it` and the
        # metrics are correct. A fresh compile is right; only the
        # deserialized executable is wrong, so the failure appeared only on
        # cache-hit runs. The fix stays LOCAL because the corruption is:
        # every other donated jit (other learners, the paged-decode pools)
        # is exercised with token/numeric-exactness assertions on warm-cache
        # runs and none reproduces it — dropping donation fleet-wide would
        # trade real decode HBM for a failure only ever observed here. The
        # signature to watch for elsewhere: a cache-hit-only failure where a
        # donated output equals its unmodified input. The nets here are
        # tiny — donation bought nothing.
        return jax.jit(update)

    def update(self, buffer: ReplayBuffer) -> Dict[str, float]:
        samples = [buffer.sample(self.minibatch_size) for _ in range(self.num_sgd_iter)]
        minibatches = {
            k: jnp.asarray(np.stack([s[k] for s in samples])) for k in samples[0].keys()
        }
        if self._update_fn is None:
            self._update_fn = self._build_update()
        self.state, metrics = self._update_fn(self.state, minibatches)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.state.params["nets"])

    def set_weights(self, weights):
        p = dict(self.state.params)
        p["nets"] = jax.device_put(weights)
        self.state = self.state._replace(params=p)


class TD3(Algorithm):
    _config_class = TD3Config
    _learner_class = TD3Learner  # hook: DDPG swaps in its single-critic losses

    def _worker_cls(self):
        return _TD3Worker

    def _worker_kwargs(self):
        cfg = self.algo_config
        return dict(
            env_spec=cfg.env,
            num_envs=cfg.num_envs_per_worker,
            rollout_fragment_length=cfg.rollout_fragment_length,
            policy_hidden=tuple(cfg.model.get("hidden", (256, 256))),
            exploration_noise=cfg.exploration_noise,
        )

    def _build_learner(self) -> LearnerGroup:
        cfg = self.algo_config
        env = _make_env(cfg.env)
        obs_dim = int(np.prod(env.observation_space.shape))
        act_dim = int(np.prod(env.action_space.shape))
        env.close()
        self.replay = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)

        learner_cls = self._learner_class

        def factory():
            return learner_cls(
                obs_dim=obs_dim,
                act_dim=act_dim,
                hidden=tuple(cfg.model.get("hidden", (256, 256))),
                lr=cfg.lr,
                gamma=cfg.gamma,
                tau=cfg.tau,
                policy_delay=cfg.policy_delay,
                target_noise=cfg.target_noise,
                target_noise_clip=cfg.target_noise_clip,
                num_sgd_iter=cfg.num_sgd_iter,
                minibatch_size=cfg.minibatch_size,
                seed=cfg.seed,
            )

        return LearnerGroup(factory, remote=False)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        collected = 0
        while collected < cfg.train_batch_size:
            batch = self.workers.sample()
            self.replay.add(batch)
            collected += len(batch)
            self._timesteps_total += len(batch)
        metrics: Dict[str, Any] = {"replay_size": len(self.replay)}
        if len(self.replay) >= cfg.learning_starts:
            metrics.update(self.learner_group._learner.update(self.replay))
            self.workers.set_weights(self.learner_group.get_weights())
        metrics["num_env_steps_sampled_this_iter"] = collected
        return metrics
