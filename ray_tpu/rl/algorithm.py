"""Algorithm: the top-level RL training driver (a tune Trainable).

Reference parity: rllib/algorithms/algorithm.py:149 (Algorithm is a
Trainable; step :757 calls the algo's training_step :1347) and
rllib/evaluation/worker_set.py:80 (WorkerSet fan-out with local fallback).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..tune.trainable import Trainable
from .config import AlgorithmConfig
from .rollout_worker import RolloutWorker
from .sample_batch import SampleBatch, concat_samples


class WorkerSet:
    """N remote rollout actors, or one inline local worker when N == 0.

    `worker_cls`/`worker_kwargs` let algorithms substitute their own
    sampling actor (DQN epsilon-greedy, SAC continuous) while keeping the
    fan-out/weight-sync/metrics plumbing (reference: worker_set.py:80 is
    likewise class-parameterized via cls=RolloutWorker)."""

    def __init__(
        self,
        config: AlgorithmConfig,
        worker_cls=None,
        worker_kwargs: Optional[Dict[str, Any]] = None,
    ):
        self.config = config
        self._local: Optional[Any] = None
        self._remote_workers: List[Any] = []
        worker_cls = worker_cls or RolloutWorker
        kwargs = (
            dict(worker_kwargs)
            if worker_kwargs is not None
            else dict(
                env_spec=config.env,
                num_envs=config.num_envs_per_worker,
                rollout_fragment_length=config.rollout_fragment_length,
                gamma=config.gamma,
                lam=config.lambda_,
                policy_hidden=tuple(config.model.get("hidden", (64, 64))),
            )
        )
        if config.num_rollout_workers == 0:
            self._local = worker_cls(seed=config.seed, **kwargs)
        else:
            import ray_tpu

            cls = ray_tpu.remote(worker_cls)
            self._remote_workers = [
                cls.options(num_cpus=config.num_cpus_per_worker).remote(
                    seed=config.seed + 1000 * (i + 1), **kwargs
                )
                for i in range(config.num_rollout_workers)
            ]
            ray_tpu.get([w.ready.remote() for w in self._remote_workers])

    @property
    def num_workers(self) -> int:
        return len(self._remote_workers)

    def sample(self) -> SampleBatch:
        """synchronous_parallel_sample (rllib/execution/rollout_ops.py)."""
        if self._local is not None:
            return self._local.sample()
        import ray_tpu

        batches = ray_tpu.get([w.sample.remote() for w in self._remote_workers])
        if batches and hasattr(batches[0], "policy_batches"):
            # multi-agent workers return MultiAgentBatch (lazy import: the
            # multi_agent module imports this one)
            from .multi_agent import concat_multi_agent

            return concat_multi_agent(batches)
        return concat_samples(batches)

    def set_weights(self, weights) -> None:
        if self._local is not None:
            self._local.set_weights(weights)
        else:
            import ray_tpu

            ray_tpu.get([w.set_weights.remote(weights) for w in self._remote_workers])

    def episode_metrics(self) -> Dict[str, float]:
        if self._local is not None:
            stats = [self._local.episode_metrics()]
        else:
            import ray_tpu

            stats = ray_tpu.get(
                [w.episode_metrics.remote() for w in self._remote_workers]
            )
        merged: Dict[str, float] = {"episodes_this_iter": 0}
        rewards = [
            s["episode_reward_mean"]
            for s in stats
            if not np.isnan(s["episode_reward_mean"])
        ]
        lens = [
            s["episode_len_mean"] for s in stats if not np.isnan(s["episode_len_mean"])
        ]
        merged["episodes_this_iter"] = int(
            sum(s["episodes_this_iter"] for s in stats)
        )
        merged["episode_reward_mean"] = float(np.mean(rewards)) if rewards else float("nan")
        merged["episode_len_mean"] = float(np.mean(lens)) if lens else float("nan")
        return merged

    def stop(self) -> None:
        if self._local is not None:
            self._local.stop()
        else:
            import ray_tpu

            for w in self._remote_workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass


class Algorithm(Trainable):
    """Subclasses implement `_build_learner()` and `training_step()`."""

    _config_class = AlgorithmConfig

    def __init__(self, config: Optional[AlgorithmConfig] = None, **kwargs):
        config = self._config_class.coerce(config)
        self.algo_config = config
        self._timesteps_total = 0
        super().__init__(config=config.to_dict())

    # -- Trainable API --

    def setup(self, config: Dict[str, Any]) -> None:
        self.workers = WorkerSet(
            self.algo_config, self._worker_cls(), self._worker_kwargs()
        )
        self.learner_group = self._build_learner()
        # push initial learner weights so all rollout policies start equal
        self.workers.set_weights(self.learner_group.get_weights())

    def step(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        result = self.training_step()
        result.setdefault("timesteps_total", self._timesteps_total)
        result["time_this_iter_s"] = time.perf_counter() - t0
        # multi-agent algorithms track episode stats in training_step and
        # have no WorkerSet
        if getattr(self, "workers", None) is not None:
            result.update(self.workers.episode_metrics())
        return result

    def train(self) -> Dict[str, Any]:
        """Convenience alias matching the reference's Algorithm.train()."""
        result = self.step()
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        return result

    def save_checkpoint(self) -> Any:
        return {"weights": self.learner_group.get_weights(),
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, checkpoint: Any) -> None:
        self.learner_group.set_weights(checkpoint["weights"])
        self._timesteps_total = checkpoint.get("timesteps_total", 0)
        self.workers.set_weights(checkpoint["weights"])

    def cleanup(self) -> None:
        self.workers.stop()

    stop = cleanup

    # -- to implement --

    def _worker_cls(self):
        """Override to use an algorithm-specific sampling actor."""
        return None

    def _worker_kwargs(self) -> Optional[Dict[str, Any]]:
        return None

    def _build_learner(self):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError
