"""APPO: asynchronous PPO — IMPALA's pipeline with PPO's clipped surrogate.

Reference parity: rllib/algorithms/appo/appo.py (APPO = IMPALA-style async
sampling + V-trace off-policy correction + the PPO clip on the importance
ratio instead of IMPALA's bare rho-weighted pg loss). Reuses IMPALA's
async training_step and vtrace; only the policy term of the loss changes.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .impala import IMPALA, ImpalaConfig, ImpalaLearner, vtrace
from .sample_batch import ACTIONS, DONES, LOGP, OBS, REWARDS


class APPOConfig(ImpalaConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.clip_eps: float = 0.3  # reference appo.py clip_param default


class APPOLearner(ImpalaLearner):
    def __init__(self, *args, clip_eps: float = 0.3, **kwargs):
        super().__init__(*args, **kwargs)
        self.clip_eps = clip_eps

    def loss(self, params, batch):
        from .models import ac_apply

        T, E = batch[ACTIONS].shape
        obs = batch[OBS].reshape(T * E, -1)
        logits, values = ac_apply(params, obs)
        logits = logits.reshape(T, E, -1)
        values = values.reshape(T, E)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, batch[ACTIONS][..., None], axis=-1)[..., 0]
        log_rho = logp - batch[LOGP]
        rho = jnp.minimum(self.rho_clip, jnp.exp(log_rho))
        c = jnp.minimum(self.c_clip, jnp.exp(log_rho))
        vs, pg_adv = vtrace(
            jax.lax.stop_gradient(values),
            batch[REWARDS],
            batch[DONES],
            batch["bootstrap_value"],
            jax.lax.stop_gradient(rho),
            jax.lax.stop_gradient(c),
            self.gamma,
        )
        # the APPO difference: clipped-surrogate on the (unclipped)
        # importance ratio, with v-trace advantages as the target
        ratio = jnp.exp(log_rho)
        pg_loss = -jnp.mean(
            jnp.minimum(
                ratio * pg_adv,
                jnp.clip(ratio, 1.0 - self.clip_eps, 1.0 + self.clip_eps) * pg_adv,
            )
        )
        vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = pg_loss + self.vf_coeff * vf_loss - self.entropy_coeff * entropy
        return total, {
            "total_loss": total,
            "policy_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_rho": jnp.mean(rho),
        }


class APPO(IMPALA):
    _config_class = APPOConfig
    _learner_cls = APPOLearner

    def _extra_learner_kwargs(self) -> Dict[str, Any]:
        return {"clip_eps": getattr(self.algo_config, "clip_eps", 0.3)}
