"""AlgorithmConfig: fluent, typed algorithm configuration.

Reference parity: rllib/algorithms/algorithm_config.py (AlgorithmConfig with
.environment()/.rollouts()/.training()/.resources() chaining and
.build(env)). Kept flat — one dataclass-ish object, chainable setters.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Union


class AlgorithmConfig:
    @classmethod
    def coerce(cls, config) -> "AlgorithmConfig":
        """Normalize None / plain-dict configs (the tune param_space path)
        into a config object — the ONE copy of the dict-to-config logic
        every algorithm family shares ('lambda' maps to lambda_)."""
        if config is None:
            return cls()
        if isinstance(config, dict):
            obj = cls()
            for k, v in config.items():
                setattr(obj, "lambda_" if k == "lambda" else k, v)
            return obj
        return config

    def __init__(self, algo_class=None):
        self.algo_class = algo_class
        # environment
        self.env: Union[str, Callable[[], Any], None] = None
        # rollouts
        self.num_rollout_workers: int = 2
        self.num_envs_per_worker: int = 1
        self.rollout_fragment_length: int = 200
        # training
        self.gamma: float = 0.99
        self.lambda_: float = 0.95
        self.lr: float = 3e-4
        self.train_batch_size: int = 4000
        self.minibatch_size: int = 128
        self.num_epochs: int = 4
        self.model: Dict[str, Any] = {"hidden": (64, 64)}
        self.seed: int = 0
        # resources
        self.num_cpus_per_worker: float = 1.0
        self.num_tpus_for_learner: float = 0.0
        self.remote_learner: bool = False
        self.mesh = None

    # -- fluent setters (subset of the reference's surface) --

    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def rollouts(
        self,
        num_rollout_workers: Optional[int] = None,
        num_envs_per_worker: Optional[int] = None,
        rollout_fragment_length: Optional[int] = None,
    ) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            key = "lambda_" if k in ("lambda", "lambda_") else k
            if not hasattr(self, key):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, key, v)
        return self

    def resources(
        self,
        num_cpus_per_worker: Optional[float] = None,
        num_tpus_for_learner: Optional[float] = None,
        remote_learner: Optional[bool] = None,
        mesh=None,
    ) -> "AlgorithmConfig":
        if num_cpus_per_worker is not None:
            self.num_cpus_per_worker = num_cpus_per_worker
        if num_tpus_for_learner is not None:
            self.num_tpus_for_learner = num_tpus_for_learner
        if remote_learner is not None:
            self.remote_learner = remote_learner
        if mesh is not None:
            self.mesh = mesh
        return self

    def debugging(self, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.copy(self)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in vars(self).items() if k != "algo_class"}

    def build(self, env=None):
        if env is not None:
            self.env = env
        if self.algo_class is None:
            raise ValueError("no algorithm class bound to this config")
        return self.algo_class(config=self)
