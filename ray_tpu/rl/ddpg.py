"""DDPG: deterministic policy gradients for continuous control.

Reference parity: rllib/algorithms/ddpg/ddpg.py (Lillicrap et al. 2015).
RLlib implements TD3 as a DDPG preset; here the relationship inverts the
same way: DDPG is the TD3 machinery with the three TD3 additions switched
off — a SINGLE critic (no clipped double-Q target), no target-policy
smoothing, and actor/target updates every step (policy_delay=1). The
rollout worker, replay buffer, and jitted scan-of-updates are shared.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .models import sac_pi_apply, sac_q_apply
from .sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS
from .td3 import TD3, TD3Config, TD3Learner


class DDPGConfig(TD3Config):
    def __init__(self):
        super().__init__()
        self.algo_class = DDPG
        # the three TD3 deltas, reverted to DDPG
        self.policy_delay = 1
        self.target_noise = 0.0
        self.target_noise_clip = 0.0
        self.exploration_noise = 0.1


class DDPGLearner(TD3Learner):
    """Single-critic losses: the target is Q1' alone (no min(q1,q2)
    pessimism), and only Q1 trains — the second head exists in the shared
    parameter structure but receives no gradient."""

    def __init__(self, *args, **kwargs):
        # direct construction must be DDPG too, not TD3-minus-one-critic:
        # revert TD3Learner's smoothing/delay defaults unless caller set them
        kwargs.setdefault("policy_delay", 1)
        kwargs.setdefault("target_noise", 0.0)
        kwargs.setdefault("target_noise_clip", 0.0)
        super().__init__(*args, **kwargs)

    def _losses(self, nets, target, mb, rng, actor_mask):
        mean_t, _ = sac_pi_apply(target, mb[NEXT_OBS])
        noise = jnp.clip(
            self.target_noise * jax.random.normal(rng, mean_t.shape),
            -self.target_noise_clip,
            self.target_noise_clip,
        )
        a_next = jnp.clip(jnp.tanh(mean_t) + noise, -1.0, 1.0)
        q1t, _ = sac_q_apply(target, mb[NEXT_OBS], a_next)
        y = mb[REWARDS] + self.gamma * (1.0 - mb[DONES]) * jax.lax.stop_gradient(q1t)
        q1, _ = sac_q_apply(nets, mb[OBS], mb[ACTIONS])
        critic_loss = 0.5 * jnp.mean((q1 - y) ** 2)

        mean, _ = sac_pi_apply(nets, mb[OBS])
        a_pi = jnp.tanh(mean)
        q1p, _ = sac_q_apply(jax.lax.stop_gradient(nets), mb[OBS], a_pi)
        actor_loss = -jnp.mean(q1p)

        total = critic_loss + actor_mask * actor_loss
        return total, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "mean_q": jnp.mean(q1),
        }


class DDPG(TD3):
    _config_class = DDPGConfig
    _learner_class = DDPGLearner
