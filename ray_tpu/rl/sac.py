"""SAC: continuous control with twin Q critics and entropy auto-tuning.

Reference parity: rllib/algorithms/sac/sac.py + sac_torch_policy.py
(squashed-Gaussian actor, twin Q, polyak targets, learned alpha). Like DQN
here, the num_sgd_iter gradient steps of an iteration run as one jitted
lax.scan; target networks and log_alpha ride in the scan carry.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm
from .config import AlgorithmConfig
from .learner import Learner, LearnerGroup, TrainState
from .models import init_sac_params, sac_pi_apply, sac_q_apply, sample_squashed_gaussian
from .replay_buffer import ReplayBuffer
from .rollout_worker import EnvLoopWorker, _make_env
from .sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS, SampleBatch


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=SAC)
        self.buffer_size: int = 100_000
        self.learning_starts: int = 1_000
        self.tau: float = 0.005  # polyak coefficient
        self.num_sgd_iter: int = 32
        self.initial_alpha: float = 0.1
        self.target_entropy: Optional[float] = None  # default: -act_dim
        self.lr = 3e-4
        self.minibatch_size = 256
        self.train_batch_size = 256  # env steps per iteration
        self.model = {"hidden": (256, 256)}


class _ContinuousWorker(EnvLoopWorker):
    """Sampling actor for Box action spaces; actions stored squashed in
    [-1, 1], scaled to the env's bounds only at step time."""

    def __init__(
        self,
        env_spec,
        num_envs: int = 1,
        rollout_fragment_length: int = 64,
        policy_hidden=(256, 256),
        seed: int = 0,
    ):
        super().__init__(env_spec, num_envs, seed)
        self.T = rollout_fragment_length
        space = self.envs[0].action_space
        self.act_dim = int(np.prod(space.shape))
        self.act_low = np.asarray(space.low, np.float32)
        self.act_high = np.asarray(space.high, np.float32)
        self.params = init_sac_params(
            jax.random.PRNGKey(seed), self.obs_dim, self.act_dim, policy_hidden
        )
        self._pi = jax.jit(sac_pi_apply)
        self._rng = np.random.default_rng(seed)

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        self.params = weights

    def _scale(self, a: np.ndarray) -> np.ndarray:
        return self.act_low + (a + 1.0) * 0.5 * (self.act_high - self.act_low)

    def _action(self, mean: np.ndarray, log_std: np.ndarray) -> np.ndarray:
        """Exploration policy in squashed [-1,1] space; TD3's worker swaps
        the learned-std Gaussian for deterministic + fixed noise."""
        noise = self._rng.standard_normal(mean.shape).astype(np.float32)
        return np.tanh(mean + np.exp(log_std) * noise)

    def sample(self) -> SampleBatch:
        E = self.num_envs
        cols = {
            OBS: np.empty((self.T, E, self.obs_dim), np.float32),
            ACTIONS: np.empty((self.T, E, self.act_dim), np.float32),
            REWARDS: np.empty((self.T, E), np.float32),
            NEXT_OBS: np.empty((self.T, E, self.obs_dim), np.float32),
            DONES: np.empty((self.T, E), np.float32),
        }
        for t in range(self.T):
            mean, log_std = jax.device_get(self._pi(self.params, self._obs))
            act = self._action(mean, log_std)
            cols[OBS][t] = self._obs
            cols[ACTIONS][t] = act
            for e in range(E):
                rew, term, _trunc, final = self._step_and_track(e, self._scale(act[e]))
                cols[REWARDS][t, e] = rew
                cols[NEXT_OBS][t, e] = final
                cols[DONES][t, e] = float(term)
        return SampleBatch(
            {k: v.reshape((self.T * E,) + v.shape[2:]) for k, v in cols.items()}
        )


class SACLearner(Learner):
    def __init__(
        self,
        obs_dim: int,
        act_dim: int,
        hidden=(256, 256),
        lr: float = 3e-4,
        gamma: float = 0.99,
        tau: float = 0.005,
        initial_alpha: float = 0.1,
        target_entropy: Optional[float] = None,
        num_sgd_iter: int = 32,
        minibatch_size: int = 256,
        seed: int = 0,
    ):
        super().__init__(config=None)
        self.gamma = gamma
        self.tau = tau
        self.num_sgd_iter = num_sgd_iter
        self.minibatch_size = minibatch_size
        self.target_entropy = (
            float(target_entropy) if target_entropy is not None else -float(act_dim)
        )
        self.optimizer = optax.adam(lr)
        nets = init_sac_params(jax.random.PRNGKey(seed), obs_dim, act_dim, hidden)
        params = {
            "nets": nets,
            "target_q": {"q1": jax.tree_util.tree_map(jnp.copy, nets["q1"]),
                         "q2": jax.tree_util.tree_map(jnp.copy, nets["q2"])},
            "log_alpha": jnp.asarray(np.log(initial_alpha), jnp.float32),
        }
        trainable = {"nets": nets, "log_alpha": params["log_alpha"]}
        self.state = TrainState(
            params=params,
            opt_state=self.optimizer.init(trainable),
            rng=jax.random.PRNGKey(seed + 1),
        )
        self._update_fn = None

    def _losses(self, trainable, target_q, mb, rng):
        nets = trainable["nets"]
        alpha = jnp.exp(trainable["log_alpha"])
        r1, r2 = jax.random.split(rng)

        # -- critic target --
        mean_n, log_std_n = sac_pi_apply(nets, mb[NEXT_OBS])
        a_next, logp_next = sample_squashed_gaussian(r1, mean_n, log_std_n)
        q1t, q2t = sac_q_apply({"q1": target_q["q1"], "q2": target_q["q2"]},
                               mb[NEXT_OBS], a_next)
        q_next = jnp.minimum(q1t, q2t) - jax.lax.stop_gradient(alpha) * logp_next
        y = mb[REWARDS] + self.gamma * (1.0 - mb[DONES]) * jax.lax.stop_gradient(q_next)

        q1, q2 = sac_q_apply(nets, mb[OBS], mb[ACTIONS])
        critic_loss = 0.5 * (jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2))

        # -- actor --
        mean, log_std = sac_pi_apply(nets, mb[OBS])
        a_pi, logp_pi = sample_squashed_gaussian(r2, mean, log_std)
        q1p, q2p = sac_q_apply(jax.lax.stop_gradient(nets), mb[OBS], a_pi)
        actor_loss = jnp.mean(
            jax.lax.stop_gradient(alpha) * logp_pi - jnp.minimum(q1p, q2p)
        )

        # -- temperature --
        alpha_loss = -jnp.mean(
            trainable["log_alpha"]
            * jax.lax.stop_gradient(logp_pi + self.target_entropy)
        )

        total = critic_loss + actor_loss + alpha_loss
        return total, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "alpha_loss": alpha_loss,
            "alpha": alpha,
            "mean_q": jnp.mean(q1),
            "entropy": -jnp.mean(logp_pi),
        }

    def _build_update(self):
        optimizer = self.optimizer
        tau = self.tau
        losses = self._losses

        def step(carry, inp):
            trainable, target_q, opt_state = carry
            mb, rng = inp
            (_, metrics), grads = jax.value_and_grad(losses, has_aux=True)(
                trainable, target_q, mb, rng
            )
            updates, opt_state = optimizer.update(grads, opt_state, trainable)
            trainable = optax.apply_updates(trainable, updates)
            # polyak target update
            target_q = jax.tree_util.tree_map(
                lambda t, o: (1.0 - tau) * t + tau * o,
                target_q,
                {"q1": trainable["nets"]["q1"], "q2": trainable["nets"]["q2"]},
            )
            return (trainable, target_q, opt_state), metrics

        def update(state: TrainState, minibatches):
            p = state.params
            rng, sub = jax.random.split(state.rng)
            n = jax.tree_util.tree_leaves(minibatches)[0].shape[0]
            rngs = jax.random.split(sub, n)
            trainable = {"nets": p["nets"], "log_alpha": p["log_alpha"]}
            (trainable, target_q, opt_state), metrics = jax.lax.scan(
                step, (trainable, p["target_q"], state.opt_state), (minibatches, rngs)
            )
            metrics = jax.tree_util.tree_map(lambda m: m.mean(), metrics)
            params = {
                "nets": trainable["nets"],
                "target_q": target_q,
                "log_alpha": trainable["log_alpha"],
            }
            return TrainState(params, opt_state, rng), metrics

        return jax.jit(update, donate_argnums=(0,))

    def update(self, buffer: ReplayBuffer) -> Dict[str, float]:
        samples = [buffer.sample(self.minibatch_size) for _ in range(self.num_sgd_iter)]
        minibatches = {
            k: jnp.asarray(np.stack([s[k] for s in samples])) for k in samples[0].keys()
        }
        if self._update_fn is None:
            self._update_fn = self._build_update()
        self.state, metrics = self._update_fn(self.state, minibatches)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.state.params["nets"])

    def set_weights(self, weights):
        p = dict(self.state.params)
        p["nets"] = jax.device_put(weights)
        self.state = self.state._replace(params=p)


class SAC(Algorithm):
    _config_class = SACConfig

    def _worker_cls(self):
        return _ContinuousWorker

    def _worker_kwargs(self):
        cfg = self.algo_config
        return dict(
            env_spec=cfg.env,
            num_envs=cfg.num_envs_per_worker,
            rollout_fragment_length=cfg.rollout_fragment_length,
            policy_hidden=tuple(cfg.model.get("hidden", (256, 256))),
        )

    def _build_learner(self) -> LearnerGroup:
        cfg = self.algo_config
        env = _make_env(cfg.env)
        obs_dim = int(np.prod(env.observation_space.shape))
        act_dim = int(np.prod(env.action_space.shape))
        env.close()
        self.replay = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)

        def factory():
            return SACLearner(
                obs_dim=obs_dim,
                act_dim=act_dim,
                hidden=tuple(cfg.model.get("hidden", (256, 256))),
                lr=cfg.lr,
                gamma=cfg.gamma,
                tau=cfg.tau,
                initial_alpha=cfg.initial_alpha,
                target_entropy=cfg.target_entropy,
                num_sgd_iter=cfg.num_sgd_iter,
                minibatch_size=cfg.minibatch_size,
                seed=cfg.seed,
            )

        return LearnerGroup(factory, remote=False)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        collected = 0
        while collected < cfg.train_batch_size:
            batch = self.workers.sample()
            self.replay.add(batch)
            collected += len(batch)
            self._timesteps_total += len(batch)
        metrics: Dict[str, Any] = {"replay_size": len(self.replay)}
        if len(self.replay) >= cfg.learning_starts:
            metrics.update(self.learner_group._learner.update(self.replay))
            self.workers.set_weights(self.learner_group.get_weights())
        metrics["num_env_steps_sampled_this_iter"] = collected
        return metrics
