"""RL library: CPU rollout-actor fleets feeding TPU learners.

Reference parity (SURVEY §2.3 RLlib rows, §3.6 call stack):
  - `AlgorithmConfig` fluent config   <- rllib/algorithms/algorithm_config.py
  - `RolloutWorker` / `WorkerSet`     <- rllib/evaluation/rollout_worker.py:166,
                                         worker_set.py:80
  - `SampleBatch`                     <- rllib/policy/sample_batch.py
  - `Learner` / `LearnerGroup`        <- rllib/core/learner/learner.py:170,
                                         learner_group.py:61
  - `Algorithm` (a tune Trainable)    <- rllib/algorithms/algorithm.py:149
  - `PPO`                             <- rllib/algorithms/ppo

TPU-first design: the sampling side stays numpy-on-CPU actors (envs are
Python), while the gradient side is a single pure-JAX update compiled over a
device mesh — epochs x minibatches run inside ONE jitted program
(lax.scan), not a Python SGD loop, and scale over the `dp` mesh axis via
sharded batches instead of the reference's NCCL allreduce between learner
actors.
"""

from .a2c import A2C, A2CConfig, A2CLearner  # noqa: F401
from .algorithm import Algorithm, WorkerSet  # noqa: F401
from .apex_dqn import ApexDQN, ApexDQNConfig, ReplayActor  # noqa: F401
from .appo import APPO, APPOConfig, APPOLearner  # noqa: F401
from .bandit import (  # noqa: F401
    BanditConfig,
    BanditLinTS,
    BanditLinTSConfig,
    BanditLinUCB,
)
from .config import AlgorithmConfig  # noqa: F401
from .dqn import DQN, DQNConfig, DQNLearner  # noqa: F401
from .es import ARS, ARSConfig, ES, ESConfig  # noqa: F401
from .impala import IMPALA, ImpalaConfig, ImpalaLearner, vtrace  # noqa: F401
from .learner import Learner, LearnerGroup  # noqa: F401
from .offline_algos import (  # noqa: F401
    BC,
    BCConfig,
    CQL,
    CQLConfig,
    MARWIL,
    MARWILConfig,
)
from .models import ac_apply, init_ac_params  # noqa: F401
from .policy import Policy  # noqa: F401
from .ppo import PPO, PPOConfig  # noqa: F401
from .replay_buffer import PrioritizedReplayBuffer, ReplayBuffer  # noqa: F401
from .rollout_worker import RolloutWorker  # noqa: F401
from .sac import SAC, SACConfig, SACLearner  # noqa: F401
from .ddpg import DDPG, DDPGConfig, DDPGLearner  # noqa: F401
from .td3 import TD3, TD3Config, TD3Learner  # noqa: F401
from .sample_batch import SampleBatch, compute_gae, concat_samples  # noqa: F401
from .multi_agent import (  # noqa: F401
    MultiAgentBatch,
    MultiAgentEnv,
    MultiAgentPPO,
    MultiAgentPPOConfig,
    MultiAgentRolloutWorker,
    make_multi_agent,
)
from .maddpg import MADDPG, MADDPGConfig  # noqa: F401
from .qmix import QMIX, QMIXConfig  # noqa: F401
from .qmix_rec import RecurrentQMIX, RecurrentQMIXConfig  # noqa: F401
from . import offline  # noqa: F401,E402
from . import llm  # noqa: F401,E402  (generation-based RL: PPO/GRPO)

from .._private.usage import record_library_usage as _rlu  # noqa: E402

_rlu("rl")
