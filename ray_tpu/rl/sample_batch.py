"""SampleBatch: columnar rollout storage + GAE.

Reference parity: rllib/policy/sample_batch.py (SampleBatch,
concat_samples) and the GAE postprocessing in
rllib/evaluation/postprocessing.py (compute_advantages). Kept numpy-native:
batches are built on CPU rollout actors and shipped to the learner host,
where they become device arrays once, sharded over the mesh.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence

import numpy as np

OBS = "obs"
NEXT_OBS = "next_obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
VALUES = "values"
LOGP = "logp"
ADVANTAGES = "advantages"
TARGETS = "value_targets"
# 0 for rows that exist only as shape padding (multi-agent ragged batches);
# mask-aware learners give them zero gradient weight
LOSS_MASK = "loss_mask"


class SampleBatch(dict):
    """A dict of equally-long numpy columns."""

    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        perm = rng.permutation(len(self))
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        for i in range(0, len(self), size):
            yield self.slice(i, i + size)

    def truncate(self, n: int) -> "SampleBatch":
        return self.slice(0, n)


def concat_samples(batches: Sequence[SampleBatch]) -> SampleBatch:
    """rllib sample_batch.py concat_samples equivalent."""
    batches = [b for b in batches if len(b) > 0]
    if not batches:
        return SampleBatch()
    keys = batches[0].keys()
    return SampleBatch({k: np.concatenate([b[k] for b in batches]) for k in keys})


def compute_gae(
    rewards: np.ndarray,
    values: np.ndarray,
    dones: np.ndarray,
    bootstrap_value: np.ndarray,
    gamma: float = 0.99,
    lam: float = 0.95,
) -> Dict[str, np.ndarray]:
    """Generalized Advantage Estimation over a [T, E] rollout block.

    rewards/values/dones: [T, E]; bootstrap_value: [E] (value of the state
    after the last step, zeroed where done). Returns advantages and value
    targets, both [T, E].
    """
    T = rewards.shape[0]
    adv = np.zeros_like(rewards, dtype=np.float32)
    next_value = bootstrap_value.astype(np.float32)
    next_adv = np.zeros_like(next_value)
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        next_adv = delta + gamma * lam * nonterminal * next_adv
        adv[t] = next_adv
        next_value = values[t]
    targets = adv + values
    return {ADVANTAGES: adv, TARGETS: targets}
