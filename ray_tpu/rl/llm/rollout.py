"""Generation rollouts THROUGH the serving stack.

The reference's env-stepping RolloutWorker (rollout_worker.py:166) samples
by calling env.step in Python; a generation-based RL worker samples by
GENERATING — so instead of a gym loop, LLMRolloutWorker drives the exact
serving data path a live replica runs: ContinuousBatcher in front of a
PagedDecodeEngine built with `logprobs=True`, which emits every sampled
token as an atomic `(token_id, behavior_logprob)` pair. Rollout traffic
therefore gets continuous batching, paged KV, chunked prefill and
preemption/readmission for free, and — because the engine is the same
class a serve Replica wraps — a WeightSubscriber (serve/weight_swap.py)
can hot-swap learner weights under it between steps mid-experiment.

The worker turns a prompt list into the padded batch layout the learner
and advantages modules share:

  tokens         [N, L] i32   prompt + response, right-padded
  loss_mask      [N, T] f32   T = L-1, shifted axis: 1.0 where position t
                              PREDICTS a response token (tokens[:, t+1])
  behavior_logp  [N, T] f32   engine logprob of that token at sample time
  rewards        [N]    f32   reward_fn(prompt_tokens, response_tokens)
  group          [N]    i32   prompt index — GRPO's sample groups
  prompt_len / response_len [N] i32

GRPO's group_size submits each prompt group_size times; the engine's
seeded sampler keeps runs reproducible, and per-request RNG streams give
the group its diversity.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ...util.metrics import rl_reward_mean_gauge, rl_rollout_tokens_counter

RewardFn = Callable[[np.ndarray, np.ndarray], float]


class LLMRolloutWorker:
    """Owns one serving stack (batcher + paged engine) and samples
    experience batches from it.

    `pad_to` fixes the token-grid length L so every rollout compiles the
    learner's update exactly once (defaults to the worst case:
    longest prompt + max_new_tokens, recomputed per call when prompts
    vary). `reward_fn(prompt_tokens, response_tokens) -> float` is the
    task: the only environment a generation-based RL run has."""

    def __init__(
        self,
        cfg,
        params,
        reward_fn: RewardFn,
        *,
        group_size: int = 1,
        max_new_tokens: int = 16,
        temperature: float = 1.0,
        seed: int = 0,
        mesh=None,
        rules=None,
        max_batch_size: Optional[int] = None,
        pad_to: Optional[int] = None,
        deployment: str = "rl_llm",
        replica: str = "rollout0",
        telemetry=False,
        engine_kwargs: Optional[Dict[str, Any]] = None,
    ):
        # serving-stack imports stay lazy so `import ray_tpu.rl` does not
        # drag the serve package in (mirrors the engine's own discipline)
        from ...models.kv_paging import PagedDecodeEngine
        from ...serve.batching import ContinuousBatcher

        if group_size < 1:
            raise ValueError(f"group_size must be >= 1, got {group_size}")
        kw = dict(engine_kwargs or {})
        kw.setdefault("speculative_k", 0)  # logprobs need per-step logits
        if max_batch_size is not None:
            kw.setdefault("max_batch_size", max_batch_size)
        self.engine = PagedDecodeEngine(
            cfg,
            params,
            temperature=temperature,
            logprobs=True,
            default_max_new_tokens=max_new_tokens,
            seed=seed,
            mesh=mesh,
            rules=rules,
            telemetry=telemetry,
            **kw,
        )
        self.batcher = ContinuousBatcher(self.engine, telemetry=telemetry)
        self.reward_fn = reward_fn
        self.group_size = int(group_size)
        self.max_new_tokens = int(max_new_tokens)
        self.pad_to = pad_to
        self._tags = {"deployment": deployment, "replica": replica}
        self._tokens_total = rl_rollout_tokens_counter()
        self._reward_gauge = rl_reward_mean_gauge()
        self.rollouts = 0

    # ------------------------------------------------------------- weights

    def set_params(self, params, version: Optional[int] = None) -> int:
        """Adopt new policy weights between engine steps (the learner's
        post-update sync). Runs on the batcher loop thread — the same
        swap point a live replica's WeightSubscriber uses."""
        return self.batcher.run_on_loop(
            lambda: self.engine.set_params(params, version=version)
        )

    @property
    def weight_version(self) -> int:
        return int(getattr(self.engine, "weight_version", 0))

    # ------------------------------------------------------------- rollout

    def rollout(
        self,
        prompts: Sequence[Sequence[int]],
        *,
        max_new_tokens: Optional[int] = None,
    ) -> Dict[str, np.ndarray]:
        """Sample group_size responses per prompt; returns the padded
        batch dict (layout in the module docstring)."""
        mnt = int(max_new_tokens or self.max_new_tokens)
        streams: List[tuple] = []
        for gi, p in enumerate(prompts):
            toks = np.asarray(p, np.int32).reshape(-1)
            for _ in range(self.group_size):
                streams.append((
                    gi, toks,
                    self.batcher.submit(tokens=toks, max_new_tokens=mnt),
                ))
        rows = []
        for gi, toks, stream in streams:
            pairs: List[tuple] = []
            while True:
                items, done = stream.next_batch(max_items=512, wait_s=10.0)
                pairs.extend(items)
                if done:
                    break
            resp = np.asarray([t for t, _ in pairs], np.int32)
            blp = np.asarray([lp for _, lp in pairs], np.float32)
            reward = float(self.reward_fn(toks, resp))
            rows.append((gi, toks, resp, blp, reward))

        N = len(rows)
        longest = max(r[1].size + r[2].size for r in rows)
        L = max(int(self.pad_to or 0), longest, 2)
        T = L - 1
        tokens = np.zeros((N, L), np.int32)
        loss_mask = np.zeros((N, T), np.float32)
        behavior_logp = np.zeros((N, T), np.float32)
        rewards = np.zeros(N, np.float32)
        group = np.zeros(N, np.int32)
        prompt_len = np.zeros(N, np.int32)
        response_len = np.zeros(N, np.int32)
        for i, (gi, toks, resp, blp, reward) in enumerate(rows):
            pl, rl = toks.size, resp.size
            tokens[i, :pl] = toks
            tokens[i, pl:pl + rl] = resp
            # response token j lives at index pl+j, predicted at t=pl+j-1
            loss_mask[i, pl - 1:pl - 1 + rl] = 1.0
            behavior_logp[i, pl - 1:pl - 1 + rl] = blp
            rewards[i] = reward
            group[i] = gi
            prompt_len[i] = pl
            response_len[i] = rl

        total_resp = int(response_len.sum())
        self._tokens_total.inc(total_resp, tags=self._tags)
        self._reward_gauge.set(float(rewards.mean()), tags=self._tags)
        self.rollouts += 1
        return {
            "tokens": tokens,
            "loss_mask": loss_mask,
            "behavior_logp": behavior_logp,
            "rewards": rewards,
            "group": group,
            "prompt_len": prompt_len,
            "response_len": response_len,
        }

    def close(self) -> None:
        self.batcher.close()
