"""Advantage estimation for generation-based RL (host-side numpy).

Two estimators, one batch layout. Rollout batches (rl/llm/rollout.py) are
padded [N, L] token grids; everything time-indexed here lives on the
SHIFTED axis T = L-1 — index t scores the prediction of tokens[:, t+1] —
so advantages drop straight into the learner's per-position logprob grid
with no realignment.

  gae_advantages   PPO: token-level GAE(gamma, lambda) over the response
                   span. The scalar sequence reward lands on the LAST
                   response token (terminal transition, bootstrap 0);
                   interior response steps carry reward 0 and bootstrap
                   the critic — the standard RLHF shaping.
  grpo_advantages  GRPO: no critic. Each prompt's group of sampled
                   responses normalizes its own rewards,
                   (r - mean_g) / (std_g + eps), broadcast over that
                   response's tokens. A group of one (or zero variance)
                   yields zero advantage — the estimator is RELATIVE by
                   construction, so group_size >= 2 is the useful regime.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def gae_advantages(
    rewards: np.ndarray,
    values: np.ndarray,
    loss_mask: np.ndarray,
    gamma: float = 1.0,
    lam: float = 0.95,
) -> Tuple[np.ndarray, np.ndarray]:
    """Token-level GAE over response positions.

    rewards [N] scalar sequence rewards; values [N, T] critic outputs on
    the shifted axis; loss_mask [N, T] 1.0 on response positions.
    Returns (advantages [N, T], returns [N, T]) — returns are the critic
    regression targets (adv + value), zero off-response.
    """
    rewards = np.asarray(rewards, np.float64)
    values = np.asarray(values, np.float64)
    m = np.asarray(loss_mask, bool)
    N, T = m.shape
    adv = np.zeros((N, T), np.float64)
    ret = np.zeros((N, T), np.float64)
    # last response position per row (terminal transition); rows with no
    # response tokens never match t == last (last = -1) and stay zero
    has = m.any(axis=1)
    last = np.where(has, T - 1 - np.argmax(m[:, ::-1], axis=1), -1)
    a_next = np.zeros(N, np.float64)
    v_next = np.zeros(N, np.float64)
    for t in range(T - 1, -1, -1):
        active = m[:, t]
        terminal = last == t
        r_t = np.where(terminal, rewards, 0.0)
        delta = r_t + gamma * np.where(terminal, 0.0, v_next) - values[:, t]
        a_t = delta + gamma * lam * np.where(terminal, 0.0, a_next)
        adv[:, t] = np.where(active, a_t, 0.0)
        ret[:, t] = np.where(active, a_t + values[:, t], 0.0)
        a_next = np.where(active, a_t, a_next)
        v_next = np.where(active, values[:, t], v_next)
    return adv.astype(np.float32), ret.astype(np.float32)


def grpo_advantages(
    rewards: np.ndarray,
    group: np.ndarray,
    loss_mask: np.ndarray,
    eps: float = 1e-6,
) -> np.ndarray:
    """Group-relative advantages: rewards [N], group [N] (same id = same
    prompt's sample group), loss_mask [N, T]. Returns [N, T] — the
    normalized scalar broadcast over each response's tokens."""
    rewards = np.asarray(rewards, np.float64)
    group = np.asarray(group)
    scalar = np.zeros(rewards.shape[0], np.float64)
    for g in np.unique(group):
        idx = np.nonzero(group == g)[0]
        if idx.size < 2:
            continue  # relative estimator needs a peer to compare against
        r = rewards[idx]
        scalar[idx] = (r - r.mean()) / (r.std() + eps)
    return (scalar[:, None] * np.asarray(loss_mask, np.float64)).astype(
        np.float32
    )


def normalize_advantages(
    adv: np.ndarray, loss_mask: np.ndarray, eps: float = 1e-6
) -> np.ndarray:
    """Batch-whiten over MASKED entries only (padding zeros would
    otherwise drag the mean) — the usual PPO variance-reduction step."""
    adv = np.asarray(adv, np.float64)
    m = np.asarray(loss_mask, bool)
    if not m.any():
        return adv.astype(np.float32)
    vals = adv[m]
    out = np.where(m, (adv - vals.mean()) / (vals.std() + eps), 0.0)
    return out.astype(np.float32)
