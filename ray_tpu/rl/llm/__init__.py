"""Generation-based RL: PPO/GRPO where the environment is the model.

Reference parity: RLlib's new-stack Learner + the RLHF pattern the LLM
systems world converged on (rollouts through a serving engine, learner
updates on a training mesh, live weight sync between them). The pieces:

  rollout.LLMRolloutWorker     samples through ContinuousBatcher +
                               PagedDecodeEngine(logprobs=True) — the
                               serving stack IS the env loop
  advantages                   token-level GAE (PPO) / group-relative
                               normalized returns (GRPO)
  learner.LLMLearner           clipped policy updates on the sharded
                               train-step machinery (+ value head for PPO)
  trainer.GenerationRLTrainer  rollout -> advantages -> update -> weight
                               sync; plugs into serve/weight_swap.py's
                               WeightPublisher for live replica hot-swap

See rl/README.md ("Generation-based RL") for the walkthrough.
"""

from .advantages import (  # noqa: F401
    gae_advantages,
    grpo_advantages,
    normalize_advantages,
)
from .learner import LLMLearner  # noqa: F401
from .rollout import LLMRolloutWorker  # noqa: F401
from .trainer import GenerationRLTrainer  # noqa: F401
