"""The generation-RL driver: rollout -> advantages -> update -> weight sync.

One `step()` is the full PPO/GRPO iteration:

  1. the rollout worker samples group_size responses per prompt through
     the serving stack (behavior logprobs ride the token stream),
  2. advantages: GAE against the learner's value head (PPO) or
     group-relative normalized rewards (GRPO),
  3. the learner runs the clipped update,
  4. the new weights reach the sampler — DIRECTLY (set_params between
     engine steps) by default, or through the live weight plane when a
     WeightPublisher is given: the learner publishes a version, serving
     replicas' WeightSubscribers pull and hot-swap on their own, and the
     local rollout worker adopts the same version so behavior policy and
     published version never diverge.

That last arm is the on-policy contract: every rollout batch is sampled
by the weights of the update that precedes it, so `behavior_logp` is the
current policy's logprob on epoch one and the importance ratio starts at
1.0 exactly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .advantages import gae_advantages, grpo_advantages, normalize_advantages
from .learner import ALGOS, LLMLearner
from .rollout import LLMRolloutWorker, RewardFn


class GenerationRLTrainer:
    """PPO ('ppo') / GRPO ('grpo') over a fixed prompt set.

    With `publisher` (serve/weight_swap.WeightPublisher) every update
    also publishes a bulk-plane weight version for subscribing replicas;
    without one the trainer is fully local (no cluster needed)."""

    def __init__(
        self,
        cfg,
        reward_fn: RewardFn,
        prompts: Sequence[Sequence[int]],
        *,
        algo: str = "grpo",
        params=None,
        seed: int = 0,
        group_size: int = 4,
        max_new_tokens: int = 8,
        temperature: float = 1.0,
        lr: float = 3e-3,
        epochs: int = 1,
        clip_ratio: float = 0.2,
        vf_coef: float = 0.5,
        entropy_coef: float = 0.0,
        kl_coef: float = 0.0,
        gamma: float = 1.0,
        gae_lambda: float = 0.95,
        normalize_adv: Optional[bool] = None,
        mesh=None,
        rules=None,
        publisher=None,
        engine_kwargs: Optional[Dict[str, Any]] = None,
        deployment: str = "rl_llm",
        replica: str = "rollout0",
    ):
        import jax

        from ...models.transformer import init_params

        if algo not in ALGOS:
            raise ValueError(f"algo must be one of {ALGOS}, got {algo!r}")
        if algo == "grpo" and group_size < 2:
            raise ValueError(
                "GRPO is group-RELATIVE: group_size must be >= 2"
            )
        self.algo = algo
        self.prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
        self.gamma = float(gamma)
        self.gae_lambda = float(gae_lambda)
        # GRPO advantages arrive normalized per group; whitening again
        # across the batch would fight that. PPO whitens by default.
        self.normalize_adv = (
            (algo == "ppo") if normalize_adv is None else bool(normalize_adv)
        )
        self.publisher = publisher

        if params is None:
            params = init_params(jax.random.PRNGKey(seed), cfg)
        self.learner = LLMLearner(
            cfg,
            params,
            algo=algo,
            temperature=temperature,
            lr=lr,
            clip_ratio=clip_ratio,
            vf_coef=vf_coef,
            entropy_coef=entropy_coef,
            kl_coef=kl_coef,
            epochs=epochs,
            mesh=mesh,
            rules=rules,
        )
        longest = max(p.size for p in self.prompts)
        self.worker = LLMRolloutWorker(
            cfg,
            self.learner.params,
            reward_fn,
            group_size=group_size,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            seed=seed,
            mesh=mesh,
            rules=rules,
            # fixed grid: one compile of the update for the whole run
            pad_to=longest + int(max_new_tokens),
            deployment=deployment,
            replica=replica,
            engine_kwargs=engine_kwargs,
        )
        self.iteration = 0
        self.history: List[Dict[str, float]] = []

    def step(self) -> Dict[str, float]:
        """One rollout->update->sync iteration; returns its metrics."""
        batch = self.worker.rollout(self.prompts)
        if self.algo == "grpo":
            adv = grpo_advantages(
                batch["rewards"], batch["group"], batch["loss_mask"]
            )
        else:
            values = self.learner.values(batch["tokens"])
            adv, ret = gae_advantages(
                batch["rewards"],
                values,
                batch["loss_mask"],
                gamma=self.gamma,
                lam=self.gae_lambda,
            )
            batch["returns"] = ret
        if self.normalize_adv:
            adv = normalize_advantages(adv, batch["loss_mask"])
        batch["advantages"] = adv

        metrics = self.learner.update(batch)

        version: Optional[int] = None
        if self.publisher is not None:
            version = self.publisher.publish(self.learner.params)
        self.worker.set_params(self.learner.params, version=version)

        self.iteration += 1
        metrics.update(
            reward_mean=float(batch["rewards"].mean()),
            reward_max=float(batch["rewards"].max()),
            response_tokens=float(batch["response_len"].sum()),
            weight_version=float(self.worker.weight_version),
            iteration=float(self.iteration),
        )
        self.history.append(metrics)
        return metrics

    def train(self, iterations: int) -> List[Dict[str, float]]:
        return [self.step() for _ in range(int(iterations))]

    def close(self) -> None:
        self.worker.close()
