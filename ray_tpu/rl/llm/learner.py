"""PPO-clip / GRPO policy updates over the sharded transformer.

Reference parity: the new-stack Learner (rl/learner.py's PPO machinery)
re-specialized for generation batches. The update rides the SAME sharded
train-step machinery as supervised training (train/step.py): param
shardings come from `param_specs` + the rule table, batches use the
`batch_sharding` pytree prefix, and the whole update is one jitted
program whose gradient collectives GSPMD derives from the sharding specs
alone. (No donated state: the RL learner is exercised by tiny-config CPU
tests, where donation trips the persistent-compile-cache aliasing issue —
see ROADMAP.)

Policy logprobs re-derive through `make_forward(_return_backbone=True)`
with EXACTLY the serving engine's sampler semantics — fp32 logits,
vocab_pad tail masked to NEG_INF, same temperature divide — so the
importance ratio exp(logp - behavior_logp) is 1.0 (up to fp noise) on the
first epoch by construction. The PPO value head is a scalar projection of
the backbone's final hidden states (w [E] + bias), trained on GAE returns
— GRPO has no critic, that's its point.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

ALGOS = ("ppo", "grpo")


class LLMLearner:
    """One policy (+ optional value head) and its optimizer.

    update(batch) expects the rollout layout (rl/llm/rollout.py) plus
    `advantages` [N, T] (and, for PPO, `returns` [N, T]) from
    rl/llm/advantages.py. `params` always exposes the CURRENT model
    params — what publishers ship and rollout workers adopt."""

    def __init__(
        self,
        cfg,
        params,
        *,
        algo: str = "grpo",
        temperature: float = 1.0,
        lr: float = 3e-3,
        clip_ratio: float = 0.2,
        vf_coef: float = 0.5,
        entropy_coef: float = 0.0,
        kl_coef: float = 0.0,
        epochs: int = 1,
        mesh=None,
        rules=None,
        optimizer=None,
    ):
        import jax
        import jax.numpy as jnp
        import optax

        from ...models.transformer import NEG_INF, make_forward, param_specs

        if algo not in ALGOS:
            raise ValueError(f"algo must be one of {ALGOS}, got {algo!r}")
        self.algo = algo
        self.cfg = cfg
        self.epochs = int(epochs)
        self.updates = 0
        clip = float(clip_ratio)
        temp = float(temperature)
        vf = float(vf_coef)
        ent_c = float(entropy_coef)
        kl_c = float(kl_coef)
        vocab_pad = int(getattr(cfg, "vocab_pad", 0) or 0)

        forward, backbone, _constrain = make_forward(
            cfg, rules, mesh, _return_backbone=True
        )

        train_params: Dict[str, Any] = {"model": params}
        if algo == "ppo":
            train_params["value_w"] = jnp.zeros((cfg.d_model,), jnp.float32)
            train_params["value_b"] = jnp.zeros((), jnp.float32)

        if optimizer is None:
            optimizer = optax.chain(
                optax.clip_by_global_norm(1.0), optax.adam(lr)
            )
        self._optimizer = optimizer
        self._opt_state = optimizer.init(train_params)
        self._train_params = train_params

        def _logp_and_hidden(model_params, tokens):
            # engine-sampler-identical logprob semantics (kv_paging._lp):
            # fp32 -> vocab_pad tail to NEG_INF -> /temperature -> softmax
            x, unembed = backbone(model_params, tokens[:, :-1])
            logits = jnp.einsum("bse,ev->bsv", x, unembed)
            logits = _constrain(logits, "batch", "seq", "vocab")
            logits = logits.astype(jnp.float32)
            if vocab_pad:
                V = logits.shape[-1]
                pad = jnp.arange(V) >= V - vocab_pad
                logits = jnp.where(pad, NEG_INF, logits)
            if temp > 0.0:
                logits = logits / temp
            logp_all = jax.nn.log_softmax(logits, axis=-1)
            tgt = tokens[:, 1:].astype(jnp.int32)
            logp = jnp.take_along_axis(logp_all, tgt[..., None], axis=-1)
            return logp[..., 0], logp_all, x

        def _value(tp, x):
            h = x.astype(jnp.float32)
            return jnp.einsum("bse,e->bs", h, tp["value_w"]) + tp["value_b"]

        def loss_fn(tp, batch):
            logp, logp_all, x = _logp_and_hidden(tp["model"], batch["tokens"])
            w = batch["loss_mask"].astype(jnp.float32)
            wsum = jnp.maximum(w.sum(), 1.0)
            adv = batch["advantages"].astype(jnp.float32)
            blp = batch["behavior_logp"].astype(jnp.float32)
            ratio = jnp.exp(logp - blp)
            clipped = jnp.clip(ratio, 1.0 - clip, 1.0 + clip)
            pg = -(jnp.minimum(ratio * adv, clipped * adv) * w).sum() / wsum
            total = pg
            metrics = {
                "pg_loss": pg,
                "ratio_mean": (ratio * w).sum() / wsum,
                "clip_frac": (
                    (jnp.abs(ratio - 1.0) > clip).astype(jnp.float32) * w
                ).sum() / wsum,
            }
            if algo == "ppo":
                v = _value(tp, x)
                v_loss = (
                    jnp.square(v - batch["returns"].astype(jnp.float32)) * w
                ).sum() / wsum
                total = total + vf * v_loss
                metrics["vf_loss"] = v_loss
            if kl_c:
                # k3 estimator vs the behavior policy: non-negative,
                # low-variance (the GRPO-paper form)
                d = blp - logp
                kl = ((jnp.exp(d) - d - 1.0) * w).sum() / wsum
                total = total + kl_c * kl
                metrics["kl"] = kl
            if ent_c:
                p = jnp.exp(logp_all)
                ent = (-(p * logp_all).sum(-1) * w).sum() / wsum
                total = total - ent_c * ent
                metrics["entropy"] = ent
            metrics["loss"] = total
            return total, metrics

        def step_fn(tp, opt_state, batch):
            (_, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(tp, batch)
            updates, opt_state = optimizer.update(grads, opt_state, tp)
            tp = optax.apply_updates(tp, updates)
            metrics["grad_norm"] = optax.global_norm(grads)
            return tp, opt_state, metrics

        if mesh is not None and rules is not None:
            # the existing sharded-train-step machinery: model leaves by
            # the rule table, value head + scalars replicated, opt state
            # matched by leaf shape, batch as a sharding prefix
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ...train.step import (
                _opt_shardings,
                _param_shardings,
                batch_sharding,
            )

            repl = NamedSharding(mesh, P())
            tp_shard: Dict[str, Any] = {
                "model": _param_shardings(mesh, rules, param_specs(cfg))
            }
            if algo == "ppo":
                tp_shard["value_w"] = repl
                tp_shard["value_b"] = repl
            tp_shapes = jax.eval_shape(lambda t: t, train_params)
            o_shapes = jax.eval_shape(optimizer.init, tp_shapes)
            o_shard = _opt_shardings(o_shapes, tp_shapes, tp_shard, mesh)
            b_shard = batch_sharding(mesh, rules)
            self._step = jax.jit(
                step_fn,
                in_shardings=(tp_shard, o_shard, b_shard),
                out_shardings=(tp_shard, o_shard, None),
            )
            self._values_fn = jax.jit(
                lambda tp, tokens: _value(
                    tp, _logp_and_hidden(tp["model"], tokens)[2]
                ),
                in_shardings=(tp_shard, b_shard),
            ) if algo == "ppo" else None
            self._train_params = jax.device_put(train_params, tp_shard)
            self._opt_state = jax.device_put(self._opt_state, o_shard)
        else:
            self._step = jax.jit(step_fn)
            self._values_fn = (
                jax.jit(
                    lambda tp, tokens: _value(
                        tp, _logp_and_hidden(tp["model"], tokens)[2]
                    )
                )
                if algo == "ppo"
                else None
            )

        # engine-parity logprob probe (tests, diagnostics): logp [N, T]
        self._logp_fn = jax.jit(
            lambda mp, tokens: _logp_and_hidden(mp, tokens)[0]
        )

    # ----------------------------------------------------------------- api

    @property
    def params(self):
        """Current model params — the tree publishers ship."""
        return self._train_params["model"]

    def values(self, tokens: np.ndarray) -> np.ndarray:
        """Critic values [N, T] for GAE (PPO only)."""
        if self._values_fn is None:
            raise RuntimeError("values() is PPO-only — GRPO has no critic")
        return np.asarray(
            self._values_fn(self._train_params, np.asarray(tokens, np.int32))
        )

    def policy_logp(self, tokens: np.ndarray) -> np.ndarray:
        """Per-position logprobs [N, T] under the CURRENT policy, engine
        sampler semantics — the parity probe against behavior_logp."""
        return np.asarray(
            self._logp_fn(self.params, np.asarray(tokens, np.int32))
        )

    def update(
        self, batch: Dict[str, np.ndarray], epochs: Optional[int] = None
    ) -> Dict[str, float]:
        """Run the clipped update `epochs` times over the batch; returns
        the LAST epoch's metrics (floats)."""
        required = ("tokens", "loss_mask", "behavior_logp", "advantages")
        for k in required:
            if k not in batch:
                raise KeyError(f"update batch missing {k!r}")
        if self.algo == "ppo" and "returns" not in batch:
            raise KeyError("PPO update batch missing 'returns'")
        feed = {
            k: np.asarray(v)
            for k, v in batch.items()
            if k in required + ("returns",)
        }
        metrics: Dict[str, Any] = {}
        for _ in range(int(epochs or self.epochs)):
            self._train_params, self._opt_state, metrics = self._step(
                self._train_params, self._opt_state, feed
            )
        self.updates += 1
        return {k: float(v) for k, v in metrics.items()}
