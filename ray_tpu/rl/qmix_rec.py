"""Recurrent QMIX: GRU agents + EPISODE replay for POMDP cooperative MARL.

Reference parity: rllib/algorithms/qmix/qmix_policy.py — the reference's
QMIX is recurrent (RNN agent networks unrolled over whole episodes drawn
from an episode replay buffer), which is what lets agents act on memory in
partially observed tasks; qmix.py here is the feedforward transition-replay
variant. TPU-first: the GRU unroll is a lax.scan over time INSIDE one
jitted update (batch of episodes in parallel), mixer and TD masking fused
into the same program — one dispatch per gradient step.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .learner import TrainState
from .multi_agent import MultiAgentEnv
from .qmix import QMIX, QMIXConfig, _dense, mix, init_qmix_params


def init_rec_params(rng, obs_dim, n_agents, n_actions, state_dim,
                    rnn_hidden=64, mixing_embed=32):
    """GRU agent net (shared, id-onehot input) + the same mixer hypernets."""
    ks = jax.random.split(rng, 10)
    in_dim = obs_dim + n_agents
    agent = {
        "enc": _dense(ks[0], in_dim, rnn_hidden),
        # GRU gates: one fused input->3H and hidden->3H block each
        "gru_x": _dense(ks[1], rnn_hidden, 3 * rnn_hidden),
        "gru_h": _dense(ks[2], rnn_hidden, 3 * rnn_hidden),
        "out": _dense(ks[3], rnn_hidden, n_actions, scale=0.01),
    }
    mixer = init_qmix_params(
        ks[4], obs_dim, n_agents, n_actions, state_dim,
        mixing_embed=mixing_embed,
    )["mixer"]
    return {"agent": agent, "mixer": mixer}


def gru_cell(params, h, x_enc):
    """Fused-gate GRU step: h' = GRU(h, x_enc). Shapes [..., H]."""
    gx = x_enc @ params["gru_x"]["w"] + params["gru_x"]["b"]
    gh = h @ params["gru_h"]["w"] + params["gru_h"]["b"]
    H = h.shape[-1]
    z = jax.nn.sigmoid(gx[..., :H] + gh[..., :H])
    r = jax.nn.sigmoid(gx[..., H:2 * H] + gh[..., H:2 * H])
    n = jnp.tanh(gx[..., 2 * H:] + r * gh[..., 2 * H:])
    return (1.0 - z) * n + z * h


def agent_step(params, h, obs_id):
    """One acting step: (hidden, obs+id) -> (new hidden, q-values)."""
    a = params["agent"]
    x = jax.nn.relu(obs_id @ a["enc"]["w"] + a["enc"]["b"])
    h = gru_cell(a, h, x)
    return h, h @ a["out"]["w"] + a["out"]["b"]


def agent_q_unroll(params, obs_id_seq, h0):
    """Unroll over time: [T, ..., in] -> [T, ..., n_actions]."""

    def step(h, obs_id):
        h, q = agent_step(params, h, obs_id)
        return h, q

    _, q_seq = jax.lax.scan(step, h0, obs_id_seq)
    return q_seq


class RecurrentQMIXConfig(QMIXConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = RecurrentQMIX
        self.rnn_hidden: int = 64
        self.episode_limit: int = 32       # max episode length (padded to)
        self.buffer_size = 2_000           # EPISODES, not transitions
        self.learning_starts = 32          # episodes before training
        self.minibatch_size = 32           # episodes per gradient step
        self.train_batch_size = 8          # episodes collected per iteration


class RecurrentQMIX(QMIX):
    """Episode-replay QMIX with memoryful agents (reference qmix_policy.py
    recurrence). Collection runs whole episodes; the update unrolls the
    shared GRU over each episode with TD masking past episode end."""

    _config_class = RecurrentQMIXConfig

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = self.algo_config
        if not callable(cfg.env):
            raise ValueError("RecurrentQMIX needs a callable MultiAgentEnv maker")
        self.env: MultiAgentEnv = cfg.env()
        self.agents = list(self.env.possible_agents)
        self.n_agents = len(self.agents)
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.n_actions = int(self.env.action_space.n)
        self._obs, _ = self.env.reset(seed=cfg.seed)
        self.state_dim = int(np.asarray(self.env.get_state()).shape[0])

        params = init_rec_params(
            jax.random.PRNGKey(cfg.seed), self.obs_dim, self.n_agents,
            self.n_actions, self.state_dim, cfg.rnn_hidden, cfg.mixing_embed,
        )
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(10.0), optax.adam(cfg.lr)
        )
        self.state = TrainState(
            params={"online": params, "target": jax.tree.map(jnp.copy, params)},
            opt_state=self.optimizer.init(params),
            rng=jax.random.PRNGKey(cfg.seed + 1),
        )
        self._step_fn = jax.jit(agent_step)
        self._update_fn = None
        self._grad_steps = 0
        self._eps_rng = np.random.default_rng(cfg.seed + 2)
        self._episodes: List[dict] = []
        self._buf_pos = 0
        self._env_steps = 0
        self._recent_returns: List[float] = []
        self._id_eye = np.eye(self.n_agents, dtype=np.float32)

    # ------------------------------------------------------------ rollouts

    def _collect_episode(self) -> dict:
        cfg = self.algo_config
        T = cfg.episode_limit
        ep = {
            "obs": np.zeros((T + 1, self.n_agents, self.obs_dim), np.float32),
            "state": np.zeros((T + 1, self.state_dim), np.float32),
            "actions": np.zeros((T, self.n_agents), np.int64),
            "reward": np.zeros(T, np.float32),
            "done": np.zeros(T, np.float32),
            "mask": np.zeros(T, np.float32),
        }
        obs, _ = self.env.reset()
        h = jnp.zeros((self.n_agents, cfg.rnn_hidden), jnp.float32)
        ret, eps = 0.0, self._epsilon()
        for t in range(T):
            obs_all = np.stack([obs[a] for a in self.agents]).reshape(
                self.n_agents, self.obs_dim
            )
            ep["obs"][t] = obs_all
            ep["state"][t] = np.asarray(self.env.get_state(), np.float32)
            inp = np.concatenate([obs_all, self._id_eye], axis=-1)
            h, q = self._step_fn(self.state.params["online"], h, jnp.asarray(inp))
            acts = np.asarray(jax.device_get(q)).argmax(axis=-1)
            explore = self._eps_rng.random(self.n_agents) < eps
            acts[explore] = self._eps_rng.integers(0, self.n_actions, explore.sum())
            nobs, rews, terms, truncs, _ = self.env.step(
                {a: int(acts[i]) for i, a in enumerate(self.agents)}
            )
            team_r = float(sum(rews.values()))
            ret += team_r
            done = bool(terms.get("__all__")) or bool(truncs.get("__all__"))
            ep["actions"][t] = acts
            ep["reward"][t] = team_r
            ep["done"][t] = float(bool(terms.get("__all__")))
            ep["mask"][t] = 1.0
            self._env_steps += 1
            obs = nobs
            if done:
                break
        final = np.stack(
            [np.asarray(obs.get(a, ep["obs"][t][i]), np.float32).reshape(-1)
             for i, a in enumerate(self.agents)]
        )
        ep["obs"][t + 1] = final
        try:
            ep["state"][t + 1] = np.asarray(self.env.get_state(), np.float32)
        except Exception:
            pass
        self._recent_returns.append(ret)
        self._recent_returns = self._recent_returns[-100:]
        return ep

    def _collect(self, n_episodes: int):
        cfg = self.algo_config
        for _ in range(n_episodes):
            ep = self._collect_episode()
            if len(self._episodes) < cfg.buffer_size:
                self._episodes.append(ep)
            else:
                self._episodes[self._buf_pos] = ep
                self._buf_pos = (self._buf_pos + 1) % cfg.buffer_size

    # -------------------------------------------------------------- update

    def _build_update(self):
        cfg = self.algo_config
        optimizer = self.optimizer
        gamma = cfg.gamma
        n_agents, rnn_hidden = self.n_agents, cfg.rnn_hidden
        id_eye = jnp.asarray(self._id_eye)

        def td_loss(online, target, mb):
            B, Tp1 = mb["obs"].shape[0], mb["obs"].shape[1]
            # [T+1, B, N, obs+N] — scan over leading time axis
            ids = jnp.broadcast_to(id_eye, (Tp1, B, n_agents, n_agents))
            obs_id = jnp.concatenate(
                [jnp.moveaxis(mb["obs"], 1, 0), ids], axis=-1
            )
            h0 = jnp.zeros((B, n_agents, rnn_hidden), jnp.float32)
            q_on = agent_q_unroll(online, obs_id, h0)   # [T+1, B, N, A]
            q_tg = agent_q_unroll(target, obs_id, h0)
            q_on = jnp.moveaxis(q_on, 0, 1)  # [B, T+1, N, A]
            q_tg = jnp.moveaxis(q_tg, 0, 1)
            chosen = jnp.take_along_axis(
                q_on[:, :-1], mb["actions"][..., None], axis=-1
            )[..., 0]                                    # [B, T, N]
            # double-Q: online argmax at t+1, target evaluates
            a_star = jnp.argmax(q_on[:, 1:], axis=-1)
            q_next = jnp.take_along_axis(
                q_tg[:, 1:], a_star[..., None], axis=-1
            )[..., 0]                                    # [B, T, N]
            qtot = mix(
                {"mixer": online["mixer"]},
                chosen.reshape(-1, n_agents),
                mb["state"][:, :-1].reshape(chosen.shape[0] * chosen.shape[1], -1),
            ).reshape(chosen.shape[:2])                  # [B, T]
            qtot_next = mix(
                {"mixer": target["mixer"]},
                q_next.reshape(-1, n_agents),
                mb["state"][:, 1:].reshape(q_next.shape[0] * q_next.shape[1], -1),
            ).reshape(q_next.shape[:2])
            y = mb["reward"] + gamma * (1.0 - mb["done"]) * (
                jax.lax.stop_gradient(qtot_next)
            )
            td = (qtot - y) * mb["mask"]
            loss = jnp.sum(td**2) / jnp.maximum(jnp.sum(mb["mask"]), 1.0)
            return loss, {"loss": loss, "qtot_mean": jnp.mean(qtot)}

        def update(state: TrainState, mb):
            (_, metrics), grads = jax.value_and_grad(
                lambda p: td_loss(p, state.params["target"], mb), has_aux=True
            )(state.params["online"])
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params["online"]
            )
            online = optax.apply_updates(state.params["online"], updates)
            return (
                TrainState(
                    {"online": online, "target": state.params["target"]},
                    opt_state,
                    state.rng,
                ),
                metrics,
            )

        return jax.jit(update, donate_argnums=(0,))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        self._collect(cfg.train_batch_size)
        metrics: Dict[str, Any] = {"episodes_collected": len(self._episodes)}
        if len(self._episodes) >= cfg.learning_starts:
            if self._update_fn is None:
                self._update_fn = self._build_update()
            rng = np.random.default_rng(self._grad_steps)
            for _ in range(cfg.num_sgd_iter):
                idx = rng.integers(0, len(self._episodes), cfg.minibatch_size)
                mb = {
                    k: jnp.asarray(np.stack([self._episodes[i][k] for i in idx]))
                    for k in self._episodes[0]
                }
                self.state, m = self._update_fn(self.state, mb)
                self._grad_steps += 1
                if self._grad_steps % cfg.target_update_freq == 0:
                    p = self.state.params
                    self.state = self.state._replace(
                        params={
                            "online": p["online"],
                            "target": jax.tree.map(jnp.copy, p["online"]),
                        }
                    )
            metrics.update({k: float(v) for k, v in m.items()})
        if self._recent_returns:
            metrics["episode_reward_mean"] = float(np.mean(self._recent_returns))
        metrics["timesteps_total"] = self._env_steps
        return metrics

    def greedy_actions(self, obs_all: np.ndarray) -> np.ndarray:
        raise NotImplementedError(
            "RecurrentQMIX agents are stateful: a single-step greedy action "
            "without hidden state is meaningless. Use greedy_episode() to "
            "evaluate, or drive agent_step() with your own hidden state."
        )

    def greedy_episode(self) -> float:
        """Play one greedy (eps=0) episode; returns the team return."""
        cfg = self.algo_config
        obs, _ = self.env.reset()
        h = jnp.zeros((self.n_agents, cfg.rnn_hidden), jnp.float32)
        ret = 0.0
        for _ in range(cfg.episode_limit):
            obs_all = np.stack([obs[a] for a in self.agents]).reshape(
                self.n_agents, self.obs_dim
            )
            inp = np.concatenate([obs_all, self._id_eye], axis=-1)
            h, q = self._step_fn(self.state.params["online"], h, jnp.asarray(inp))
            acts = np.asarray(jax.device_get(q)).argmax(axis=-1)
            obs, rews, terms, truncs, _ = self.env.step(
                {a: int(acts[i]) for i, a in enumerate(self.agents)}
            )
            ret += float(sum(rews.values()))
            if bool(terms.get("__all__")) or bool(truncs.get("__all__")):
                break
        return ret
