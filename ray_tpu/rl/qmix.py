"""QMIX: cooperative multi-agent Q-learning with a monotonic mixing net.

Reference parity: rllib/algorithms/qmix/qmix.py:236 (QMIX algorithm —
per-agent Q networks + QMixer hypernetwork, target nets, team-reward TD)
and qmix_policy.py. TPU-first redesign:
  - ONE feedforward Q network shared by all agents (agent-id one-hot
    appended to the observation — the standard parameter-sharing QMIX
    formulation), so the per-agent forward is a single batched matmul
    over [B * n_agents, obs+n] rather than a per-agent module dict.
  - the K gradient steps of a training iteration run as one jitted
    lax.scan over presampled minibatches (same shape as dqn.py), target
    params carried in the same pytree.
  - the mixer's monotonicity (dQtot/dQ_i >= 0) comes from abs() on the
    hypernetwork-produced mixing weights, exactly the reference
    formulation (qmix.py QMixer.forward).
Transition-level replay over feedforward agents is the non-recurrent QMIX
variant (the reference's recurrent episode replay exists for POMDP envs;
R2D2-style recurrence is tracked separately).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm
from .config import AlgorithmConfig
from .learner import TrainState
from .multi_agent import MultiAgentEnv

OBS_ALL = "obs_all"          # [B, N, obs]
STATE = "state"              # [B, state_dim]
ACTIONS_ALL = "actions_all"  # [B, N]
TEAM_REWARD = "team_reward"  # [B]
NEXT_OBS_ALL = "next_obs_all"
NEXT_STATE = "next_state"
DONE = "done"                # [B]


def _dense(rng, fan_in, fan_out, scale=1.0):
    w = jax.random.normal(rng, (fan_in, fan_out), jnp.float32)
    return {"w": w * scale / np.sqrt(fan_in), "b": jnp.zeros((fan_out,), jnp.float32)}


def init_qmix_params(
    rng, obs_dim: int, n_agents: int, n_actions: int, state_dim: int,
    hidden=(64, 64), mixing_embed: int = 32,
):
    """Agent Q net (shared, id-onehot input) + mixer hypernetworks."""
    ks = jax.random.split(rng, 8)
    in_dim = obs_dim + n_agents
    agent = {
        "l1": _dense(ks[0], in_dim, hidden[0]),
        "l2": _dense(ks[1], hidden[0], hidden[1]),
        "out": _dense(ks[2], hidden[1], n_actions, scale=0.01),
    }
    mixer = {
        # state-conditioned weights: abs() at use enforces monotonicity
        "hyper_w1": _dense(ks[3], state_dim, n_agents * mixing_embed),
        "hyper_b1": _dense(ks[4], state_dim, mixing_embed),
        "hyper_w2": _dense(ks[5], state_dim, mixing_embed),
        # state value head (the mixer's final bias, a 2-layer hypernet in
        # the reference — one layer suffices at this scale)
        "hyper_v": _dense(ks[6], state_dim, 1),
    }
    return {"agent": agent, "mixer": mixer}


def agent_q(params, obs_id: jnp.ndarray) -> jnp.ndarray:
    """[..., obs+n_agents] -> [..., n_actions]"""
    a = params["agent"]
    h = jax.nn.relu(obs_id @ a["l1"]["w"] + a["l1"]["b"])
    h = jax.nn.relu(h @ a["l2"]["w"] + a["l2"]["b"])
    return h @ a["out"]["w"] + a["out"]["b"]


def mix(params, agent_qs: jnp.ndarray, state: jnp.ndarray) -> jnp.ndarray:
    """Monotonic mixing: [B, N] per-agent chosen Qs + [B, S] state -> [B]
    (reference: qmix.py QMixer.forward)."""
    m = params["mixer"]
    B, N = agent_qs.shape
    embed = m["hyper_b1"]["b"].shape[0]
    w1 = jnp.abs(state @ m["hyper_w1"]["w"] + m["hyper_w1"]["b"]).reshape(B, N, embed)
    b1 = (state @ m["hyper_b1"]["w"] + m["hyper_b1"]["b"])[:, None, :]
    hidden = jax.nn.elu(agent_qs[:, None, :] @ w1 + b1)  # [B, 1, embed]
    w2 = jnp.abs(state @ m["hyper_w2"]["w"] + m["hyper_w2"]["b"])[:, :, None]
    v = state @ m["hyper_v"]["w"] + m["hyper_v"]["b"]
    return (hidden @ w2)[:, 0, 0] + v[:, 0]


class QMIXConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=QMIX)
        self.mixing_embed: int = 32
        self.buffer_size: int = 20_000
        self.learning_starts: int = 500
        self.target_update_freq: int = 200  # gradient steps between syncs
        self.num_sgd_iter: int = 16
        self.epsilon_start: float = 1.0
        self.epsilon_end: float = 0.05
        self.epsilon_decay_steps: int = 4_000
        self.lr = 5e-4
        self.minibatch_size = 64
        self.train_batch_size = 256  # env steps collected per iteration


class QMIX(Algorithm):
    """Cooperative MARL over a MultiAgentEnv with a shared team reward.
    The env must implement get_state() (global mixer input); agents listed
    in possible_agents act every step."""

    _config_class = QMIXConfig

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = self.algo_config
        if not callable(cfg.env):
            raise ValueError("QMIX needs a callable MultiAgentEnv maker")
        self.env: MultiAgentEnv = cfg.env()
        self.agents = list(self.env.possible_agents)
        self.n_agents = len(self.agents)
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.n_actions = int(self.env.action_space.n)
        self._obs, _ = self.env.reset(seed=cfg.seed)
        self.state_dim = int(np.asarray(self.env.get_state()).shape[0])

        hidden = tuple(cfg.model.get("hidden", (64, 64)))
        params = init_qmix_params(
            jax.random.PRNGKey(cfg.seed), self.obs_dim, self.n_agents,
            self.n_actions, self.state_dim, hidden, cfg.mixing_embed,
        )
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(10.0), optax.adam(cfg.lr)
        )
        self.state = TrainState(
            params={"online": params, "target": jax.tree.map(jnp.copy, params)},
            opt_state=self.optimizer.init(params),
            rng=jax.random.PRNGKey(cfg.seed + 1),
        )
        self._q_fn = jax.jit(agent_q)
        self._update_fn = None
        self._grad_steps = 0
        self._eps_rng = np.random.default_rng(cfg.seed + 2)
        self._buffer: List[Tuple] = []
        self._buf_pos = 0
        self._env_steps = 0
        self._ep_ret = 0.0
        self._recent_returns: List[float] = []
        # agent-id one-hots appended to observations (shared Q net)
        self._id_eye = np.eye(self.n_agents, dtype=np.float32)

    # -- rollouts (epsilon-greedy, inline: QMIX envs are cheap and the
    #    replay path dominates; reference runs local replay collection) --

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def _act(self, obs_all: np.ndarray, eps: float) -> np.ndarray:
        inp = np.concatenate([obs_all, self._id_eye], axis=-1)
        qs = np.asarray(jax.device_get(self._q_fn(self.state.params["online"], inp)))
        acts = qs.argmax(axis=-1)
        explore = self._eps_rng.random(self.n_agents) < eps
        acts[explore] = self._eps_rng.integers(0, self.n_actions, explore.sum())
        return acts.astype(np.int64)

    def _collect(self, n_steps: int):
        cfg = self.algo_config
        for _ in range(n_steps):
            obs_all = np.stack([self._obs[a] for a in self.agents])
            state = np.asarray(self.env.get_state(), np.float32)
            acts = self._act(obs_all, self._epsilon())
            nobs, rews, terms, truncs, _ = self.env.step(
                {a: int(acts[i]) for i, a in enumerate(self.agents)}
            )
            done = bool(terms.get("__all__")) or bool(truncs.get("__all__"))
            team_r = float(sum(rews.values()))
            self._ep_ret += team_r
            if done:
                self._recent_returns.append(self._ep_ret)
                self._recent_returns = self._recent_returns[-100:]
                self._ep_ret = 0.0
                self._obs, _ = self.env.reset()
                next_obs_all = np.stack([self._obs[a] for a in self.agents])
            else:
                self._obs = nobs
                next_obs_all = np.stack([self._obs[a] for a in self.agents])
            next_state = np.asarray(self.env.get_state(), np.float32)
            tr = (obs_all, state, acts, team_r, next_obs_all, next_state, float(done))
            if len(self._buffer) < cfg.buffer_size:
                self._buffer.append(tr)
            else:
                self._buffer[self._buf_pos] = tr
                self._buf_pos = (self._buf_pos + 1) % cfg.buffer_size
            self._env_steps += 1

    # -- update (one jitted scan over K presampled minibatches) --

    def _build_update(self):
        cfg = self.algo_config
        optimizer = self.optimizer
        n_agents, n_actions = self.n_agents, self.n_actions
        gamma = cfg.gamma
        id_eye = jnp.asarray(self._id_eye)

        def td_loss(online, target, mb):
            B = mb[TEAM_REWARD].shape[0]
            ids = jnp.broadcast_to(id_eye, (B, n_agents, n_agents))
            inp = jnp.concatenate([mb[OBS_ALL], ids], axis=-1)
            qs = agent_q(online, inp)  # [B, N, A]
            chosen = jnp.take_along_axis(
                qs, mb[ACTIONS_ALL][..., None], axis=-1
            )[..., 0]  # [B, N]
            q_tot = mix(online, chosen, mb[STATE])
            ninp = jnp.concatenate([mb[NEXT_OBS_ALL], ids], axis=-1)
            # double-Q argmax from ONLINE agents, evaluated by TARGET
            next_online = agent_q(online, ninp)
            next_acts = next_online.argmax(axis=-1)
            next_target = jnp.take_along_axis(
                agent_q(target, ninp), next_acts[..., None], axis=-1
            )[..., 0]
            next_tot = mix(target, next_target, mb[NEXT_STATE])
            y = mb[TEAM_REWARD] + gamma * (1.0 - mb[DONE]) * next_tot
            td = q_tot - jax.lax.stop_gradient(y)
            return jnp.mean(td**2), jnp.mean(jnp.abs(td))

        def update(state: TrainState, minibatches):
            target = state.params["target"]  # frozen across the K steps

            def step(carry, mb):
                params, opt_state = carry
                (loss, abs_td), grads = jax.value_and_grad(
                    lambda p: td_loss(p, target, mb), has_aux=True
                )(params)
                updates, opt_state = optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), {"loss": loss, "abs_td": abs_td}

            (online, opt_state), metrics = jax.lax.scan(
                step, (state.params["online"], state.opt_state), minibatches
            )
            new = TrainState(
                params={"online": online, "target": state.params["target"]},
                opt_state=opt_state,
                rng=state.rng,
            )
            return new, jax.tree.map(jnp.mean, metrics)

        return jax.jit(update, donate_argnums=(0,))

    def _sample_minibatches(self, k: int, size: int):
        idx = self._eps_rng.integers(0, len(self._buffer), size=(k, size))
        cols = {
            OBS_ALL: np.empty((k, size, self.n_agents, self.obs_dim), np.float32),
            STATE: np.empty((k, size, self.state_dim), np.float32),
            ACTIONS_ALL: np.empty((k, size, self.n_agents), np.int64),
            TEAM_REWARD: np.empty((k, size), np.float32),
            NEXT_OBS_ALL: np.empty((k, size, self.n_agents, self.obs_dim), np.float32),
            NEXT_STATE: np.empty((k, size, self.state_dim), np.float32),
            DONE: np.empty((k, size), np.float32),
        }
        for ki in range(k):
            for si, b in enumerate(idx[ki]):
                o, s, a, r, no, ns, d = self._buffer[b]
                cols[OBS_ALL][ki, si] = o
                cols[STATE][ki, si] = s
                cols[ACTIONS_ALL][ki, si] = a
                cols[TEAM_REWARD][ki, si] = r
                cols[NEXT_OBS_ALL][ki, si] = no
                cols[NEXT_STATE][ki, si] = ns
                cols[DONE][ki, si] = d
        return {k_: jnp.asarray(v) for k_, v in cols.items()}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        self._collect(cfg.train_batch_size)
        self._timesteps_total = self._env_steps
        metrics: Dict[str, Any] = {
            "epsilon": self._epsilon(),
            "num_env_steps_sampled_this_iter": cfg.train_batch_size,
        }
        if len(self._buffer) >= cfg.learning_starts:
            if self._update_fn is None:
                self._update_fn = self._build_update()
            mbs = self._sample_minibatches(cfg.num_sgd_iter, cfg.minibatch_size)
            self.state, m = self._update_fn(self.state, mbs)
            metrics.update({k: float(v) for k, v in m.items()})
            self._grad_steps += cfg.num_sgd_iter
            if self._grad_steps % cfg.target_update_freq < cfg.num_sgd_iter:
                self.state = self.state._replace(
                    params={
                        "online": self.state.params["online"],
                        "target": jax.tree.map(
                            jnp.copy, self.state.params["online"]
                        ),
                    }
                )
        metrics["episode_reward_mean"] = (
            float(np.mean(self._recent_returns[-20:])) if self._recent_returns else 0.0
        )
        return metrics

    def greedy_actions(self, obs_all: np.ndarray) -> np.ndarray:
        return self._act(obs_all, eps=0.0)

    # -- Trainable contract (the base Algorithm versions dereference
    #    learner_group/workers, which QMIX's inline design has neither of) --

    def save_checkpoint(self) -> Any:
        return {
            "params": jax.device_get(self.state.params),
            "opt_state": jax.device_get(self.state.opt_state),
            "env_steps": self._env_steps,
            "grad_steps": self._grad_steps,
            # replay buffer deliberately not persisted (reference default)
        }

    def load_checkpoint(self, checkpoint: Any) -> None:
        self.state = TrainState(
            params=jax.device_put(checkpoint["params"]),
            opt_state=jax.device_put(checkpoint["opt_state"]),
            rng=self.state.rng,
        )
        self._env_steps = checkpoint.get("env_steps", 0)
        self._grad_steps = checkpoint.get("grad_steps", 0)
        self._timesteps_total = self._env_steps

    def cleanup(self) -> None:
        self.env.close()

    stop = cleanup
