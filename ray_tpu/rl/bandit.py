"""Contextual bandits: LinUCB and linear Thompson sampling.

Reference parity: rllib/algorithms/bandit/ (BanditLinUCB / BanditLinTS over
the online linear models in bandit_torch_model.py). A bandit env is a
one-step MDP: reset() yields a context, step(arm) yields a reward and the
next context. Both algorithms keep per-arm ridge-regression sufficient
statistics (A = I + sum x x^T, b = sum r x) — pure numpy, updated online;
no replay, no networks.

TPU note: bandit state is KB-sized linear algebra — deliberately host-side
(the reference's is torch-on-CPU too); it exists for inventory parity and
as the exploration-theory baseline next to the deep algorithms."""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .config import AlgorithmConfig
from .rollout_worker import _make_env
from ..tune.trainable import Trainable


class _LinearArms:
    """Per-arm ridge statistics with incrementally maintained A^-1
    (Sherman–Morrison), so act() is O(d^2) per arm, not O(d^3)."""

    def __init__(self, n_arms: int, dim: int, lam: float = 1.0):
        self.n_arms, self.dim = n_arms, dim
        self.A_inv = np.stack([np.eye(dim) / lam for _ in range(n_arms)])
        self.b = np.zeros((n_arms, dim))
        self.versions = np.zeros(n_arms, np.int64)  # cache keys (LinTS chol)

    def theta(self) -> np.ndarray:
        return np.einsum("kij,kj->ki", self.A_inv, self.b)

    def update(self, arm: int, x: np.ndarray, r: float) -> None:
        Ai = self.A_inv[arm]
        Ax = Ai @ x
        self.A_inv[arm] = Ai - np.outer(Ax, Ax) / (1.0 + x @ Ax)
        self.b[arm] += r * x
        self.versions[arm] += 1


class BanditConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=BanditLinUCB)
        self.alpha: float = 1.0        # UCB exploration width
        self.lambda_reg: float = 1.0
        self.train_batch_size = 100    # env interactions per train()

    def exploration(self, *, alpha: Optional[float] = None) -> "BanditConfig":
        if alpha is not None:
            self.alpha = alpha
        return self


class BanditLinUCB(Trainable):
    """LinUCB (Li et al. 2010): pick argmax_k theta_k.x + alpha*sqrt(x'A^-1x)."""

    _config_class = BanditConfig

    def __init__(self, config=None, **kwargs):
        config = self._config_class.coerce(config)
        self.algo_config = config
        cfg = config
        self.env = _make_env(cfg.env)
        self.dim = int(np.prod(self.env.observation_space.shape))
        self.n_arms = int(self.env.action_space.n)
        self.arms = _LinearArms(self.n_arms, self.dim, cfg.lambda_reg)
        self._obs, _ = self.env.reset(seed=cfg.seed)
        self._rng = np.random.default_rng(cfg.seed)
        self._timesteps_total = 0
        self.iteration = 0
        self._cum_reward = 0.0

    # -- per-algorithm scoring --

    def _scores(self, x: np.ndarray) -> np.ndarray:
        exploit = self.arms.theta() @ x
        widths = np.sqrt(np.einsum("i,kij,j->k", x, self.arms.A_inv, x))
        return exploit + self.algo_config.alpha * widths

    def compute_action(self, obs) -> int:
        x = np.asarray(obs, np.float64).reshape(-1)
        return int(np.argmax(self._scores(x)))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        rewards = []
        for _ in range(cfg.train_batch_size):
            x = np.asarray(self._obs, np.float64).reshape(-1)
            arm = self.compute_action(x)
            obs2, r, term, trunc, _ = self.env.step(arm)
            self.arms.update(arm, x, float(r))
            rewards.append(float(r))
            self._timesteps_total += 1
            self._obs = self.env.reset()[0] if (term or trunc) else obs2
        self._cum_reward += float(np.sum(rewards))
        return {
            "episode_reward_mean": float(np.mean(rewards)),
            "cumulative_reward": self._cum_reward,
            "timesteps_total": self._timesteps_total,
        }

    # tune's TrialRunner drives class trainables via step(); standalone
    # callers use the base Trainable.train() wrapper
    step = training_step

    def save_checkpoint(self) -> Any:
        return {"A_inv": self.arms.A_inv.copy(), "b": self.arms.b.copy(),
                "versions": self.arms.versions.copy(),
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, checkpoint: Any) -> None:
        # copies: update() mutates in place, and one checkpoint object may
        # restore several algos (or be reused) — no aliasing
        self.arms.A_inv = np.array(checkpoint["A_inv"])
        self.arms.b = np.array(checkpoint["b"])
        if "versions" in checkpoint:
            self.arms.versions = np.asarray(checkpoint["versions"]).copy()
        else:
            self.arms.versions += 1  # force divergence from any cached keys
        self._chol_cache = {}  # stale factors must not survive a restore
        self._timesteps_total = checkpoint.get("timesteps_total", 0)

    def stop(self) -> None:
        try:
            self.env.close()
        except Exception:
            pass

    cleanup = stop


class BanditLinTSConfig(BanditConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = BanditLinTS  # resolved at call time (defined below)
        self.alpha = 0.3


class BanditLinTS(BanditLinUCB):
    """Linear Thompson sampling: score each arm with a posterior draw
    theta_k ~ N(theta_hat_k, alpha^2 A_k^-1) (reference: BanditLinTS)."""

    _config_class = BanditLinTSConfig

    def _scores(self, x: np.ndarray) -> np.ndarray:
        cfg = self.algo_config
        theta = self.arms.theta()
        out = np.empty(self.n_arms)
        for k in range(self.n_arms):
            # symmetrize (Sherman–Morrison drift) and sample via a Cholesky
            # factor with a jitter fallback: O(d^3) only when the cached
            # factor is stale, never an SVD per pull
            draw = theta[k] + cfg.alpha * self._chol(k) @ self._rng.standard_normal(
                self.dim
            )
            out[k] = draw @ x
        return out

    def _chol(self, arm: int) -> np.ndarray:
        if not hasattr(self, "_chol_cache"):
            self._chol_cache = {}
        version = int(self.arms.versions[arm])
        cached = self._chol_cache.get(arm)
        if cached is not None and cached[0] == version:
            return cached[1]
        cov = self.arms.A_inv[arm]
        cov = 0.5 * (cov + cov.T)
        for jitter in (0.0, 1e-10, 1e-8, 1e-6):
            try:
                L = np.linalg.cholesky(cov + jitter * np.eye(self.dim))
                break
            except np.linalg.LinAlgError:
                continue
        else:
            L = np.eye(self.dim) * np.sqrt(max(np.trace(cov) / self.dim, 1e-12))
        self._chol_cache[arm] = (version, L)
        return L


