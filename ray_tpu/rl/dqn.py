"""DQN: epsilon-greedy rollouts -> replay buffer -> jitted double-Q updates.

Reference parity: rllib/algorithms/dqn/dqn.py (training_step: sample,
store_to_replay_buffer, sample_from_replay_buffer, train, target-net sync)
and dqn_torch_policy.py loss. TPU-first: the K gradient steps of one
training iteration run as ONE jitted lax.scan over presampled minibatches,
and the target network lives inside the same params pytree (a scan carry),
so iteration cost is a single dispatch.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm
from .config import AlgorithmConfig
from .learner import Learner, LearnerGroup, TrainState
from .models import init_q_params, q_apply
from .replay_buffer import ReplayBuffer
from .rollout_worker import EnvLoopWorker, _make_env
from .sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS, SampleBatch


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DQN)
        self.buffer_size: int = 50_000
        self.learning_starts: int = 1_000
        self.target_update_freq: int = 500  # gradient steps between syncs
        self.num_sgd_iter: int = 32  # gradient steps per training iteration
        self.double_q: bool = True
        self.epsilon_start: float = 1.0
        self.epsilon_end: float = 0.05
        self.epsilon_decay_steps: int = 10_000
        self.lr = 1e-3
        self.minibatch_size = 64
        self.train_batch_size = 512  # env steps collected per iteration


class _EpsilonGreedyWorker(EnvLoopWorker):
    """Sampling actor: steps envs with eps-greedy Q policy, returns raw
    transitions (reference: rollout side of dqn.py + EpsilonGreedy
    exploration)."""

    def __init__(
        self,
        env_spec,
        num_envs: int = 1,
        rollout_fragment_length: int = 64,
        policy_hidden=(64, 64),
        seed: int = 0,
    ):
        super().__init__(env_spec, num_envs, seed)
        self.T = rollout_fragment_length
        self.num_actions = int(self.envs[0].action_space.n)
        self.params = init_q_params(
            jax.random.PRNGKey(seed), self.obs_dim, self.num_actions, policy_hidden
        )
        self._apply = jax.jit(q_apply)
        self._rng = np.random.default_rng(seed)
        self.epsilon = 1.0

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        self.params = weights

    def set_epsilon(self, eps: float):
        self.epsilon = float(eps)

    def sample(self, epsilon: Optional[float] = None) -> SampleBatch:
        if epsilon is not None:
            self.epsilon = float(epsilon)
        E = self.num_envs
        cols = {
            OBS: np.empty((self.T, E, self.obs_dim), np.float32),
            ACTIONS: np.empty((self.T, E), np.int64),
            REWARDS: np.empty((self.T, E), np.float32),
            NEXT_OBS: np.empty((self.T, E, self.obs_dim), np.float32),
            DONES: np.empty((self.T, E), np.float32),
        }
        for t in range(self.T):
            q = np.asarray(jax.device_get(self._apply(self.params, self._obs)))
            greedy = q.argmax(axis=-1)
            explore = self._rng.random(E) < self.epsilon
            actions = np.where(explore, self._rng.integers(0, self.num_actions, E), greedy)
            cols[OBS][t] = self._obs
            cols[ACTIONS][t] = actions
            for e in range(E):
                rew, term, _trunc, final = self._step_and_track(e, int(actions[e]))
                cols[REWARDS][t, e] = rew
                cols[NEXT_OBS][t, e] = final
                # time-limit truncation is NOT a terminal for bootstrapping
                cols[DONES][t, e] = float(term)
        return SampleBatch({k: v.reshape((self.T * E,) + v.shape[2:]) for k, v in cols.items()})


def dqn_td_huber(online, target, mb, gamma: float, double_q: bool):
    """The (double-)DQN TD computation shared by DQN and Ape-X: returns
    (chosen q, td error, elementwise Huber). Huber is the reference's
    default loss; callers reduce it (mean, or IS-weighted mean)."""
    q = q_apply(online, mb[OBS])
    q_sel = jnp.take_along_axis(q, mb[ACTIONS][:, None], axis=-1)[:, 0]
    q_next_t = q_apply(target, mb[NEXT_OBS])
    if double_q:
        a_star = jnp.argmax(q_apply(online, mb[NEXT_OBS]), axis=-1)
        q_next = jnp.take_along_axis(q_next_t, a_star[:, None], axis=-1)[:, 0]
    else:
        q_next = jnp.max(q_next_t, axis=-1)
    y = mb[REWARDS] + gamma * (1.0 - mb[DONES]) * jax.lax.stop_gradient(q_next)
    td = q_sel - y
    huber = jnp.where(jnp.abs(td) <= 1.0, 0.5 * td**2, jnp.abs(td) - 0.5)
    return q_sel, td, huber


class DQNLearner(Learner):
    def __init__(
        self,
        obs_dim: int,
        num_actions: int,
        hidden=(64, 64),
        lr: float = 1e-3,
        gamma: float = 0.99,
        double_q: bool = True,
        target_update_freq: int = 500,
        num_sgd_iter: int = 32,
        minibatch_size: int = 64,
        seed: int = 0,
    ):
        super().__init__(config=None)
        self.gamma = gamma
        self.double_q = double_q
        self.target_update_freq = target_update_freq
        self.num_sgd_iter = num_sgd_iter
        self.minibatch_size = minibatch_size
        self.optimizer = optax.adam(lr)
        params = init_q_params(jax.random.PRNGKey(seed), obs_dim, num_actions, hidden)
        self.state = TrainState(
            params={"online": params, "target": jax.tree_util.tree_map(jnp.copy, params)},
            opt_state=self.optimizer.init(params),
            rng=jax.random.PRNGKey(seed + 1),
        )
        self._grad_steps = 0
        self._update_fn = None

    def loss(self, online, target, mb):
        q_sel, td, huber = dqn_td_huber(
            online, target, mb, self.gamma, self.double_q
        )
        loss = jnp.mean(huber)
        return loss, {"loss": loss, "mean_q": jnp.mean(q_sel), "mean_td": jnp.mean(jnp.abs(td))}

    def _build_update(self):
        optimizer = self.optimizer
        loss_fn = self.loss

        def step(carry, mb):
            online, target, opt_state = carry
            (_, metrics), grads = jax.value_and_grad(
                lambda p: loss_fn(p, target, mb), has_aux=True
            )(online)
            updates, opt_state = optimizer.update(grads, opt_state, online)
            online = optax.apply_updates(online, updates)
            return (online, target, opt_state), metrics

        def update(state: TrainState, minibatches):
            params = state.params
            (online, target, opt_state), metrics = jax.lax.scan(
                step, (params["online"], params["target"], state.opt_state), minibatches
            )
            metrics = jax.tree_util.tree_map(lambda m: m.mean(), metrics)
            new_state = TrainState(
                {"online": online, "target": target}, opt_state, state.rng
            )
            return new_state, metrics

        return jax.jit(update, donate_argnums=(0,))

    def update(self, buffer: Union[ReplayBuffer, SampleBatch]) -> Dict[str, float]:
        """Run num_sgd_iter gradient steps on minibatches presampled from
        the buffer — one compiled dispatch for the whole scan."""
        if isinstance(buffer, SampleBatch):  # remote-learner path gets a batch
            mbs = {k: np.asarray(v) for k, v in buffer.items()}
            n_iter = mbs[OBS].shape[0] // self.minibatch_size
            minibatches = {
                k: jnp.asarray(
                    v[: n_iter * self.minibatch_size].reshape(
                        (n_iter, self.minibatch_size) + v.shape[1:]
                    )
                )
                for k, v in mbs.items()
            }
        else:
            samples = [buffer.sample(self.minibatch_size) for _ in range(self.num_sgd_iter)]
            minibatches = {
                k: jnp.asarray(np.stack([s[k] for s in samples]))
                for k in samples[0].keys()
            }
            n_iter = self.num_sgd_iter
        if self._update_fn is None:
            self._update_fn = self._build_update()
        self.state, metrics = self._update_fn(self.state, minibatches)
        self._grad_steps += n_iter
        if self._grad_steps % self.target_update_freq < n_iter:
            p = self.state.params
            self.state = self.state._replace(
                params={
                    "online": p["online"],
                    "target": jax.tree_util.tree_map(jnp.copy, p["online"]),
                }
            )
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.state.params["online"])

    def set_weights(self, weights):
        p = dict(self.state.params)
        p["online"] = jax.device_put(weights)
        self.state = self.state._replace(params=p)


class DQN(Algorithm):
    _config_class = DQNConfig

    def _worker_cls(self):
        return _EpsilonGreedyWorker

    def _worker_kwargs(self):
        cfg = self.algo_config
        return dict(
            env_spec=cfg.env,
            num_envs=cfg.num_envs_per_worker,
            rollout_fragment_length=cfg.rollout_fragment_length,
            policy_hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )

    def _build_learner(self) -> LearnerGroup:
        cfg = self.algo_config
        env = _make_env(cfg.env)
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close()
        self.replay = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)

        def factory():
            return DQNLearner(
                obs_dim=obs_dim,
                num_actions=num_actions,
                hidden=tuple(cfg.model.get("hidden", (64, 64))),
                lr=cfg.lr,
                gamma=cfg.gamma,
                double_q=cfg.double_q,
                target_update_freq=cfg.target_update_freq,
                num_sgd_iter=cfg.num_sgd_iter,
                minibatch_size=cfg.minibatch_size,
                seed=cfg.seed,
            )

        return LearnerGroup(factory, remote=False)

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._timesteps_total / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        eps = self._epsilon()
        collected = 0
        while collected < cfg.train_batch_size:
            if self.workers._local is not None:
                batch = self.workers._local.sample(eps)
            else:
                import ray_tpu

                from .sample_batch import concat_samples

                batch = concat_samples(
                    ray_tpu.get(
                        [w.sample.remote(eps) for w in self.workers._remote_workers]
                    )
                )
            self.replay.add(batch)
            collected += len(batch)
            self._timesteps_total += len(batch)
        metrics: Dict[str, Any] = {"epsilon": eps, "replay_size": len(self.replay)}
        if len(self.replay) >= cfg.learning_starts:
            metrics.update(self.learner_group._learner.update(self.replay))
            self.workers.set_weights(self.learner_group.get_weights())
        metrics["num_env_steps_sampled_this_iter"] = collected
        return metrics
