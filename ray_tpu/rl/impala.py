"""IMPALA: asynchronous rollouts + V-trace off-policy correction.

Reference parity: rllib/algorithms/impala/impala.py (async sampling with
learner queues; workers act with stale weights, v-trace corrects the
off-policyness) with the v-trace math of rllib vtrace_torch/tf. TPU-first:
the correction + policy/value update is one jitted program (v-trace is a
reverse lax.scan over the time axis); asynchrony comes from ray_tpu.wait
over in-flight sample refs — the learner updates on whichever worker's
fragment lands first and only THAT worker gets fresh weights (per-worker
weight push, the reference's broadcasted-weights-on-next-request).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm
from .config import AlgorithmConfig
from .learner import Learner, LearnerGroup, TrainState
from .models import ac_apply, init_ac_params
from .rollout_worker import _make_env
from .sample_batch import ACTIONS, DONES, LOGP, OBS, REWARDS, SampleBatch


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=IMPALA)
        self.vtrace_rho_clip: float = 1.0
        self.vtrace_c_clip: float = 1.0
        self.vf_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.max_grad_norm: float = 40.0
        self.max_requests_in_flight: int = 2  # per worker
        self.lr = 5e-4
        self.rollout_fragment_length = 64


def vtrace(
    values, rewards, dones, bootstrap_value, rho, c, gamma
):
    """V-trace targets (Espeholt et al. 2018, eq. 1) as a reverse scan.

    All inputs time-major [T, E]; returns (vs [T, E], pg_adv [T, E]).
    """
    # V(x_{t+1}): shift values up; last row bootstraps
    values_tp1 = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    not_done = 1.0 - dones
    deltas = rho * (rewards + gamma * not_done * values_tp1 - values)

    def back(acc, inp):
        delta_t, c_t, nd_t = inp
        acc = delta_t + gamma * nd_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        back, jnp.zeros_like(bootstrap_value), (deltas, c, not_done), reverse=True
    )
    vs = values + vs_minus_v
    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = rho * (rewards + gamma * not_done * vs_tp1 - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaLearner(Learner):
    def __init__(
        self,
        obs_dim: int,
        num_actions: int,
        hidden=(64, 64),
        lr: float = 5e-4,
        gamma: float = 0.99,
        rho_clip: float = 1.0,
        c_clip: float = 1.0,
        vf_coeff: float = 0.5,
        entropy_coeff: float = 0.01,
        max_grad_norm: float = 40.0,
        seed: int = 0,
    ):
        super().__init__(config=None)
        self.gamma = gamma
        self.rho_clip = rho_clip
        self.c_clip = c_clip
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(max_grad_norm), optax.rmsprop(lr, decay=0.99)
        )
        params = init_ac_params(jax.random.PRNGKey(seed), obs_dim, num_actions, hidden)
        self.state = TrainState(
            params=params, opt_state=self.optimizer.init(params), rng=jax.random.PRNGKey(seed)
        )
        self._update_fn = None

    def loss(self, params, batch):
        T, E = batch[ACTIONS].shape
        obs = batch[OBS].reshape(T * E, -1)
        logits, values = ac_apply(params, obs)
        logits = logits.reshape(T, E, -1)
        values = values.reshape(T, E)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, batch[ACTIONS][..., None], axis=-1)[..., 0]
        log_rho = logp - batch[LOGP]  # target vs behavior
        rho = jnp.minimum(self.rho_clip, jnp.exp(log_rho))
        c = jnp.minimum(self.c_clip, jnp.exp(log_rho))
        vs, pg_adv = vtrace(
            jax.lax.stop_gradient(values),
            batch[REWARDS],
            batch[DONES],
            batch["bootstrap_value"],
            jax.lax.stop_gradient(rho),
            jax.lax.stop_gradient(c),
            self.gamma,
        )
        pg_loss = -jnp.mean(logp * pg_adv)
        vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = pg_loss + self.vf_coeff * vf_loss - self.entropy_coeff * entropy
        return total, {
            "total_loss": total,
            "policy_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_rho": jnp.mean(rho),
        }

    def _build_update(self):
        optimizer = self.optimizer
        loss_fn = self.loss

        def update(state: TrainState, batch):
            (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params, batch
            )
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(params, opt_state, state.rng), metrics

        return jax.jit(update, donate_argnums=(0,))

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        cols = {k: jnp.asarray(np.asarray(v)) for k, v in batch.items()}
        if self._update_fn is None:
            self._update_fn = self._build_update()
        self.state, metrics = self._update_fn(self.state, cols)
        return {k: float(v) for k, v in metrics.items()}


class IMPALA(Algorithm):
    _config_class = ImpalaConfig
    _learner_cls = ImpalaLearner  # APPO swaps in its clipped-surrogate learner

    def _extra_learner_kwargs(self) -> Dict[str, Any]:
        return {}

    def _build_learner(self) -> LearnerGroup:
        cfg = self.algo_config
        env = _make_env(cfg.env)
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close()

        learner_cls = self._learner_cls
        extra = self._extra_learner_kwargs()

        def factory():
            return learner_cls(
                **extra,
                obs_dim=obs_dim,
                num_actions=num_actions,
                hidden=tuple(cfg.model.get("hidden", (64, 64))),
                lr=cfg.lr,
                gamma=cfg.gamma,
                rho_clip=cfg.vtrace_rho_clip,
                c_clip=cfg.vtrace_c_clip,
                vf_coeff=cfg.vf_coeff,
                entropy_coeff=cfg.entropy_coeff,
                max_grad_norm=cfg.max_grad_norm,
                seed=cfg.seed,
            )

        return LearnerGroup(factory, remote=False)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        learner = self.learner_group._learner
        target = cfg.train_batch_size
        consumed = 0
        metrics: Dict[str, Any] = {}

        if self.workers._local is not None:
            # synchronous local fallback
            while consumed < target:
                batch = self.workers._local.sample_time_major()
                n = int(np.prod(batch[ACTIONS].shape))
                consumed += n
                self._timesteps_total += n
                metrics = learner.update(batch)
                self.workers._local.set_weights(learner.get_weights())
            metrics["num_env_steps_sampled_this_iter"] = consumed
            return metrics

        import ray_tpu

        workers = self.workers._remote_workers
        # the pipeline persists across training_steps: prime once
        in_flight: Dict[Any, Any] = getattr(self, "_inflight", {})
        if not in_flight:
            for w in workers:
                for _ in range(cfg.max_requests_in_flight):
                    in_flight[w.sample_time_major.remote()] = w
        while consumed < target:
            done, _ = ray_tpu.wait(list(in_flight), num_returns=1)
            w = in_flight.pop(done[0])
            batch = ray_tpu.get(done[0])
            n = int(np.prod(batch[ACTIONS].shape))
            consumed += n
            self._timesteps_total += n
            metrics = learner.update(batch)
            # fresh weights only to the worker that just reported, then
            # immediately put it back to work (async pipeline)
            w.set_weights.remote(learner.get_weights())
            in_flight[w.sample_time_major.remote()] = w
        # drain: leave in-flight refs; next step consumes them
        self._inflight = in_flight
        metrics["num_env_steps_sampled_this_iter"] = consumed
        return metrics
