"""Model catalog: config-driven policy/value network construction.

Reference parity: rllib/models/catalog.py:204 (ModelCatalog.get_model_v2 —
picks fcnet/vision/recurrent models from the observation space + model
config) and rllib/core/models/catalog.py:28 (new-stack Catalog building
encoder + heads). ray_tpu's catalog returns (init_fn, apply_fn) pairs of
pure JAX functions over a params pytree, so one definition runs jitted on
CPU rollout actors and pjit'ed on the learner mesh.

Selection mirrors the reference:
- rank-3 obs (H, W, C)  -> conv encoder (conv_filters or an auto scheme)
- flat obs              -> MLP encoder (fcnet_hiddens/fcnet_activation)
- use_lstm=True         -> LSTM core between encoder and heads; apply then
  threads a recurrent state: apply(params, obs, state) -> (out, state').
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_ACTIVATIONS = {
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "swish": jax.nn.swish,
    "silu": jax.nn.swish,
    "elu": jax.nn.elu,
}


@dataclass
class ModelConfig:
    """Subset of the reference's MODEL_DEFAULTS that shapes the network."""

    fcnet_hiddens: Sequence[int] = (64, 64)
    fcnet_activation: str = "tanh"
    # [(out_channels, kernel, stride), ...]; None = auto scheme by obs size
    conv_filters: Optional[Sequence[Tuple[int, int, int]]] = None
    conv_activation: str = "relu"
    use_lstm: bool = False
    lstm_cell_size: int = 128


def _act(name: str) -> Callable:
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"unknown activation {name!r} (supported: {sorted(_ACTIVATIONS)})"
        ) from None


from .models import _dense_init  # single source for the orthogonal {w, b} init


def _auto_conv_filters(hw: Tuple[int, int]):
    """Reference-style defaults: Atari-ish for >=64px, small otherwise."""
    if min(hw) >= 64:
        return [(16, 8, 4), (32, 4, 2), (64, 3, 2)]
    return [(16, 4, 2), (32, 3, 2)]


# --------------------------------------------------------------------------
# encoders
# --------------------------------------------------------------------------


def _mlp_encoder(cfg: ModelConfig, obs_dim: int):
    hidden = list(cfg.fcnet_hiddens)
    act = _act(cfg.fcnet_activation)

    def init(rng):
        layers = []
        dims = [obs_dim, *hidden]
        for i in range(len(dims) - 1):
            rng, sub = jax.random.split(rng)
            layers.append(_dense_init(sub, dims[i], dims[i + 1], np.sqrt(2)))
        return {"layers": layers}

    def apply(params, obs):
        x = obs.reshape(obs.shape[0], -1)
        for layer in params["layers"]:
            x = act(x @ layer["w"] + layer["b"])
        return x

    return init, apply, (hidden[-1] if hidden else obs_dim)


def _conv_encoder(cfg: ModelConfig, obs_shape: Tuple[int, int, int]):
    h, w, c = obs_shape
    filters = list(cfg.conv_filters or _auto_conv_filters((h, w)))
    act = _act(cfg.conv_activation)

    def out_hw(size, kernel, stride):  # SAME padding
        return -(-size // stride)

    shapes = []
    ch, hh, ww = c, h, w
    for out_ch, k, s in filters:
        shapes.append((ch, out_ch, k, s))
        hh, ww, ch = out_hw(hh, k, s), out_hw(ww, k, s), out_ch
    flat_dim = hh * ww * ch

    def init(rng):
        convs = []
        for in_ch, out_ch, k, s in shapes:
            rng, sub = jax.random.split(rng)
            wgt = jax.nn.initializers.orthogonal(np.sqrt(2))(
                sub, (k, k, in_ch, out_ch), jnp.float32
            )
            convs.append({"w": wgt, "b": jnp.zeros((out_ch,), jnp.float32)})
        return {"convs": convs}

    def apply(params, obs):
        x = obs.astype(jnp.float32)
        for (in_ch, out_ch, k, s), layer in zip(shapes, params["convs"]):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(s, s), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ) + layer["b"]
            x = act(x)
        return x.reshape(x.shape[0], -1)

    return init, apply, flat_dim


def _lstm_core(cell_size: int, in_dim: int):
    def init(rng):
        rng1, rng2 = jax.random.split(rng)
        scale = 1.0 / np.sqrt(in_dim + cell_size)
        return {
            "wx": jax.random.normal(rng1, (in_dim, 4 * cell_size)) * scale,
            "wh": jax.random.normal(rng2, (cell_size, 4 * cell_size)) * scale,
            "b": jnp.zeros((4 * cell_size,), jnp.float32),
        }

    def apply(params, x, state):
        h, c = state
        gates = x @ params["wx"] + h @ params["wh"] + params["b"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h, (h, c)

    def initial_state(batch: int):
        return (
            jnp.zeros((batch, cell_size), jnp.float32),
            jnp.zeros((batch, cell_size), jnp.float32),
        )

    return init, apply, initial_state


# --------------------------------------------------------------------------
# catalog entry points
# --------------------------------------------------------------------------


def _encoder_for(obs_shape: Sequence[int], cfg: ModelConfig):
    obs_shape = tuple(int(s) for s in obs_shape)
    if len(obs_shape) == 3:
        return _conv_encoder(cfg, obs_shape)  # (H, W, C) image
    return _mlp_encoder(cfg, int(np.prod(obs_shape)))


def get_actor_critic(
    obs_shape: Sequence[int],
    num_actions: int,
    config: Optional[ModelConfig] = None,
):
    """Returns (init_fn, apply_fn[, initial_state_fn]).

    Stateless (default): apply(params, obs) -> (logits [B, A], value [B]).
    use_lstm: apply(params, obs, state) -> ((logits, value), state'), plus
    an initial_state(batch) third return (reference: use_lstm wrapper in
    ModelCatalog / recurrent encoders in the new-stack catalog).
    """
    cfg = config or ModelConfig()
    enc_init, enc_apply, enc_dim = _encoder_for(obs_shape, cfg)
    head_in = cfg.lstm_cell_size if cfg.use_lstm else enc_dim
    if cfg.use_lstm:
        lstm_init, lstm_apply, lstm_state = _lstm_core(cfg.lstm_cell_size, enc_dim)

    def init(rng):
        rng_e, rng_l, rng_pi, rng_vf = jax.random.split(rng, 4)
        params = {
            "encoder": enc_init(rng_e),
            "pi": _dense_init(rng_pi, head_in, num_actions, 0.01),
            "vf": _dense_init(rng_vf, head_in, 1, 1.0),
        }
        if cfg.use_lstm:
            params["lstm"] = lstm_init(rng_l)
        return params

    def heads(params, x):
        logits = x @ params["pi"]["w"] + params["pi"]["b"]
        value = (x @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return logits, value

    if not cfg.use_lstm:

        def apply(params, obs):
            return heads(params, enc_apply(params["encoder"], obs))

        return init, apply

    def apply_recurrent(params, obs, state):
        x = enc_apply(params["encoder"], obs)
        x, state = lstm_apply(params["lstm"], x, state)
        return heads(params, x), state

    return init, apply_recurrent, lstm_state


def get_q_model(
    obs_shape: Sequence[int],
    num_actions: int,
    config: Optional[ModelConfig] = None,
):
    """Returns (init_fn, apply_fn): apply(params, obs) -> Q-values [B, A]."""
    cfg = config or ModelConfig()
    if cfg.use_lstm:
        raise ValueError("recurrent Q networks are not supported")
    enc_init, enc_apply, enc_dim = _encoder_for(obs_shape, cfg)

    def init(rng):
        rng_e, rng_q = jax.random.split(rng)
        return {
            "encoder": enc_init(rng_e),
            "q": _dense_init(rng_q, enc_dim, num_actions, 1.0),
        }

    def apply(params, obs):
        x = enc_apply(params["encoder"], obs)
        return x @ params["q"]["w"] + params["q"]["b"]

    return init, apply
