"""Uniform replay buffer for off-policy algorithms (DQN/SAC).

Reference parity: rllib/utils/replay_buffers/replay_buffer.py (ring storage,
uniform sample). Columns are preallocated numpy rings sized at first add, so
sampling is a single fancy-index per column — no per-transition Python
objects.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .sample_batch import SampleBatch


class ReplayBuffer:
    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = len(batch)
        if self._cols is None:
            self._cols = {
                k: np.empty((self.capacity,) + np.asarray(v).shape[1:], np.asarray(v).dtype)
                for k, v in batch.items()
            }
        end = self._idx + n
        for k, v in batch.items():
            v = np.asarray(v)
            if end <= self.capacity:
                self._cols[k][self._idx : end] = v
            else:  # wrap
                split = self.capacity - self._idx
                self._cols[k][self._idx :] = v[:split]
                self._cols[k][: end - self.capacity] = v[split:]
        self._idx = end % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> SampleBatch:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        idx = self._rng.integers(0, self._size, size=batch_size)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    rllib/utils/replay_buffers/prioritized_replay_buffer.py — the sum-tree
    proportional scheme of Schaul et al.). Numpy-vectorized: sampling is one
    cumsum + searchsorted over the priority ring, importance weights are
    (N * P)^-beta normalized by their max (the published correction)."""

    def __init__(self, capacity: int, alpha: float = 0.6, seed: int = 0):
        super().__init__(capacity, seed=seed)
        self.alpha = float(alpha)
        self._prios = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def add(self, batch: SampleBatch) -> None:
        n = len(batch)
        start = self._idx
        super().add(batch)
        # new transitions get max priority so they are seen at least once
        idx = (start + np.arange(n)) % self.capacity
        self._prios[idx] = self._max_prio ** self.alpha

    def sample(self, batch_size: int, beta: float = 0.4):
        """Returns (batch, indices, is_weights)."""
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        p = self._prios[: self._size]
        cum = np.cumsum(p)
        total = cum[-1]
        targets = self._rng.random(batch_size) * total
        idx = np.searchsorted(cum, targets, side="right")
        idx = np.minimum(idx, self._size - 1)
        probs = p[idx] / total
        weights = (self._size * probs) ** (-float(beta))
        # normalize by the BUFFER-wide max weight (Schaul et al. eq. after
        # (1): max_i w_i comes from the min-probability transition), so a
        # transition's weight doesn't depend on which batch sampled it
        max_w = (self._size * (p.min() / total)) ** (-float(beta))
        weights = (weights / max_w).astype(np.float32)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()}), idx, weights

    def update_priorities(self, indices, priorities) -> None:
        priorities = np.asarray(priorities, np.float64) + 1e-6
        self._prios[np.asarray(indices)] = priorities ** self.alpha
        self._max_prio = max(self._max_prio, float(priorities.max()))
