"""Uniform replay buffer for off-policy algorithms (DQN/SAC).

Reference parity: rllib/utils/replay_buffers/replay_buffer.py (ring storage,
uniform sample). Columns are preallocated numpy rings sized at first add, so
sampling is a single fancy-index per column — no per-transition Python
objects.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .sample_batch import SampleBatch


class ReplayBuffer:
    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = len(batch)
        if self._cols is None:
            self._cols = {
                k: np.empty((self.capacity,) + np.asarray(v).shape[1:], np.asarray(v).dtype)
                for k, v in batch.items()
            }
        end = self._idx + n
        for k, v in batch.items():
            v = np.asarray(v)
            if end <= self.capacity:
                self._cols[k][self._idx : end] = v
            else:  # wrap
                split = self.capacity - self._idx
                self._cols[k][self._idx :] = v[:split]
                self._cols[k][: end - self.capacity] = v[split:]
        self._idx = end % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> SampleBatch:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        idx = self._rng.integers(0, self._size, size=batch_size)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})
