"""Inference-side policy: jitted action computation on CPU rollout actors.

Reference parity: rllib/policy/policy.py (compute_actions_from_input_dict,
get/set_weights). One jit-compiled forward per rollout worker; sampling and
bookkeeping stay numpy.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import numpy as np

from .models import ac_apply, init_ac_params


class Policy:
    def __init__(self, obs_dim: int, num_actions: int, hidden=(64, 64), seed: int = 0):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.params = init_ac_params(
            jax.random.PRNGKey(seed), obs_dim, num_actions, hidden
        )
        self._apply = jax.jit(ac_apply)
        self._value = jax.jit(lambda params, obs: ac_apply(params, obs)[1])
        self._np_rng = np.random.default_rng(seed)

    def compute_actions(
        self, obs: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """obs [E, obs_dim] -> (actions [E], logp [E], values [E])."""
        logits, values = jax.device_get(self._apply(self.params, obs))
        logits = logits - logits.max(axis=-1, keepdims=True)
        probs = np.exp(logits)
        probs /= probs.sum(axis=-1, keepdims=True)
        # vectorized categorical sampling via inverse CDF
        u = self._np_rng.random((obs.shape[0], 1))
        actions = (probs.cumsum(axis=-1) < u).sum(axis=-1).astype(np.int64)
        actions = np.minimum(actions, self.num_actions - 1)
        logp = np.log(probs[np.arange(obs.shape[0]), actions] + 1e-20)
        return actions, logp.astype(np.float32), values.astype(np.float32)

    def compute_values(self, obs: np.ndarray) -> np.ndarray:
        """Value-only forward: no sampling, does not advance the action RNG."""
        return np.asarray(jax.device_get(self._value(self.params, obs)), np.float32)

    def get_weights(self) -> Dict[str, Any]:
        return jax.device_get(self.params)

    def set_weights(self, weights: Dict[str, Any]) -> None:
        self.params = weights
