"""Offline RL IO: write rollout experience to disk, read it back for
offline training (behavior cloning / offline evaluation).

Reference parity: rllib/offline/ (json_writer.py / json_reader.py /
dataset_reader.py) — SampleBatches serialize to sharded .npz files (columns
are numpy arrays already; npz keeps them zero-parse and compact vs the
reference's base64-in-JSON rows), and readers stream shards through the
data layer so offline datasets compose with map_batches/shuffle/split.
"""

from __future__ import annotations

import glob
import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from .sample_batch import SampleBatch, concat_samples


class JsonWriter:
    """Append SampleBatches to sharded files under a directory.

    (Name kept for reference parity; the on-disk format is npz shards.)"""

    def __init__(self, path: str, *, max_rows_per_file: int = 5000):
        self.path = path
        self.max_rows = max_rows_per_file
        os.makedirs(path, exist_ok=True)
        self._pending: List[SampleBatch] = []
        self._rows = 0
        self._shard = len(glob.glob(os.path.join(path, "shard-*.npz")))

    def write(self, batch: SampleBatch) -> None:
        self._pending.append(batch)
        self._rows += len(batch)
        if self._rows >= self.max_rows:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        merged = concat_samples(self._pending)
        out = os.path.join(self.path, f"shard-{self._shard:06d}.npz")
        tmp = out + ".tmp.npz"  # .npz suffix: savez must not append one
        np.savez_compressed(tmp, **{k: np.asarray(v) for k, v in merged.items()})
        os.replace(tmp, out)
        self._shard += 1
        self._pending = []
        self._rows = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.flush()


def _load_shard(path: str) -> SampleBatch:
    with np.load(path) as z:
        return SampleBatch({k: z[k] for k in z.files})


class JsonReader:
    """Stream SampleBatches back from a written directory."""

    def __init__(self, path: str, *, shuffle: bool = False, seed: Optional[int] = None):
        self.files = sorted(glob.glob(os.path.join(path, "shard-*.npz")))
        if not self.files:
            raise FileNotFoundError(f"no offline shards under {path}")
        if shuffle:
            np.random.default_rng(seed).shuffle(self.files)

    def __iter__(self) -> Iterator[SampleBatch]:
        for f in self.files:
            yield _load_shard(f)

    def read_all(self) -> SampleBatch:
        return concat_samples([_load_shard(f) for f in self.files])


def to_dataset(path: str):
    """Expose an offline directory as a Dataset of SampleBatch blocks
    (composes with the data layer: map_batches, split_at, actor pools)."""
    from ..data.dataset import Dataset

    files = sorted(glob.glob(os.path.join(path, "shard-*.npz")))
    if not files:
        raise FileNotFoundError(f"no offline shards under {path}")
    return Dataset([lambda f=f: _load_shard(f) for f in files])


def write_dataset(batches: Sequence[SampleBatch], path: str, **kw) -> int:
    """Convenience: write a sequence of batches; returns total rows."""
    total = 0
    with JsonWriter(path, **kw) as w:
        for b in batches:
            w.write(b)
            total += len(b)
    return total
