"""Ape-X DQN: distributed prioritized experience replay.

Reference parity: rllib/algorithms/apex_dqn/apex_dqn.py — the Ape-X
architecture (Horgan et al.): many exploration actors with an epsilon
LADDER push transitions to dedicated replay-buffer ACTORS; the learner
samples prioritized batches from them, trains, and writes updated TD-error
priorities back; weights broadcast periodically. The rollout→replay data
path rides the object store actor-to-actor (`replay.add.remote(sample_ref)`
— the driver never touches transition bytes), which is exactly the
reference's ray-object-store replay plumbing.

TPU-first: the learner's per-batch update (IS-weighted double-Q Huber step
+ per-sample |TD| for the priority write-back) is ONE jitted function; the
distributed machinery around it is ordinary actors.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .algorithm import Algorithm
from .dqn import DQNConfig, DQNLearner, _EpsilonGreedyWorker, dqn_td_huber
from .learner import LearnerGroup, TrainState
from .replay_buffer import PrioritizedReplayBuffer
from .rollout_worker import _make_env
from .sample_batch import SampleBatch


class ReplayActor:
    """A replay shard as an actor (reference: apex's ReplayActor). Rollout
    actors push into it; the learner samples from it and writes priorities
    back. Holding the buffer in an actor is what lets N rollout actors and
    the learner run fully asynchronously."""

    def __init__(self, capacity: int, alpha: float = 0.6, seed: int = 0):
        self.buffer = PrioritizedReplayBuffer(capacity, alpha=alpha, seed=seed)

    def ready(self) -> bool:
        return True

    def add(self, batch: SampleBatch) -> int:
        self.buffer.add(batch)
        return len(self.buffer)

    def size(self) -> int:
        return len(self.buffer)

    def sample(self, batch_size: int, beta: float = 0.4):
        if len(self.buffer) < batch_size:
            return None
        batch, idx, weights = self.buffer.sample(batch_size, beta=beta)
        return dict(batch), idx, weights

    def update_priorities(self, indices, priorities) -> bool:
        self.buffer.update_priorities(indices, priorities)
        return True


class ApexDQNConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = ApexDQN
        self.num_rollout_workers = 2
        self.replay_buffer_capacity: int = 100_000
        self.prioritized_replay_alpha: float = 0.6
        self.prioritized_replay_beta: float = 0.4
        # epsilon ladder (Ape-X eq. 1): worker i of N explores with
        # eps_base ** (1 + i/(N-1) * eps_exponent)
        self.epsilon_base: float = 0.4
        self.epsilon_exponent: float = 7.0
        self.samples_per_iteration: int = 4  # sample() calls per worker/iter


class ApexDQNLearner(DQNLearner):
    """DQN learner whose update is importance-weighted and returns the
    per-sample |TD| the replay actor needs for its priority write-back."""

    def _build_prio_update(self):
        optimizer = self.optimizer
        gamma, double_q = self.gamma, self.double_q

        def update(state: TrainState, mb, is_weights):
            def loss_fn(online):
                q_sel, td, huber = dqn_td_huber(
                    online, state.params["target"], mb, gamma, double_q
                )
                loss = jnp.mean(is_weights * huber)
                return loss, (td, q_sel)

            (loss, (td, q_sel)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params["online"]
            )
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params["online"]
            )
            online = optax.apply_updates(state.params["online"], updates)
            new_state = TrainState(
                {"online": online, "target": state.params["target"]},
                opt_state,
                state.rng,
            )
            metrics = {"loss": loss, "mean_q": jnp.mean(q_sel)}
            return new_state, jnp.abs(td), metrics

        return jax.jit(update, donate_argnums=(0,))

    def update_prioritized(self, batch: Dict[str, np.ndarray], is_weights):
        if getattr(self, "_prio_update_fn", None) is None:
            self._prio_update_fn = self._build_prio_update()
        mb = {k: jnp.asarray(np.asarray(v)) for k, v in batch.items()}
        self.state, td_abs, metrics = self._prio_update_fn(
            self.state, mb, jnp.asarray(is_weights)
        )
        self._grad_steps += 1
        if self._grad_steps % self.target_update_freq == 0:
            p = self.state.params
            self.state = self.state._replace(
                params={
                    "online": p["online"],
                    "target": jax.tree_util.tree_map(jnp.copy, p["online"]),
                }
            )
        return np.asarray(td_abs), {k: float(v) for k, v in metrics.items()}


class ApexDQN(Algorithm):
    _config_class = ApexDQNConfig

    def __init__(self, config=None, **kwargs):
        # validate BEFORE Algorithm.__init__ spawns the WorkerSet, so a bad
        # config doesn't leak live envs/actors on the error path
        n = (
            config.get("num_rollout_workers")
            if isinstance(config, dict)
            else getattr(config, "num_rollout_workers", None)
        )
        if n is not None and n < 1:
            raise ValueError(
                "ApexDQN is the DISTRIBUTED replay architecture: it needs "
                "num_rollout_workers >= 1 (use DQN for single-process runs)"
            )
        super().__init__(config, **kwargs)

    def _worker_cls(self):
        return _EpsilonGreedyWorker

    def _worker_kwargs(self):
        cfg = self.algo_config
        return dict(
            env_spec=cfg.env,
            num_envs=cfg.num_envs_per_worker,
            rollout_fragment_length=cfg.rollout_fragment_length,
            policy_hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )

    def _build_learner(self) -> LearnerGroup:
        import ray_tpu

        cfg = self.algo_config
        env = _make_env(cfg.env)
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close()

        Replay = ray_tpu.remote(ReplayActor)
        self.replay_actor = Replay.remote(
            cfg.replay_buffer_capacity,
            alpha=cfg.prioritized_replay_alpha,
            seed=cfg.seed,
        )
        ray_tpu.get(self.replay_actor.ready.remote())

        # epsilon ladder across workers (Ape-X): diverse exploration
        n = max(1, cfg.num_rollout_workers)
        self._worker_eps = [
            cfg.epsilon_base ** (1.0 + (i / max(1, n - 1)) * cfg.epsilon_exponent)
            for i in range(n)
        ]

        def factory():
            return ApexDQNLearner(
                obs_dim=obs_dim,
                num_actions=num_actions,
                hidden=tuple(cfg.model.get("hidden", (64, 64))),
                lr=cfg.lr,
                gamma=cfg.gamma,
                double_q=cfg.double_q,
                target_update_freq=cfg.target_update_freq,
                num_sgd_iter=cfg.num_sgd_iter,
                minibatch_size=cfg.minibatch_size,
                seed=cfg.seed,
            )

        return LearnerGroup(factory, remote=False)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        cfg = self.algo_config
        learner: ApexDQNLearner = self.learner_group._learner
        workers = self.workers._remote_workers

        # 1. rollout -> replay, actor-to-actor: pass each sample's REF to
        # the replay actor; transition bytes ride the object store, never
        # the driver (reference: apex's store_to_replay pipeline)
        add_refs = []
        for _ in range(cfg.samples_per_iteration):
            for w, eps in zip(workers, self._worker_eps):
                add_refs.append(self.replay_actor.add.remote(w.sample.remote(eps)))
        sizes = ray_tpu.get(add_refs)
        self._timesteps_total += (
            cfg.samples_per_iteration
            * len(workers)
            * cfg.rollout_fragment_length
            * cfg.num_envs_per_worker
        )

        metrics: Dict[str, Any] = {"replay_size": int(sizes[-1])}
        if sizes[-1] < cfg.learning_starts:
            return metrics

        # 2. prioritized learn loop with TD-priority write-back; the next
        # batch is prefetched while the current one trains — but only when
        # another iteration will actually consume it (a dangling sample is
        # an O(buffer) cumsum + transfer thrown away)
        next_ref = self.replay_actor.sample.remote(
            cfg.minibatch_size, cfg.prioritized_replay_beta
        )
        for i in range(cfg.num_sgd_iter):
            got = ray_tpu.get(next_ref)
            if i + 1 < cfg.num_sgd_iter and got is not None:
                next_ref = self.replay_actor.sample.remote(
                    cfg.minibatch_size, cfg.prioritized_replay_beta
                )
            if got is None:
                break
            batch, idx, weights = got
            td_abs, m = learner.update_prioritized(batch, weights)
            self.replay_actor.update_priorities.remote(idx, td_abs)
            metrics.update(m)

        # 3. weight broadcast
        weights = learner.get_weights()
        ray_tpu.get([w.set_weights.remote(weights) for w in workers])
        return metrics

    def cleanup(self) -> None:
        import ray_tpu

        super().cleanup()
        try:
            ray_tpu.kill(self.replay_actor)
        except Exception:
            pass

    stop = cleanup
