"""Learner: the gradient-update side, compiled as ONE XLA program.

Reference parity: rllib/core/learner/learner.py:170 (compute_gradients :482,
apply_gradients :604, update :1086) and learner_group.py:61 (LearnerGroup of
DDP-style learner actors). TPU-first redesign: where the reference runs a
Python loop of minibatch SGD steps with NCCL allreduce between learner
actors, here the whole update — num_epochs x num_minibatches, with
per-epoch reshuffling — is a single jitted program (lax.scan over scans)
executing on a device mesh; data parallelism is a sharded batch dimension
lowered by GSPMD to ICI all-reduces, not actor-to-actor collectives.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .models import ac_apply, init_ac_params
from .sample_batch import ACTIONS, ADVANTAGES, LOGP, LOSS_MASK, OBS, TARGETS, VALUES, SampleBatch


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    rng: jax.Array


class Learner:
    """Base learner: owns params/optimizer; subclasses define the loss.

    Subclass contract (mirrors Learner.compute_loss_for_module in the
    reference): implement `loss(params, minibatch) -> (scalar, metrics)`.
    """

    def __init__(self, config):
        self.config = config
        self._update_fn: Optional[Callable] = None

    # -- weights (learner.py get_state/set_state) --

    def get_weights(self) -> Any:
        return jax.device_get(self.state.params)

    def set_weights(self, weights: Any) -> None:
        self.state = self.state._replace(params=jax.device_put(weights))

    def loss(self, params, minibatch) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        raise NotImplementedError


class PPOLearner(Learner):
    def __init__(
        self,
        obs_dim: int,
        num_actions: int,
        hidden=(64, 64),
        lr: float = 3e-4,
        clip_eps: float = 0.2,
        vf_coeff: float = 0.5,
        entropy_coeff: float = 0.01,
        num_epochs: int = 4,
        minibatch_size: int = 128,
        max_grad_norm: float = 0.5,
        seed: int = 0,
        mesh=None,
    ):
        super().__init__(config=None)
        self.clip_eps = clip_eps
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.num_epochs = num_epochs
        self.minibatch_size = minibatch_size
        self.mesh = mesh
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(max_grad_norm),
            optax.adam(lr, eps=1e-5),
        )
        params = init_ac_params(jax.random.PRNGKey(seed), obs_dim, num_actions, hidden)
        self.state = TrainState(
            params=params,
            opt_state=self.optimizer.init(params),
            rng=jax.random.PRNGKey(seed + 1),
        )

    def loss(self, params, mb):
        # mask-aware means: padded rows (multi-agent ragged batches carry
        # LOSS_MASK=0 padding) contribute zero gradient, not duplicate data
        w = mb[LOSS_MASK]
        wsum = jnp.maximum(jnp.sum(w), 1.0)

        def wmean(x):
            return jnp.sum(x * w) / wsum

        logits, value = ac_apply(params, mb[OBS])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, mb[ACTIONS][:, None], axis=-1)[:, 0]
        ratio = jnp.exp(logp - mb[LOGP])
        adv = mb[ADVANTAGES]
        adv_mean = wmean(adv)
        adv_std = jnp.sqrt(jnp.maximum(wmean((adv - adv_mean) ** 2), 0.0))
        adv = (adv - adv_mean) / (adv_std + 1e-8)
        pg_loss = -wmean(
            jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1.0 - self.clip_eps, 1.0 + self.clip_eps) * adv,
            )
        )
        # clipped value loss (PPO2-style)
        v_clip = mb[VALUES] + jnp.clip(
            value - mb[VALUES], -self.clip_eps, self.clip_eps
        )
        vf_loss = 0.5 * wmean(
            jnp.maximum((value - mb[TARGETS]) ** 2, (v_clip - mb[TARGETS]) ** 2)
        )
        entropy = wmean(-jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = pg_loss + self.vf_coeff * vf_loss - self.entropy_coeff * entropy
        approx_kl = wmean(mb[LOGP] - logp)
        return total, {
            "total_loss": total,
            "policy_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "approx_kl": approx_kl,
        }

    def _build_update(self, batch_size: int):
        # minibatch size aligned to the mesh so sharded batch dims divide
        # evenly across devices (GSPMD requires divisible global shapes)
        n_dev = 1 if self.mesh is None else int(np.prod(self.mesh.devices.shape))
        mb_size = max(n_dev, (self.minibatch_size // n_dev) * n_dev)
        num_mb = max(1, batch_size // mb_size)
        used = num_mb * mb_size
        self._built_used = used
        num_epochs = self.num_epochs
        optimizer = self.optimizer
        loss_fn = self.loss

        def minibatch_step(carry, mb):
            params, opt_state = carry
            (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, mb)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), metrics

        def epoch_step(carry, epoch_rng):
            params, opt_state, batch = carry
            perm = jax.random.permutation(epoch_rng, used)
            shuffled = jax.tree_util.tree_map(
                lambda a: a[perm].reshape((num_mb, mb_size) + a.shape[1:]), batch
            )
            (params, opt_state), metrics = jax.lax.scan(
                minibatch_step, (params, opt_state), shuffled
            )
            return (params, opt_state, batch), metrics

        def update(state: TrainState, batch):
            rng, sub = jax.random.split(state.rng)
            epoch_rngs = jax.random.split(sub, num_epochs)
            (params, opt_state, _), metrics = jax.lax.scan(
                epoch_step, (state.params, state.opt_state, batch), epoch_rngs
            )
            # report the last epoch's mean metrics
            metrics = jax.tree_util.tree_map(lambda m: m[-1].mean(), metrics)
            return TrainState(params, opt_state, rng), metrics

        if self.mesh is not None and np.prod(self.mesh.devices.shape) > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            data_axes = tuple(self.mesh.axis_names)
            replicated = NamedSharding(self.mesh, P())
            self._batch_sharding = NamedSharding(self.mesh, P(data_axes))
            return jax.jit(
                update,
                in_shardings=(replicated, self._batch_sharding),
                out_shardings=(replicated, replicated),
                donate_argnums=(0,),
            )
        self._batch_sharding = None
        return jax.jit(update, donate_argnums=(0,))

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        """One training iteration over a full sample batch."""
        size = len(batch)
        if self._update_fn is None or getattr(self, "_built_for", None) != size:
            self._update_fn = self._build_update(size)
            self._built_for = size
        # truncate on host BEFORE device_put so the sharded leading dim is
        # exactly the mesh-aligned size the compiled program expects
        used = self._built_used
        cols = {
            k: jnp.asarray(batch[k][:used])
            for k in (OBS, ACTIONS, LOGP, ADVANTAGES, TARGETS, VALUES)
        }
        cols[LOSS_MASK] = (
            jnp.asarray(batch[LOSS_MASK][:used])
            if LOSS_MASK in batch.keys()
            else jnp.ones(used, jnp.float32)
        )
        if self._batch_sharding is not None:
            cols = {k: jax.device_put(v, self._batch_sharding) for k, v in cols.items()}
        self.state, metrics = self._update_fn(self.state, cols)
        return {k: float(v) for k, v in metrics.items()}


class LearnerGroup:
    """Drives one or more learners.

    Reference parity: learner_group.py:61. In ray_tpu the group is almost
    always ONE learner spanning the whole mesh (GSPMD replaces the
    reference's multi-actor DDP); `remote=True` runs that learner in a
    dedicated TPU actor so rollouts and updates overlap.
    """

    def __init__(
        self,
        learner_factory: Callable[[], Learner],
        remote: bool = False,
        num_tpus: float = 0.0,
    ):
        self._remote = remote
        if remote:
            import ray_tpu

            holder = ray_tpu.remote(_LearnerActor)
            opts = {"num_cpus": 1}
            if num_tpus:
                # a TPU reservation routes the actor to a full-site worker
                # that may own the chips (head._spawn_worker needs_tpu path)
                opts["resources"] = {"TPU": num_tpus}
            self._actor = holder.options(**opts).remote(learner_factory)
            ray_tpu.get(self._actor.ready.remote())
        else:
            self._learner = learner_factory()

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        if self._remote:
            import ray_tpu

            return ray_tpu.get(self._actor.update.remote(dict(batch)))
        return self._learner.update(batch)

    def get_weights(self):
        if self._remote:
            import ray_tpu

            return ray_tpu.get(self._actor.get_weights.remote())
        return self._learner.get_weights()

    def set_weights(self, weights) -> None:
        if self._remote:
            import ray_tpu

            ray_tpu.get(self._actor.set_weights.remote(weights))
        else:
            self._learner.set_weights(weights)


class _LearnerActor:
    def __init__(self, learner_factory):
        self.learner = learner_factory()

    def ready(self):
        return True

    def update(self, batch_dict):
        return self.learner.update(SampleBatch(batch_dict))

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)
