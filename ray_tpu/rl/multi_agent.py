"""Multi-agent RL: dict-keyed envs, policy mapping, per-policy training.

Reference parity:
  - MultiAgentEnv protocol: rllib/env/multi_agent_env.py:30 (reset/step
    over per-agent dicts, "__all__" termination key, possibly-disjoint
    agent sets per step).
  - make_multi_agent: rllib/env/multi_agent_env.py:399 (wrap N copies of a
    single-agent env into one multi-agent env).
  - MultiAgentBatch: rllib/policy/sample_batch.py MultiAgentBatch (dict
    policy_id -> SampleBatch + env-step accounting).
  - Policy mapping: rllib/policy/policy_map.py:20 + the
    policy_mapping_fn config of algorithm_config.py — agents are routed to
    named policies; policies train ONLY on their own agents' experience.

TPU-first redesign notes: policies stay small CPU-side pytrees for
rollouts; training batches are merged per policy and each policy's PPO
update is the same single jitted epochs-x-minibatches program the
single-agent learner compiles (learner.py) — one dispatch per policy per
iteration, not per agent.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from .algorithm import Algorithm
from .learner import PPOLearner
from .ppo import PPOConfig
from .policy import Policy
from .sample_batch import (
    ACTIONS,
    ADVANTAGES,
    DONES,
    LOGP,
    OBS,
    REWARDS,
    TARGETS,
    VALUES,
    SampleBatch,
    compute_gae,
    concat_samples,
)

AgentID = Any
PolicyID = str


class MultiAgentEnv:
    """Dict-keyed environment (reference: multi_agent_env.py:30).

    Subclasses implement reset() -> (obs_dict, info_dict) and
    step(action_dict) -> (obs, rewards, terminateds, truncateds, infos),
    all keyed by agent id; terminateds/truncateds carry the special
    "__all__" key ending the episode for everyone. Agents may appear and
    disappear between steps — an agent acts exactly when its id is in the
    latest obs dict."""

    # uniform spaces (per-agent overrides via observation_spaces dicts)
    observation_space: Any = None
    action_space: Any = None
    possible_agents: List[AgentID] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[AgentID, Any]):
        raise NotImplementedError

    def get_state(self) -> np.ndarray:
        """Global state for centralized critics/mixers (QMIX). Default:
        concatenation of every possible agent's last observation is NOT
        derivable here, so subclasses with centralized training override
        this; envs used only with independent learners can ignore it."""
        raise NotImplementedError

    def close(self):
        pass


def make_multi_agent(env_spec: Union[str, Callable[[], Any]], num_agents: int):
    """N independent copies of a single-agent env as one MultiAgentEnv
    (reference: multi_agent_env.py:399 make_multi_agent). Agent i's episode
    ends independently; "__all__" fires when every copy is done."""

    def _make():
        from .rollout_worker import _make_env

        return _make_env(env_spec)

    class _MultiEnv(MultiAgentEnv):
        def __init__(self):
            self.envs = {i: _make() for i in range(num_agents)}
            self.possible_agents = list(self.envs)
            probe = self.envs[0]
            self.observation_space = probe.observation_space
            self.action_space = probe.action_space
            self._done: Dict[AgentID, bool] = {}

        def reset(self, *, seed: Optional[int] = None):
            obs, infos = {}, {}
            for i, env in self.envs.items():
                o, info = env.reset(seed=None if seed is None else seed + i)
                obs[i] = np.asarray(o, np.float32)
                infos[i] = info
            self._done = {i: False for i in self.envs}
            return obs, infos

        def step(self, action_dict):
            obs, rews, terms, truncs, infos = {}, {}, {}, {}, {}
            for i, a in action_dict.items():
                if self._done.get(i, True):
                    continue
                o, r, te, tr, info = self.envs[i].step(a)
                rews[i] = float(r)
                terms[i] = bool(te)
                truncs[i] = bool(tr)
                infos[i] = info
                # the FINAL observation rides the obs dict even when the
                # copy ended (RLlib convention) — truncation bootstrapping
                # needs V(s_final); consumers use terms/truncs, not obs
                # presence, to decide whether the agent acts again
                obs[i] = np.asarray(o, np.float32)
                if te or tr:
                    self._done[i] = True
            all_done = all(self._done.values())
            terms["__all__"] = all_done
            truncs["__all__"] = False
            return obs, rews, terms, truncs, infos

    return _MultiEnv


class MultiAgentBatch:
    """Per-policy sample batches + env-step count (reference:
    sample_batch.py MultiAgentBatch)."""

    def __init__(self, policy_batches: Dict[PolicyID, SampleBatch], env_steps: int):
        self.policy_batches = policy_batches
        self._env_steps = int(env_steps)

    def env_steps(self) -> int:
        return self._env_steps

    def agent_steps(self) -> int:
        return sum(len(b) for b in self.policy_batches.values())

    def __len__(self) -> int:
        return self._env_steps


def concat_multi_agent(batches: List[MultiAgentBatch]) -> MultiAgentBatch:
    out: Dict[PolicyID, List[SampleBatch]] = {}
    steps = 0
    for mb in batches:
        steps += mb.env_steps()
        for pid, b in mb.policy_batches.items():
            out.setdefault(pid, []).append(b)
    return MultiAgentBatch(
        {pid: concat_samples(bs) for pid, bs in out.items()}, steps
    )


class _AgentTrajectory:
    """Per-agent episode columns, GAE'd on close with that agent's policy."""

    __slots__ = ("obs", "actions", "rewards", "values", "logp")

    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.actions: List[int] = []
        self.rewards: List[float] = []
        self.values: List[float] = []
        self.logp: List[float] = []

    def close(
        self, bootstrap: float, gamma: float, lam: float, terminal: bool = True
    ) -> SampleBatch:
        T = len(self.actions)
        rew = np.asarray(self.rewards, np.float32).reshape(T, 1)
        val = np.asarray(self.values, np.float32).reshape(T, 1)
        dones = np.zeros((T, 1), np.float32)
        # compute_gae multiplies the bootstrap by (1 - dones[-1]): only a
        # genuine termination may mark the last step done, else the
        # truncation/fragment-edge bootstrap would be silently zeroed
        if terminal:
            dones[-1, 0] = 1.0
        gae = compute_gae(
            rew, val, dones, np.asarray([bootstrap], np.float32), gamma, lam
        )
        return SampleBatch(
            {
                OBS: np.stack(self.obs).astype(np.float32),
                ACTIONS: np.asarray(self.actions, np.int64),
                REWARDS: rew[:, 0],
                DONES: dones[:, 0],
                VALUES: val[:, 0],
                LOGP: np.asarray(self.logp, np.float32),
                ADVANTAGES: gae[ADVANTAGES][:, 0],
                TARGETS: gae[TARGETS][:, 0],
            }
        )


class MultiAgentRolloutWorker:
    """One sampling actor over a MultiAgentEnv: routes each agent's obs to
    its mapped policy, collects per-AGENT trajectories, and emits a
    per-POLICY MultiAgentBatch with GAE attached (reference:
    rollout_worker.py sample() + policy_map routing)."""

    def __init__(
        self,
        env_maker: Callable[[], MultiAgentEnv],
        policy_specs: Dict[PolicyID, Tuple[int, int]],  # pid -> (obs_dim, n_act)
        policy_mapping_fn: Callable[[AgentID], PolicyID],
        rollout_fragment_length: int = 200,
        gamma: float = 0.99,
        lam: float = 0.95,
        seed: int = 0,
        policy_hidden=(64, 64),
    ):
        self.env = env_maker()
        self.map_fn = policy_mapping_fn
        self.T = rollout_fragment_length
        self.gamma, self.lam = gamma, lam
        self.policies: Dict[PolicyID, Policy] = {
            pid: Policy(od, na, policy_hidden, seed=seed + i)
            for i, (pid, (od, na)) in enumerate(sorted(policy_specs.items()))
        }
        self._obs, _ = self.env.reset(seed=seed)
        self._traj: Dict[AgentID, _AgentTrajectory] = {}
        self._episode_returns: List[float] = []
        self._episode_lens: List[int] = []
        self._ep_ret = 0.0
        self._ep_len = 0
        self._episodes_since_drain = 0

    def ready(self) -> bool:
        return True

    def get_weights(self) -> Dict[PolicyID, Any]:
        return {pid: p.get_weights() for pid, p in self.policies.items()}

    def set_weights(self, weights: Dict[PolicyID, Any]) -> None:
        for pid, w in weights.items():
            self.policies[pid].set_weights(w)

    def _policy_of(self, aid: AgentID) -> PolicyID:
        return self.map_fn(aid)

    def sample(self) -> MultiAgentBatch:
        """Collect >= T env steps (finishing episodes at the fragment edge
        by bootstrap-truncating every live trajectory)."""
        done_batches: Dict[PolicyID, List[SampleBatch]] = {}
        steps = 0
        while steps < self.T:
            acting = sorted(self._obs.keys())
            if not acting:  # defensive: empty obs dict outside episode end
                self._obs, _ = self.env.reset()
                continue
            # route by policy: ONE batched forward per policy per step
            by_pid: Dict[PolicyID, List[AgentID]] = {}
            for aid in acting:
                by_pid.setdefault(self._policy_of(aid), []).append(aid)
            actions: Dict[AgentID, int] = {}
            meta: Dict[AgentID, Tuple[float, float]] = {}
            for pid, aids in by_pid.items():
                obs_mat = np.stack([self._obs[a] for a in aids])
                acts, logps, vals = self.policies[pid].compute_actions(obs_mat)
                for a, act, lp, v in zip(aids, acts, logps, vals):
                    actions[a] = int(act)
                    meta[a] = (float(lp), float(v))
            nobs, rews, terms, truncs, _ = self.env.step(actions)
            steps += 1
            self._ep_len += 1
            ep_end = bool(terms.get("__all__")) or bool(truncs.get("__all__"))
            acting_set = set(acting)
            # the env may pay an agent that did NOT act this step (RLlib
            # allows reward dicts over any agent): fold it into that
            # agent's LAST transition rather than dropping it
            for aid, r in rews.items():
                if aid == "__all__" or aid in acting_set:
                    continue
                self._ep_ret += float(r)
                tr = self._traj.get(aid)
                if tr is not None and tr.rewards:
                    tr.rewards[-1] += float(r)
            ended_agents = set()
            for aid in acting:
                tr = self._traj.setdefault(aid, _AgentTrajectory())
                tr.obs.append(self._obs[aid])
                tr.actions.append(actions[aid])
                r = float(rews.get(aid, 0.0))
                tr.rewards.append(r)
                self._ep_ret += r
                lp, v = meta[aid]
                tr.logp.append(lp)
                tr.values.append(v)
                a_term = bool(terms.get(aid))
                a_trunc = bool(truncs.get(aid))
                # an episode ending only via "__all__" (RLlib convention)
                # must still close every live trajectory, or it would bleed
                # across the reset into the next episode. An agent merely
                # ABSENT from the next obs dict (turn-based env) keeps its
                # trajectory open — it may act again later this episode.
                if a_term or a_trunc or ep_end:
                    terminal = a_term or (
                        bool(terms.get("__all__")) and not a_trunc
                    )
                    boot = 0.0
                    if not terminal and aid in nobs:
                        boot = float(
                            self.policies[self._policy_of(aid)].compute_values(
                                nobs[aid][None]
                            )[0]
                        )
                    done_batches.setdefault(self._policy_of(aid), []).append(
                        tr.close(boot, self.gamma, self.lam, terminal=terminal)
                    )
                    self._traj.pop(aid, None)
                    ended_agents.add(aid)
            if ep_end:
                # close any agent whose trajectory is still open (it did
                # not act this step but its episode just ended)
                for aid, tr in list(self._traj.items()):
                    if tr.actions:
                        done_batches.setdefault(self._policy_of(aid), []).append(
                            tr.close(
                                0.0, self.gamma, self.lam,
                                terminal=bool(terms.get("__all__")),
                            )
                        )
                    self._traj.pop(aid, None)
                self._episode_returns.append(self._ep_ret)
                self._episode_lens.append(self._ep_len)
                self._episodes_since_drain += 1
                self._ep_ret = 0.0
                self._ep_len = 0
                self._obs, _ = self.env.reset()
            else:
                # final observations of ended agents stay OUT of the acting
                # set (the RLlib obs dict may carry them for bootstrapping)
                self._obs = {a: o for a, o in nobs.items() if a not in ended_agents}
        # fragment edge: bootstrap-close every live trajectory (the episode
        # continues next sample(), but PPO trains on completed GAE segments)
        for aid, tr in list(self._traj.items()):
            if not tr.actions:
                continue
            pid = self._policy_of(aid)
            boot = 0.0
            if aid in self._obs:
                boot = float(self.policies[pid].compute_values(self._obs[aid][None])[0])
            done_batches.setdefault(pid, []).append(
                tr.close(boot, self.gamma, self.lam, terminal=False)
            )
            self._traj.pop(aid, None)
        return MultiAgentBatch(
            {pid: concat_samples(bs) for pid, bs in done_batches.items()}, steps
        )

    def episode_metrics(self, window: int = 100) -> Dict[str, Any]:
        """Same contract as EnvLoopWorker.episode_metrics, so WorkerSet
        aggregates multi-agent workers identically."""
        rets = self._episode_returns[-window:]
        lens = self._episode_lens[-window:]
        out = {
            "episodes_this_iter": self._episodes_since_drain,
            "episode_reward_mean": float(np.mean(rets)) if rets else float("nan"),
            "episode_len_mean": float(np.mean(lens)) if lens else float("nan"),
        }
        self._episodes_since_drain = 0
        return out

    def stop(self) -> None:
        self.env.close()


class MultiAgentPPOConfig(PPOConfig):
    """PPOConfig + the multi-agent routing block — inherits the PPO
    hyperparameter defaults (clip_eps/vf_coeff/entropy_coeff/
    max_grad_norm) so single- and multi-agent PPO stay in lockstep."""

    def __init__(self):
        super().__init__()
        self.algo_class = MultiAgentPPO
        self.policies: Optional[Dict[PolicyID, Tuple[int, int]]] = None
        self.policy_mapping_fn: Callable[[AgentID], PolicyID] = (
            lambda aid: "default_policy"
        )

    def multi_agent(
        self,
        *,
        policies: Optional[Dict[PolicyID, Tuple[int, int]]] = None,
        policy_mapping_fn: Optional[Callable[[AgentID], PolicyID]] = None,
    ) -> "MultiAgentPPOConfig":
        """Reference: AlgorithmConfig.multi_agent(policies=...,
        policy_mapping_fn=...). policies maps policy id -> (obs_dim,
        num_actions); None infers ONE shared policy from the env spaces."""
        if policies is not None:
            self.policies = policies
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self


class _MultiPolicyLearnerGroup:
    """LearnerGroup-shaped adapter over per-policy learners, so every base
    Algorithm/Trainable path (save_checkpoint/load_checkpoint/weight sync)
    works unchanged on multi-agent algorithms (reference:
    learner_group.py's MultiRLModule handling)."""

    def __init__(self, learners: Dict[PolicyID, PPOLearner]):
        self.learners = learners

    def update(self, batch: MultiAgentBatch) -> Dict[str, Any]:
        return {
            pid: self.learners[pid].update(pb)
            for pid, pb in batch.policy_batches.items()
        }

    def get_weights(self) -> Dict[PolicyID, Any]:
        return {pid: ln.get_weights() for pid, ln in self.learners.items()}

    def set_weights(self, weights: Dict[PolicyID, Any]) -> None:
        for pid, w in weights.items():
            self.learners[pid].set_weights(w)


class MultiAgentPPO(Algorithm):
    """Independent/shared-parameter PPO over a MultiAgentEnv: one
    PPOLearner per policy, each updated on its own merged batch
    (reference: the multi-agent training path of ppo.py training_step +
    policy_map.py). Rides the base WorkerSet/Trainable plumbing — the
    sampling actor and learner group are the only multi-agent parts."""

    _config_class = MultiAgentPPOConfig

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = self.algo_config
        if not callable(cfg.env):
            raise ValueError("MultiAgentPPO needs a callable env maker")
        if cfg.policies is None:
            probe = cfg.env()
            obs_dim = int(np.prod(probe.observation_space.shape))
            n_act = int(probe.action_space.n)
            probe.close()
            cfg.policies = {"default_policy": (obs_dim, n_act)}
        super().setup(config)

    def _worker_cls(self):
        return MultiAgentRolloutWorker

    def _worker_kwargs(self) -> Dict[str, Any]:
        cfg = self.algo_config
        return dict(
            env_maker=cfg.env,
            policy_specs=cfg.policies,
            policy_mapping_fn=cfg.policy_mapping_fn,
            rollout_fragment_length=cfg.rollout_fragment_length,
            gamma=cfg.gamma,
            lam=cfg.lambda_,
            policy_hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )

    def _build_learner(self) -> _MultiPolicyLearnerGroup:
        cfg = self.algo_config
        return _MultiPolicyLearnerGroup(
            {
                pid: PPOLearner(
                    obs_dim=od,
                    num_actions=na,
                    hidden=tuple(cfg.model.get("hidden", (64, 64))),
                    lr=cfg.lr,
                    clip_eps=cfg.clip_eps,
                    vf_coeff=cfg.vf_coeff,
                    entropy_coeff=cfg.entropy_coeff,
                    num_epochs=cfg.num_epochs,
                    minibatch_size=cfg.minibatch_size,
                    max_grad_norm=cfg.max_grad_norm,
                    seed=cfg.seed + i,
                    mesh=cfg.mesh,
                )
                for i, (pid, (od, na)) in enumerate(sorted(cfg.policies.items()))
            }
        )

    def _fit_policy_batch(self, b: SampleBatch) -> SampleBatch:
        """Fix each policy's batch at ONE size across iterations: per-policy
        agent-step counts are ragged (episodes finish at different times),
        and PPOLearner.update re-jits for every new size. Short batches pad
        cyclically for SHAPE only — padded rows carry LOSS_MASK=0, so the
        mask-aware PPO loss gives them zero gradient weight (no silent
        training on duplicated data); overflow is dropped."""
        from .sample_batch import LOSS_MASK

        cfg = self.algo_config
        mb = cfg.minibatch_size
        n_pol = max(1, len(cfg.policies))
        target = max(mb, (cfg.train_batch_size // n_pol) // mb * mb)
        n = len(b)
        if n == target:
            return b
        if n > target:
            return b.slice(0, target)
        idx = np.arange(target) % n
        out = SampleBatch({k: v[idx] for k, v in b.items()})
        out[LOSS_MASK] = (np.arange(target) < n).astype(np.float32)
        return out

    def training_step(self) -> Dict[str, Any]:
        collected: List[MultiAgentBatch] = []
        steps = 0
        while steps < self.algo_config.train_batch_size:
            b = self.workers.sample()
            collected.append(b)
            steps += b.env_steps()
        batch = concat_multi_agent(collected)
        self._timesteps_total += batch.env_steps()
        fitted = MultiAgentBatch(
            {
                pid: self._fit_policy_batch(pb)
                for pid, pb in batch.policy_batches.items()
                if len(pb)
            },
            batch.env_steps(),
        )
        metrics: Dict[str, Any] = self.learner_group.update(fitted)
        self.workers.set_weights(self.learner_group.get_weights())
        metrics["num_env_steps_sampled_this_iter"] = batch.env_steps()
        metrics["agent_steps_this_iter"] = batch.agent_steps()
        return metrics
