"""RolloutWorker: a CPU actor stepping vectorized envs with the current policy.

Reference parity: rllib/evaluation/rollout_worker.py:166 (RolloutWorker.sample
collecting SampleBatches from env loops) with the env vectorization of
rllib/env/vector_env.py. Persistent env state across sample() calls
(truncate-style rollout fragments), episode-return tracking for metrics, and
GAE postprocessing done worker-side (rllib postprocessing.py) so the learner
receives ready-to-train columns.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .policy import Policy
from .sample_batch import (
    ACTIONS,
    ADVANTAGES,
    DONES,
    LOGP,
    OBS,
    REWARDS,
    TARGETS,
    VALUES,
    SampleBatch,
    compute_gae,
)


def _make_env(env_spec: Union[str, Callable[[], Any]]):
    if callable(env_spec):
        return env_spec()
    import gymnasium

    return gymnasium.make(env_spec)


class EnvLoopWorker:
    """Shared env-fleet plumbing for every sampling actor (PPO/IMPALA's
    RolloutWorker, DQN's epsilon-greedy worker, SAC's continuous worker):
    env construction, per-env return/length tracking, reset-on-done, and
    drained episode metrics. Keeping this in ONE place is what keeps
    episodes_this_iter semantics identical across algorithms."""

    def __init__(self, env_spec: Union[str, Callable[[], Any]], num_envs: int, seed: int):
        self.envs = [_make_env(env_spec) for _ in range(num_envs)]
        self.num_envs = num_envs
        self.obs_dim = int(np.prod(self.envs[0].observation_space.shape))
        self._obs = np.stack(
            [env.reset(seed=seed + i)[0] for i, env in enumerate(self.envs)]
        ).astype(np.float32).reshape(num_envs, self.obs_dim)
        self._episode_returns = np.zeros(num_envs, np.float32)
        self._episode_lens = np.zeros(num_envs, np.int64)
        self._completed_returns: List[float] = []
        self._completed_lens: List[int] = []
        self._episodes_since_drain = 0

    def ready(self) -> bool:
        return True

    def _step_and_track(self, e: int, action):
        """Step env e, track episode stats, reset on episode end.
        Returns (reward, terminated, truncated, final_obs) where final_obs
        is the PRE-reset next observation (what off-policy buffers store
        and truncation bootstrapping evaluates); self._obs[e] is advanced
        to the post-reset observation."""
        nobs, rew, terminated, truncated, _ = self.envs[e].step(action)
        final_obs = np.asarray(nobs, np.float32).reshape(self.obs_dim)
        self._episode_returns[e] += rew
        self._episode_lens[e] += 1
        obs_next = final_obs
        if terminated or truncated:
            self._completed_returns.append(float(self._episode_returns[e]))
            self._completed_lens.append(int(self._episode_lens[e]))
            self._episodes_since_drain += 1
            self._episode_returns[e] = 0.0
            self._episode_lens[e] = 0
            robs, _ = self.envs[e].reset()
            obs_next = np.asarray(robs, np.float32).reshape(self.obs_dim)
        self._obs[e] = obs_next
        return rew, terminated, truncated, final_obs

    def episode_metrics(self, window: int = 100) -> Dict[str, Any]:
        """Drain completed-episode stats (rllib metrics.py collect_episodes)."""
        returns = self._completed_returns[-window:]
        lens = self._completed_lens[-window:]
        out = {
            "episodes_this_iter": self._episodes_since_drain,
            "episode_reward_mean": float(np.mean(returns)) if returns else float("nan"),
            "episode_reward_max": float(np.max(returns)) if returns else float("nan"),
            "episode_reward_min": float(np.min(returns)) if returns else float("nan"),
            "episode_len_mean": float(np.mean(lens)) if lens else float("nan"),
        }
        self._completed_returns = self._completed_returns[-window:]
        self._completed_lens = self._completed_lens[-window:]
        self._episodes_since_drain = 0
        return out

    def stop(self) -> None:
        for env in self.envs:
            env.close()


class RolloutWorker(EnvLoopWorker):
    """One sampling actor; also usable inline (local mode, num_workers=0)."""

    def __init__(
        self,
        env_spec: Union[str, Callable[[], Any]],
        num_envs: int = 1,
        rollout_fragment_length: int = 200,
        gamma: float = 0.99,
        lam: float = 0.95,
        seed: int = 0,
        policy_hidden=(64, 64),
    ):
        super().__init__(env_spec, num_envs, seed)
        self.T = rollout_fragment_length
        self.gamma = gamma
        self.lam = lam
        self.num_actions = int(self.envs[0].action_space.n)
        self.policy = Policy(self.obs_dim, self.num_actions, policy_hidden, seed=seed)

    # -- weight sync (rollout_worker.py get/set_weights) --

    def get_weights(self) -> Dict[str, Any]:
        return self.policy.get_weights()

    def set_weights(self, weights: Dict[str, Any]) -> None:
        self.policy.set_weights(weights)

    # -- sampling --

    def sample(self) -> SampleBatch:
        """Collect T steps from each of E envs; returns a flat [T*E] batch
        with GAE advantages/targets already attached."""
        T, E = self.T, self.num_envs
        obs_buf = np.empty((T, E, self.obs_dim), np.float32)
        act_buf = np.empty((T, E), np.int64)
        rew_buf = np.empty((T, E), np.float32)
        done_buf = np.empty((T, E), np.float32)
        val_buf = np.empty((T, E), np.float32)
        logp_buf = np.empty((T, E), np.float32)

        # (t, e, final_obs) for time-limit truncations: their value is folded
        # into the reward below so GAE doesn't chain across the reset.
        truncations: List[tuple] = []

        for t in range(T):
            actions, logp, values = self.policy.compute_actions(self._obs)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            val_buf[t] = values
            logp_buf[t] = logp
            for e in range(self.num_envs):
                rew, terminated, truncated, final = self._step_and_track(e, int(actions[e]))
                rew_buf[t, e] = rew
                done_buf[t, e] = float(terminated or truncated)
                if truncated and not terminated:
                    truncations.append((t, e, final))

        if truncations:
            # bootstrap through time-limit truncation: fold gamma * V(s_final)
            # into the reward at the truncated step, then treat it as terminal
            final_obs = np.stack([o for _, _, o in truncations])
            final_vals = self.policy.compute_values(final_obs)
            for (t, e, _), v in zip(truncations, final_vals):
                rew_buf[t, e] += self.gamma * v

        bootstrap = self.policy.compute_values(self._obs) * (1.0 - done_buf[-1])
        gae = compute_gae(rew_buf, val_buf, done_buf, bootstrap, self.gamma, self.lam)
        flat = lambda a: a.reshape((T * E,) + a.shape[2:])
        return SampleBatch(
            {
                OBS: flat(obs_buf),
                ACTIONS: flat(act_buf),
                REWARDS: flat(rew_buf),
                DONES: flat(done_buf),
                VALUES: flat(val_buf),
                LOGP: flat(logp_buf),
                ADVANTAGES: flat(gae[ADVANTAGES]),
                TARGETS: flat(gae[TARGETS]),
            }
        )

    def sample_time_major(self) -> SampleBatch:
        """Collect T steps from each env, keeping the [T, E] time structure
        and the behavior-policy logp — the input v-trace needs (IMPALA;
        reference: rllib impala sample batches keep time_major=True).

        Columns: obs [T,E,D], actions/rewards/dones/logp [T,E], plus
        'bootstrap_value' [E] = V(s_T) for the truncated tail.
        """
        T, E = self.T, self.num_envs
        obs_buf = np.empty((T, E, self.obs_dim), np.float32)
        act_buf = np.empty((T, E), np.int64)
        rew_buf = np.empty((T, E), np.float32)
        done_buf = np.empty((T, E), np.float32)
        logp_buf = np.empty((T, E), np.float32)
        truncations: List[tuple] = []

        for t in range(T):
            actions, logp, _values = self.policy.compute_actions(self._obs)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            logp_buf[t] = logp
            for e in range(self.num_envs):
                rew, terminated, truncated, final = self._step_and_track(e, int(actions[e]))
                rew_buf[t, e] = rew
                done_buf[t, e] = float(terminated or truncated)
                if truncated and not terminated:
                    truncations.append((t, e, final))

        if truncations:
            final_obs = np.stack([o for _, _, o in truncations])
            final_vals = self.policy.compute_values(final_obs)
            for (t, e, _), v in zip(truncations, final_vals):
                rew_buf[t, e] += self.gamma * v

        bootstrap = self.policy.compute_values(self._obs) * (1.0 - done_buf[-1])
        return SampleBatch(
            {
                OBS: obs_buf,
                ACTIONS: act_buf,
                REWARDS: rew_buf,
                DONES: done_buf,
                LOGP: logp_buf,
                "bootstrap_value": bootstrap,
            }
        )
