"""Policy/value networks as pure JAX functions.

Reference parity: rllib/models/catalog.py:204 (ModelCatalog) and
rllib/core/models/catalog.py:28 build framework-specific torch/tf modules;
here the catalog is a pair of pure functions (init, apply) over a params
pytree, so the same network runs jitted on a CPU rollout actor and pjit'ed
on the learner mesh without wrappers.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _dense_init(rng, fan_in: int, fan_out: int, scale: float) -> Dict[str, jnp.ndarray]:
    # orthogonal init, the PPO-standard choice
    w = jax.nn.initializers.orthogonal(scale)(rng, (fan_in, fan_out), jnp.float32)
    return {"w": w, "b": jnp.zeros((fan_out,), jnp.float32)}


def init_ac_params(
    rng: jax.Array,
    obs_dim: int,
    num_actions: int,
    hidden: Sequence[int] = (64, 64),
) -> Dict[str, Any]:
    """Separate actor and critic MLP towers (rllib's default fcnet)."""
    params: Dict[str, Any] = {"pi": [], "vf": []}
    for tower, out_dim, out_scale in (("pi", num_actions, 0.01), ("vf", 1, 1.0)):
        dims = [obs_dim, *hidden]
        layers = []
        for i in range(len(dims) - 1):
            rng, sub = jax.random.split(rng)
            layers.append(_dense_init(sub, dims[i], dims[i + 1], np.sqrt(2)))
        rng, sub = jax.random.split(rng)
        layers.append(_dense_init(sub, dims[-1], out_dim, out_scale))
        params[tower] = layers
    return params


def _mlp(layers, x: jnp.ndarray) -> jnp.ndarray:
    for layer in layers[:-1]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return x @ last["w"] + last["b"]


def ac_apply(params: Dict[str, Any], obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (action_logits [B, A], value [B])."""
    logits = _mlp(params["pi"], obs)
    value = _mlp(params["vf"], obs)[..., 0]
    return logits, value


def _tower_init(rng, dims: Sequence[int], out_scale: float) -> list:
    layers = []
    for i in range(len(dims) - 2):
        rng, sub = jax.random.split(rng)
        layers.append(_dense_init(sub, dims[i], dims[i + 1], np.sqrt(2)))
    rng, sub = jax.random.split(rng)
    layers.append(_dense_init(sub, dims[-2], dims[-1], out_scale))
    return layers


def init_q_params(
    rng: jax.Array, obs_dim: int, num_actions: int, hidden: Sequence[int] = (64, 64)
) -> Dict[str, Any]:
    """Discrete Q-network (DQN; reference: rllib dqn_torch_model)."""
    return {"q": _tower_init(rng, [obs_dim, *hidden, num_actions], 1.0)}


def q_apply(params: Dict[str, Any], obs: jnp.ndarray) -> jnp.ndarray:
    """Returns Q-values [B, A]."""
    return _mlp(params["q"], obs)


def init_sac_params(
    rng: jax.Array, obs_dim: int, act_dim: int, hidden: Sequence[int] = (256, 256)
) -> Dict[str, Any]:
    """Squashed-Gaussian actor + twin Q critics (SAC; reference:
    rllib/algorithms/sac/sac_torch_model.py)."""
    r1, r2, r3 = jax.random.split(rng, 3)
    return {
        "pi": _tower_init(r1, [obs_dim, *hidden, 2 * act_dim], 0.01),
        "q1": _tower_init(r2, [obs_dim + act_dim, *hidden, 1], 1.0),
        "q2": _tower_init(r3, [obs_dim + act_dim, *hidden, 1], 1.0),
    }


LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def sac_pi_apply(params: Dict[str, Any], obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (mean [B, A], log_std [B, A]) of the pre-squash Gaussian."""
    out = _mlp(params["pi"], obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)


def sac_q_apply(params: Dict[str, Any], obs: jnp.ndarray, act: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (q1 [B], q2 [B]) for squashed actions in [-1, 1]."""
    x = jnp.concatenate([obs, act], axis=-1)
    return _mlp(params["q1"], x)[..., 0], _mlp(params["q2"], x)[..., 0]


def sample_squashed_gaussian(rng, mean, log_std):
    """Reparameterized tanh-squashed sample; returns (action, logp)."""
    std = jnp.exp(log_std)
    eps = jax.random.normal(rng, mean.shape)
    pre = mean + std * eps
    act = jnp.tanh(pre)
    # log-prob with tanh change of variables (SAC appendix C)
    logp = jnp.sum(
        -0.5 * (eps**2 + 2 * log_std + jnp.log(2 * jnp.pi))
        - jnp.log(1.0 - act**2 + 1e-6),
        axis=-1,
    )
    return act, logp
