"""Policy/value networks as pure JAX functions.

Reference parity: rllib/models/catalog.py:204 (ModelCatalog) and
rllib/core/models/catalog.py:28 build framework-specific torch/tf modules;
here the catalog is a pair of pure functions (init, apply) over a params
pytree, so the same network runs jitted on a CPU rollout actor and pjit'ed
on the learner mesh without wrappers.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _dense_init(rng, fan_in: int, fan_out: int, scale: float) -> Dict[str, jnp.ndarray]:
    # orthogonal init, the PPO-standard choice
    w = jax.nn.initializers.orthogonal(scale)(rng, (fan_in, fan_out), jnp.float32)
    return {"w": w, "b": jnp.zeros((fan_out,), jnp.float32)}


def init_ac_params(
    rng: jax.Array,
    obs_dim: int,
    num_actions: int,
    hidden: Sequence[int] = (64, 64),
) -> Dict[str, Any]:
    """Separate actor and critic MLP towers (rllib's default fcnet)."""
    params: Dict[str, Any] = {"pi": [], "vf": []}
    for tower, out_dim, out_scale in (("pi", num_actions, 0.01), ("vf", 1, 1.0)):
        dims = [obs_dim, *hidden]
        layers = []
        for i in range(len(dims) - 1):
            rng, sub = jax.random.split(rng)
            layers.append(_dense_init(sub, dims[i], dims[i + 1], np.sqrt(2)))
        rng, sub = jax.random.split(rng)
        layers.append(_dense_init(sub, dims[-1], out_dim, out_scale))
        params[tower] = layers
    return params


def _mlp(layers, x: jnp.ndarray) -> jnp.ndarray:
    for layer in layers[:-1]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return x @ last["w"] + last["b"]


def ac_apply(params: Dict[str, Any], obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (action_logits [B, A], value [B])."""
    logits = _mlp(params["pi"], obs)
    value = _mlp(params["vf"], obs)[..., 0]
    return logits, value
